#
# ModelServer: one fitted model behind a dynamic micro-batcher and a
# dedicated dispatch worker thread.
#
# The worker pops coalesced batches (serving/batcher.py), zero-pads each to
# its power-of-two row bucket (serving/entry.py bucket_rows), and runs the
# model's ServingEntry.call — upload, AOT-cached executable, host fetch —
# then scatters the output columns back to the requests' futures.  Running
# dispatch on its own thread is what overlaps the host->device->host
# pipeline with queue fill: while a batch is on device, the next one is
# coalescing.
#
# Warmup at construction makes steady state compile-free: every serving
# bucket is AOT-submitted through ops/precompile (entry.warm) AND dispatched
# once end to end with a synthetic batch, so the first real request lands on
# executables that already exist.  The engine then watches the precompile
# compile/fallback counters; any post-warm compile is recorded in
# serving.<name>.steady_compiles and assert_steady_state() turns it into a
# hard failure (the CI serving gate's zero-new-compiles contract).
#
# Observability rides profiling: process-wide counters under
# serving.<name>.* (requests/rows/batches/coalesced_batches/rejected/
# timeouts/errors/pad_rows/flush_*), per-request wall-clock latencies under
# serve.<name>.latency (profiling.percentiles gives p50/p95/p99), and
# per-batch dispatch times under serve.<name>.dispatch.
#

from __future__ import annotations

import contextlib
import logging
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import profiling, sanitize, watch
from ..parallel import faults
from .batcher import (  # noqa: F401
    MicroBatcher,
    RequestTimeout,
    ServerOverloaded,
    resolve_future,
)
from .entry import ServingEntry, bucket_rows, entry_for, serve_buckets

logger = logging.getLogger("spark_rapids_ml_tpu.serving")

# -- lifecycle states (srml-watch health plane + srml-shield recovery) --------
# WARMING    constructing: buckets compiling, worker not yet started
# READY      serving; SLO burn within budget
# DEGRADED   serving, but the SLO burn fraction over the latency window
#            exceeds SRML_SERVE_SLO_BURN (alert, don't page)
# DRAINING   drain()/shutdown() started; new submits rejected
# UNHEALTHY  the dispatch worker is wedged or dead and the supervisor is
#            out of restart budget: submits fail fast with ServerUnhealthy
#            (fail over to another replica — this server will not recover
#            by itself)
# RECOVERING the supervisor is restarting the worker after a death or a
#            watchdog-confirmed wedge: queued and in-flight requests were
#            failed with the typed retryable ServerRecovering; submits
#            fail fast with the same until the restart completes
WARMING = "WARMING"
READY = "READY"
DEGRADED = "DEGRADED"
DRAINING = "DRAINING"
UNHEALTHY = "UNHEALTHY"
RECOVERING = "RECOVERING"

# numeric codes for the gauge surface (render_prometheus srml_health family).
# Codes are STABLE identifiers (dashboards key on them), so RECOVERING takes
# the next free code; severity ORDER for worst-state rollups is SEVERITY.
STATE_CODES = {
    WARMING: 0, READY: 1, DEGRADED: 2, DRAINING: 3, UNHEALTHY: 4,
    RECOVERING: 5,
}
# least- to most-severe, for ModelRegistry.health()'s worst-state rollup
# (RECOVERING outranks DRAINING — it is an active failure being repaired —
# but UNHEALTHY stays worst: it means the supervisor gave up)
SEVERITY = (WARMING, READY, DEGRADED, DRAINING, RECOVERING, UNHEALTHY)

SLO_MS_ENV = "SRML_SERVE_SLO_MS"
SLO_BURN_ENV = "SRML_SERVE_SLO_BURN"
_DEFAULT_SLO_BURN = 0.1

# -- continuous batching (srml-router) ----------------------------------------
# SRML_SERVE_INFLIGHT_DEPTH > 1 splits the request path into a two-stage
# pipeline per server: an ASSEMBLY thread pops coalesced batches and does
# the host-side work (deadline bookkeeping, zero-pad to the pow2 bucket)
# while the DISPATCH worker — still the only thread that touches jax for
# this server — runs the previous batch on device.  Depth bounds the
# assembled-but-undispatched backlog (depth-1 slots), exactly PR 2's
# double-buffering applied to serving: admit and assemble batch k+1 while
# batch k executes.  Depth 1 (the default) is the original single-thread
# path, bit-for-bit.
INFLIGHT_DEPTH_ENV = "SRML_SERVE_INFLIGHT_DEPTH"
_DEFAULT_INFLIGHT_DEPTH = 1

# -- srml-shield recovery policy (docs/robustness.md) -------------------------
# A worker death (exception escaping the dispatch loop) or a watchdog-
# confirmed wedge triggers a bounded-restart-with-backoff: up to
# SRML_SERVE_MAX_RESTARTS supervised restarts per server lifetime, each
# preceded by SRML_SERVE_RESTART_BACKOFF_S * 2^(n-1) seconds of backoff and
# a re-warm of every bucket from the RETAINED AOT executable cache (zero
# new steady-state compiles — gated).  Budget exhausted => UNHEALTHY, for
# good: restart storms hide real breakage.
MAX_RESTARTS_ENV = "SRML_SERVE_MAX_RESTARTS"
RESTART_BACKOFF_ENV = "SRML_SERVE_RESTART_BACKOFF_S"
_DEFAULT_MAX_RESTARTS = 3
_DEFAULT_RESTART_BACKOFF_S = 0.05


def _max_restarts() -> int:
    from ..utils import env_float

    return int(env_float(MAX_RESTARTS_ENV, _DEFAULT_MAX_RESTARTS))


def _restart_backoff_s() -> float:
    from ..utils import env_float

    return env_float(RESTART_BACKOFF_ENV, _DEFAULT_RESTART_BACKOFF_S)


class ServerUnhealthy(RuntimeError):
    """Raised by submit() when the server's dispatch worker is wedged or
    the supervisor has exhausted its restart budget (UNHEALTHY state):
    callers should fail over to another replica rather than queue behind a
    worker that may never come back."""

    retryable = True  # on ANOTHER replica, not this server


class ServerRecovering(RuntimeError):
    """The typed RETRYABLE error of the self-healing path: set on queued
    and in-flight requests when the supervisor restarts the dispatch
    worker, and raised by submit() while the restart is underway.  The
    same request retried after the (sub-second) recovery window succeeds —
    unlike ServerUnhealthy, the server IS coming back."""

    retryable = True


def _slo_ms() -> float:
    """SRML_SERVE_SLO_MS: target request latency.  0 (default) disables SLO
    scoring — attainment reports 1.0 vacuously."""
    try:
        return float(os.environ.get(SLO_MS_ENV, "") or 0.0)
    except ValueError:
        return 0.0


def _slo_burn_budget() -> float:
    try:
        return float(os.environ.get(SLO_BURN_ENV, "") or _DEFAULT_SLO_BURN)
    except ValueError:
        return _DEFAULT_SLO_BURN


def _compile_watermark() -> int:
    """Total executable builds so far: AOT pool compiles plus jit fallbacks
    (a fallback means an AOT executable rejected its inputs and a FRESH jit
    compile happened — that is a new compile even though the pool counter
    does not move)."""
    return profiling.counter("precompile.compile") + profiling.counter(
        "precompile.fallback"
    )


# The compile watermark is PROCESS-wide, so a server dispatching while
# ANOTHER server warms up would see the warmer's compiles in its own
# window and fail assert_steady_state spuriously (the multi-model registry
# load-under-traffic case).  Every warmup registers here; a dispatch whose
# window overlapped any warmup skips compile attribution for that batch
# (counted as unattributed, never as a steady-state breach).
_warm_lock = sanitize.lockdep_lock("serve.engine.warm")
_warm_active = 0
_warm_epoch = 0  # bumped at every warmup start AND end


@contextlib.contextmanager
def _warm_scope():
    global _warm_active, _warm_epoch
    with _warm_lock:
        _warm_active += 1
        _warm_epoch += 1
    try:
        yield
    finally:
        with _warm_lock:
            _warm_active -= 1
            _warm_epoch += 1


def _warm_snapshot():
    with _warm_lock:
        return _warm_active, _warm_epoch


class ModelServer:
    """Online inference for one fitted model.

    Construction warms every serving bucket and starts the dispatch worker;
    `submit` enqueues a request and returns a Future, `predict` is the
    blocking convenience.  Use as a context manager or call shutdown()."""

    def __init__(
        self,
        name: str,
        model: Any,
        mesh: Any = None,
        *,
        max_batch: Optional[int] = None,
        max_wait_ms: Optional[float] = None,
        queue_depth: Optional[int] = None,
        default_timeout_ms: Optional[float] = None,
        inflight_depth: Optional[int] = None,
        warm: bool = True,
    ):
        self.name = str(name)
        self.model = model
        self.ns = f"serving.{self.name}"
        from ..utils import env_float

        self.inflight_depth = max(
            1,
            int(
                inflight_depth
                if inflight_depth is not None
                else env_float(INFLIGHT_DEPTH_ENV, _DEFAULT_INFLIGHT_DEPTH)
            ),
        )
        self._entry: ServingEntry = entry_for(model, mesh)
        self._batcher = MicroBatcher(
            n_cols=self._entry.n_cols,
            dtype=self._entry.dtype,
            counter_ns=self.ns,
            max_batch=max_batch,
            max_wait_ms=max_wait_ms,
            queue_depth=queue_depth,
            default_timeout_ms=default_timeout_ms,
        )
        self.buckets = serve_buckets(self._batcher.max_batch)
        self._wide = np.dtype(self._entry.dtype).itemsize == 8
        self._steady_compiles = 0
        self._warmed = False
        # health plane: lifecycle state + wedge detection.  _busy_since is
        # set by the worker around each device dispatch; a dispatch older
        # than SRML_WATCH_STALL_S flips the server UNHEALTHY (lazily, from
        # submit()/health() — no extra thread, no extra jax contention).
        # State/busy transitions happen under _health_lock: a client
        # flipping UNHEALTHY and the worker clearing busy must not
        # interleave, or a slow-but-successful dispatch near the threshold
        # could pin UNHEALTHY with no recovery path left.
        self._state = WARMING
        self._busy_since: Optional[float] = None
        self._drain_begun = False
        self._health_lock = sanitize.lockdep_lock("serve.engine.health")
        # srml-shield supervisor state: restart budget spent so far, the
        # CURRENT worker generation (a wedge recovery SUPERSEDES the stuck
        # worker by bumping the generation — when its blocked dispatch
        # finally returns it sees the stale generation and exits instead of
        # double-consuming the batcher), and the in-flight batch (so a
        # recovery can fail those requests from outside the worker thread)
        self._restarts = 0
        self._worker_gen = 0
        self._inflight: Optional[list] = None
        self._shutdown_begun = False
        self._recovery_epoch = 0  # guards stale recoveries (see _recover)
        # depth>1 continuous batching: the CURRENT generation's bounded
        # assembled-batch queue and assembly thread (None at depth 1).
        # Rebuilt per worker generation — a recovery must never leave a new
        # dispatcher popping a dead generation's pipe.
        self._pipe: Optional["queue.Queue"] = None
        self._asm: Optional[threading.Thread] = None
        self._burn_cache = (float("-inf"), 0.0)  # (stamped-at, burn)
        # one srml-scope trace session spans the server's lifetime (warmup
        # through shutdown) when SRML_TRACE_DIR is set: every queue/dispatch
        # span — recorded on the worker thread — lands in one Perfetto file.
        # The session holds the process-wide span-collection scope open, so
        # it MUST close on every exit path: a failed warmup closes it here
        # (re-raised), shutdown() closes it normally, and __del__ backstops
        # a server abandoned without shutdown — a leaked scope would starve
        # every later fit/search trace of its spans.
        self._trace_stack = contextlib.ExitStack()
        self.trace_path = self._trace_stack.enter_context(
            profiling.trace_session(f"serve-{self.name}")
        )
        try:
            if warm:
                self._warm_buckets()
            self._start_worker()
            self._state = READY
        except BaseException:
            self._trace_stack.close()
            raise

    def _make_worker_locked(self) -> Tuple[int, list]:
        """Build the next worker generation's thread set (dispatch worker,
        plus the assembly thread and a FRESH pipe at inflight_depth > 1)
        under the already-held health lock; returns (gen, threads to
        start).  The ONE construction rule shared by _start_worker and the
        recovery path, so a recovered server always gets the same pipeline
        shape it was built with."""
        self._worker_gen += 1
        gen = self._worker_gen
        pipe = None
        if self.inflight_depth > 1:
            pipe = queue.Queue(maxsize=self.inflight_depth - 1)
            self._pipe = pipe
        # BOTH pipeline threads are pinned to THEIR generation's pipe via
        # thread args — a late-scheduled stale-generation thread reading
        # self._pipe would pop the successor's work (double dispatch: two
        # jax threads for one server, the rendezvous hazard this module
        # exists to avoid)
        worker = threading.Thread(
            target=self._worker_main, args=(gen, pipe),
            name=f"srml-serve-{self.name}-g{gen}", daemon=True,
        )
        self._worker = worker
        threads = [worker]
        if pipe is not None:
            asm = threading.Thread(
                target=self._assembler_main, args=(gen, pipe),
                name=f"srml-serve-{self.name}-asm-g{gen}", daemon=True,
            )
            self._asm = asm
            threads.append(asm)
        return gen, threads

    def _start_worker(self) -> int:
        """Start a (new-generation) dispatch worker thread (and, at
        inflight_depth > 1, its assembly-stage sibling); returns the
        generation.  Called at construction and by the recovery path."""
        with self._health_lock:
            gen, threads = self._make_worker_locked()
        for t in threads:
            t.start()
        return gen

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self._trace_stack.close()  # idempotent
        except Exception:  # graftlint: disable=R9 (GC-time close; logging itself can fail at interpreter teardown)
            pass

    # -- warmup -------------------------------------------------------------
    def _warm_buckets(self) -> None:
        """Compile every serving-bucket geometry before traffic: AOT-submit
        through the precompile pool (parallel compiles), wait, then push one
        synthetic batch per bucket through the FULL dispatch path so any
        internal jit a route owns (e.g. the kNN merge) is compiled too.
        After this, the compile watermark is the steady-state baseline."""
        from ..ops.precompile import global_precompiler

        t0 = profiling.now()
        with _warm_scope(), profiling.span(
            f"serve.{self.name}.warm", buckets=len(self.buckets)
        ):
            keys = self._entry.warm(list(self.buckets))
            if keys:
                global_precompiler().wait(keys)
            with self._x64_scope():
                for b in self.buckets:
                    out = self._entry.call(*self._synth_args(b))
                    missing = [c for c in self._entry.out_cols if c not in out]
                    assert not missing, (
                        f"serving entry {self._entry.name!r} returned columns "
                        f"{sorted(out)} missing declared {missing}"
                    )
        profiling.record_duration(f"serve.{self.name}.warmup", profiling.now() - t0)
        profiling.incr_counter(f"{self.ns}.warmed_buckets", len(self.buckets))
        self._warmed = True

    def _x64_scope(self):
        import contextlib

        if self._wide:
            from ..compat import enable_x64

            # the worker thread is outside any fit's x64 scope; a float64
            # model's kernels must not silently canonicalize to f32 here
            return enable_x64(True)
        return contextlib.nullcontext()

    # -- client API ---------------------------------------------------------
    def submit(
        self,
        features: np.ndarray,
        timeout_ms: Optional[float] = None,
        *,
        lane: int = 0,
    ):
        """Enqueue one request ((D,) row or (n, D) block, n <= max_batch);
        returns a Future resolving to {output column: np array of n rows}.
        `lane` is the srml-lanes multiplex hook (which lane of a stacked
        parameter buffer these rows score against — MultiplexServer resolves
        it from a model_id; dedicated servers leave the default 0).
        Raises ServerOverloaded when the queue bound is hit, ServerRecovering
        (retryable: the supervisor is restarting the worker — retry HERE
        after the sub-second recovery window) while a restart is underway,
        and ServerUnhealthy when the worker is wedged with no restart
        budget left (fail over to ANOTHER replica)."""
        age = self._check_wedged()
        with self._health_lock:
            state = self._state
        if state == RECOVERING:
            # also the path the DETECTING submit takes when restart budget
            # remains: _maybe_restart_wedged flips to RECOVERING
            # synchronously, so the caller that noticed the wedge is told
            # "retry here" — not to abandon a replica that is seconds from
            # READY
            raise ServerRecovering(
                f"{self.ns}: restarting the dispatch worker after a "
                "failure; retry shortly"
            )
        if age is not None or state == UNHEALTHY:
            raise ServerUnhealthy(
                f"{self.ns}: dispatch worker wedged for {age or 0.0:.1f}s "
                f"(> SRML_WATCH_STALL_S={watch.stall_threshold_s():g}) "
                "with no restart budget left; fail over to another replica"
            )
        return self._batcher.submit(features, timeout_ms=timeout_ms, lane=lane)

    def _check_wedged(self) -> Optional[float]:
        """Seconds the in-flight dispatch has been wedged when the server
        is UNHEALTHY, else None.  The flip decision (and the age the error
        message quotes) is taken under the health lock; reporting side
        effects run outside it.  SRML_WATCH_STALL_S == 0 disables
        detection; the worker restores the lifecycle state if the dispatch
        eventually returns."""
        stall_s = watch.stall_threshold_s()
        flipped = False
        with self._health_lock:
            busy = self._busy_since
            now = profiling.now()
            if self._state == UNHEALTHY:
                return now - busy if busy is not None else 0.0
            if stall_s <= 0 or busy is None or now - busy <= stall_s:
                return None
            self._state = UNHEALTHY
            flipped = True
            age = now - busy
        if flipped:
            profiling.incr_counter(f"{self.ns}.unhealthy")
            logger.error(
                "%s: dispatch worker wedged for %.1fs — flipping UNHEALTHY "
                "and dumping flight recorder",
                self.ns, age,
            )
            watch.dump(f"serve-wedged-{self.name}")
            # srml-shield: the watchdog ACTS (dump + supervised restart)
            # instead of only flagging — wedge detection is lazy (driven
            # by submit()/state()/health() calls), so the restart launches
            # from whichever caller noticed
            self._maybe_restart_wedged()
        return age

    def predict(
        self, features: np.ndarray, timeout_ms: Optional[float] = None
    ) -> Dict[str, np.ndarray]:
        """Blocking convenience around submit(); the client-side wait is
        bounded by the request timeout plus one dispatch."""
        fut = self.submit(features, timeout_ms=timeout_ms)
        wait_s = None
        if timeout_ms is not None and timeout_ms > 0:
            wait_s = timeout_ms / 1000.0 + 60.0  # dispatch slack
        return fut.result(timeout=wait_s)

    # -- dispatch worker + srml-shield supervisor ----------------------------
    def _worker_main(self, gen: int, pipe: Optional["queue.Queue"]) -> None:
        """Worker thread top frame: a BaseException escaping the dispatch
        loop is a WORKER DEATH (not a per-batch model error — those are
        relayed to futures inside _dispatch) and triggers the supervised
        restart."""
        try:
            self._run(gen, pipe)
        except BaseException as exc:  # noqa: BLE001 - the supervisor catches
            self._on_worker_death(exc, gen)

    def _run(self, gen: int, pipe: Optional["queue.Queue"]) -> None:
        if pipe is not None:
            self._run_pipelined(gen, pipe)
            return
        while True:
            # the queue span covers the worker's wait for a coalesced batch:
            # in a trace, long serve.<n>.queue spans between short dispatch
            # spans read as spare capacity, back-to-back dispatches as
            # saturation
            with profiling.span(f"serve.{self.name}.queue"):
                item = self._batcher.take()
            if item is None:
                return
            batch, _reason = item
            profiling.record_duration(
                f"serve.{self.name}.inflight_depth", 1.0
            )
            if not self._process(gen, batch, None):
                return

    # -- depth>1 continuous batching (srml-router) ----------------------------
    def _assembler_main(self, gen: int, pipe: "queue.Queue") -> None:
        """Assembly stage of the depth>1 pipeline: pop coalesced batches
        and do the HOST-side work (pad to the pow2 bucket) while the
        dispatch worker has the previous batch on device.  This thread
        never touches jax — the one-jax-thread-per-server rule that keeps
        XLA:CPU's cross-program rendezvous out of the request path holds
        at every depth.  On supersede/stop it fails its in-hand batch and
        drains its own pipe (it is the only producer, so after this drain
        the pipe stays empty forever — no future is ever stranded)."""
        from .batcher import CANCELLED

        try:
            while True:
                with profiling.span(f"serve.{self.name}.queue"):
                    # hold=pipe.full is the iteration-level part of the
                    # pipeline: while a staged batch already waits for the
                    # device, the NEXT batch stays open to late arrivals
                    # (closing it early could not dispatch it sooner, only
                    # freeze its occupancy below the bucket) — the
                    # dispatcher kick()s the moment the slot frees
                    item = self._batcher.take(
                        cancelled=lambda: self._worker_gen != gen,
                        hold=pipe.full,
                    )
                if item is CANCELLED:
                    break  # superseded: queued work belongs to the successor
                if item is None:
                    # stopped and drained: wake the dispatcher for exit.
                    # The sentinel trails every real item (single producer),
                    # so the dispatcher resolves everything first.
                    self._pipe_put(pipe, None, gen)
                    return
                batch, _reason = item
                assembled = self._assemble(batch)
                if not self._pipe_put(pipe, (batch, assembled), gen):
                    break  # superseded while the pipe was full
                # pipeline depth achieved by THIS admission: batches staged
                # in the pipe plus the one on device — the
                # serve.<n>.inflight_depth series (percentiles > 1 mean
                # assembly genuinely overlapped device execution)
                busy = 1 if self._busy_since is not None else 0
                profiling.record_duration(
                    f"serve.{self.name}.inflight_depth",
                    float(pipe.qsize() + busy),
                )
        except BaseException as exc:  # noqa: BLE001 - assembly must not hang clients
            # host-side assembly death (bookkeeping bug or injected): fail
            # queued work the way a worker death does, through the same
            # supervisor — a silently dead assembler would strand every
            # queued request behind a live-looking server
            self._on_worker_death(exc, gen)
            return
        self._drain_pipe(pipe)

    def _pipe_put(self, pipe: "queue.Queue", item, gen: int) -> bool:
        """Bounded-wait put that notices supersede: a pipe stuck full
        because its dispatcher died must not park the assembler forever
        (graftlint R9 discipline, same as the batcher's 1 s re-check)."""
        while True:
            try:
                pipe.put(item, timeout=1.0)
                return True
            except queue.Full:
                if self._worker_gen != gen:
                    if item is not None:
                        for r in item[0]:
                            resolve_future(
                                r.future,
                                exc=ServerRecovering(
                                    f"{self.ns}: worker superseded with the "
                                    "pipeline full; retry"
                                ),
                            )
                    return False

    def _drain_pipe(self, pipe: Optional["queue.Queue"]) -> int:
        """Fail every assembled-but-undispatched batch in `pipe` with the
        typed retryable error; returns the number of requests failed."""
        n = 0
        while pipe is not None:
            try:
                item = pipe.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            for r in item[0]:
                if resolve_future(
                    r.future,
                    exc=ServerRecovering(
                        f"{self.ns}: pipeline flushed during recovery; retry"
                    ),
                ):
                    n += 1
        return n

    def _run_pipelined(self, gen: int, pipe: "queue.Queue") -> None:
        """Dispatch stage of the depth>1 pipeline: pop ASSEMBLED batches
        and run the device leg.  The pop wait is bounded so a superseded
        generation exits within one re-check interval even if its
        assembler died without a sentinel."""
        while True:
            try:
                with profiling.span(f"serve.{self.name}.pipe"):
                    item = pipe.get(timeout=1.0)
            except queue.Empty:
                if self._worker_gen != gen:
                    return
                continue
            if item is None:
                return
            # the staging slot just freed: wake an assembler holding a
            # deadline-expired batch open so it closes and stages now
            self._batcher.kick()
            batch, assembled = item
            if not self._process(gen, batch, assembled):
                return

    def _process(self, gen: int, batch, assembled) -> bool:
        """Shared per-batch guard around _dispatch (both depths): health
        bookkeeping, error relay, supersede detection.  Returns False when
        this worker generation was superseded and must exit."""
        with self._health_lock:
            self._busy_since = profiling.now()
            self._inflight = batch
        dying = True  # a BaseException escaping _dispatch = worker death
        try:
            self._dispatch(batch, assembled)
            dying = False
        except Exception as exc:  # noqa: BLE001 - worker must survive
            dying = False
            # _dispatch relays model errors to the batch's futures; this
            # guard is for bookkeeping bugs (e.g. a racing future state)
            # — one batch may be lost, the server must not wedge.
            # BaseExceptions (InjectedWorkerDeath, interpreter teardown)
            # deliberately ESCAPE to _worker_main: they are deaths, not
            # batch errors.
            logger.exception("%s: dispatch bookkeeping failed", self.ns)
            profiling.incr_counter(f"{self.ns}.errors")
            rec = watch.recorder()
            if rec is not None:
                rec.record_exception(exc, f"serve-{self.name}")
            for r in batch:
                resolve_future(
                    r.future,
                    exc=RuntimeError(f"{self.ns}: dispatch failed"),
                )
        finally:
            with self._health_lock:
                superseded = self._worker_gen != gen
                recovered = False
                if not superseded and not dying:
                    # on the DEATH path _inflight must survive this
                    # finally: _on_worker_death fails those futures
                    # with the typed retryable error
                    self._busy_since = None
                    self._inflight = None
                    recovered = self._state == UNHEALTHY
                    if recovered:
                        # the wedged dispatch came back after all (no
                        # restart budget was left, so no supersede):
                        # recover — UNHEALTHY describes the worker, not
                        # history (but a drain that began meanwhile
                        # stays a drain)
                        self._state = (
                            DRAINING if self._drain_begun else READY
                        )
            if recovered:
                profiling.incr_counter(f"{self.ns}.recovered")
                logger.warning(
                    "%s: wedged dispatch returned; %s",
                    self.ns, self._state,
                )
        if self._worker_gen != gen:
            # a wedge recovery superseded this worker while its dispatch
            # was blocked: a new generation owns the batcher now — exit
            # instead of double-consuming (the blocked batch's futures
            # were already failed with ServerRecovering; resolve_future
            # made this worker's late scatter a harmless no-op)
            logger.warning(
                "%s: superseded worker generation %d exiting after its "
                "blocked dispatch returned", self.ns, gen,
            )
            return False
        return True

    # -- the supervisor: bounded restart with backoff -------------------------
    def _on_worker_death(self, exc: BaseException, gen: int) -> None:
        """The dispatch worker died (exception escaped its loop).  Fail the
        in-flight batch with the typed retryable error, then run the
        bounded-restart policy."""
        profiling.incr_counter(f"{self.ns}.worker_deaths")
        logger.error("%s: dispatch worker died: %s: %s",
                     self.ns, type(exc).__name__, exc)
        rec = watch.recorder()
        if rec is not None:
            rec.record_exception(exc, f"serve-{self.name}")
        watch.dump(f"serve-died-{self.name}")
        with self._health_lock:
            if self._worker_gen != gen:
                return  # already superseded by a wedge recovery
            inflight, self._inflight = self._inflight, None
            self._busy_since = None
        for r in inflight or []:
            resolve_future(
                r.future,
                exc=ServerRecovering(
                    f"{self.ns}: dispatch worker died mid-batch; retry"
                ),
            )
        self._recover("worker-death")

    def _maybe_restart_wedged(self) -> None:
        """Wedge half of the supervisor: SUPERSEDE the stuck worker (bump
        the generation; its eventual return becomes a no-op exit), fail its
        in-flight batch, and restart — on a helper thread, because the
        caller is a client inside submit()/health()."""
        with self._health_lock:
            if self._state != UNHEALTHY or self._drain_begun:
                return
            if self._restarts >= _max_restarts():
                return  # budget spent: stay UNHEALTHY (legacy lazy-recover
                #         path still applies if the dispatch ever returns)
            # flip RECOVERING synchronously so the caller that DETECTED the
            # wedge (this very submit/state call) already reports the
            # retryable "restarting" verdict, not fail-over
            self._state = RECOVERING
            self._worker_gen += 1
            inflight, self._inflight = self._inflight, None
            self._busy_since = None
        threading.Thread(
            target=self._wedge_recovery, args=(inflight,),
            name=f"srml-serve-{self.name}-recover", daemon=True,
        ).start()

    def _wedge_recovery(self, inflight) -> None:
        for r in inflight or []:
            resolve_future(
                r.future,
                exc=ServerRecovering(
                    f"{self.ns}: dispatch wedged past the stall threshold; "
                    "worker superseded — retry"
                ),
            )
        self._recover("wedged-dispatch")

    def _recover(self, reason: str) -> None:
        """Bounded-restart-with-backoff: shed everything queued with the
        typed retryable error (never a hang), back off, re-warm every
        bucket from the RETAINED AOT executable cache (zero new compiles —
        a recovery that would have to compile is a recovery into a cold
        replica, which defeats the SLO), then start a new worker
        generation.  Budget exhausted => UNHEALTHY, permanently.  A
        recovery racing drain()/shutdown() sheds (so quiescence resolves)
        but never restarts — a shut-down server must not resurrect a
        worker or report READY."""
        t0 = profiling.now()
        with self._health_lock:
            aborting = self._drain_begun or self._shutdown_begun
            if aborting:
                budget_spent = False
                attempt = self._restarts
            elif self._restarts >= _max_restarts():
                self._state = UNHEALTHY
                budget_spent = True
                attempt = self._restarts
            else:
                self._restarts += 1
                attempt = self._restarts
                self._state = RECOVERING
                budget_spent = False
            self._recovery_epoch += 1
            my_epoch = self._recovery_epoch
        shed = self._batcher.fail_pending(
            ServerRecovering(
                f"{self.ns}: recovering from {reason}; retry shortly"
            )
        )
        # depth>1: assembled-but-undispatched batches in the dead
        # generation's pipe are admitted requests too — shed them the same
        # way (the old assembler's own exit-drain backstops any later put)
        shed += self._drain_pipe(self._pipe)
        if shed:
            profiling.incr_counter(f"{self.ns}.shed_recovery", shed)
        if aborting:
            logger.warning(
                "%s: %s during drain/shutdown — shed %d request(s), no "
                "restart", self.ns, reason, shed,
            )
            return
        if budget_spent:
            logger.error(
                "%s: %s after %d restart(s) — budget (%s=%d) exhausted; "
                "UNHEALTHY until replaced",
                self.ns, reason, attempt, MAX_RESTARTS_ENV, _max_restarts(),
            )
            return
        time.sleep(_restart_backoff_s() * (2 ** (attempt - 1)))
        try:
            self._rewarm()
        except BaseException:  # noqa: BLE001 - a broken model must not loop
            logger.exception(
                "%s: bucket re-warm failed during recovery — UNHEALTHY",
                self.ns,
            )
            with self._health_lock:
                self._state = UNHEALTHY
            return
        with self._health_lock:
            # a recovery superseded while it was re-warming (another wedge
            # escalation consumed the budget, or shutdown began) must not
            # resurrect a worker or clobber a terminal state.  The check,
            # the worker-generation reservation, AND the state transition
            # share ONE lock acquisition: a shutdown landing between them
            # would otherwise get its worker resurrected and its state
            # flipped READY after teardown.
            stale = (
                self._recovery_epoch != my_epoch
                or self._shutdown_begun
                or self._state == UNHEALTHY
            )
            if not stale:
                _gen, threads = self._make_worker_locked()
                self._state = DRAINING if self._drain_begun else READY
        if stale:
            logger.warning(
                "%s: recovery #%d superseded during re-warm; standing down",
                self.ns, attempt,
            )
            return
        for t in threads:
            t.start()
        dt = profiling.now() - t0
        profiling.incr_counter(f"{self.ns}.restarts")
        profiling.record_duration(f"serve.{self.name}.recovery", dt)
        logger.warning(
            "%s: recovered from %s via supervised restart #%d in %.1f ms "
            "(buckets re-warmed from the retained AOT cache)",
            self.ns, reason, attempt, dt * 1e3,
        )

    def _rewarm(self) -> None:
        """One synthetic batch per bucket through the FULL dispatch path on
        the recovery thread.  The AOT executable cache survives the worker,
        so this performs ZERO new compiles (gated) — it exists to verify
        the model can still dispatch, so a restart into a broken model
        burns its budget HERE, not on live traffic.  Wrapped in _warm_scope
        so any compile that somehow happens is never attributed to a
        concurrently-dispatching server's steady state.  busy_since is set
        for its duration so a model that HANGS in the re-warm is visible to
        the same wedge detector as a hung dispatch: _check_wedged flips the
        server out of RECOVERING (whose submit error says "retry here")
        into UNHEALTHY ("fail over"), escalating until the restart budget
        is gone instead of advertising a recovery that never lands."""
        with self._health_lock:
            self._busy_since = profiling.now()
        try:
            with _warm_scope(), self._x64_scope(), profiling.span(
                f"serve.{self.name}.rewarm", buckets=len(self.buckets)
            ):
                for b in self.buckets:
                    self._entry.call(*self._synth_args(b))
        finally:
            with self._health_lock:
                self._busy_since = None

    def _synth_args(self, b: int) -> tuple:
        """The synthetic warm/re-warm batch for one bucket, as the full
        entry.call argument tuple.  Subclasses whose entries take extra
        per-row arguments append them here (MultiplexServer adds the lane
        id vector), so warmup dispatches the exact call geometry traffic
        will."""
        return (np.zeros((b, self._entry.n_cols), dtype=self._entry.dtype),)

    def _assemble(self, batch) -> Tuple[np.ndarray, int, int]:
        """Host-side batch assembly: zero-pad the coalesced requests to
        their pow2 row bucket.  Runs on the dispatch worker at depth 1 and
        on the assembly thread at depth > 1 — the work the pipeline
        overlaps with device execution.  Subclasses may return extra
        per-row arrays after (padded, n_rows, b); _dispatch forwards them
        to entry.call (the srml-lanes lane-id vector rides here)."""
        n_rows = sum(r.n_rows for r in batch)
        b = bucket_rows(n_rows, self._batcher.max_batch)
        # empty + tail-only zero fill, NOT np.zeros + overwrite: the bucket
        # is written exactly once either way, but zeros() pre-fills the
        # whole buffer, doubling assembly memory traffic for a full bucket
        # — host bandwidth the depth>1 assembler shares with the device leg
        padded = np.empty((b, self._entry.n_cols), dtype=self._entry.dtype)
        off = 0
        for r in batch:
            padded[off : off + r.n_rows] = r.features
            off += r.n_rows
        if b > n_rows:
            padded[n_rows:] = 0
        profiling.incr_counter(f"{self.ns}.pad_rows", b - n_rows)
        return padded, n_rows, b

    def _dispatch(self, batch, assembled=None) -> None:
        # srml-shield: the serving injection site (tag = server name, so a
        # plan targets ONE server deterministically).  kill here raises
        # InjectedWorkerDeath — a BaseException that escapes the per-batch
        # Exception guard and lands in _worker_main as a worker death.
        faults.site("serving.dispatch", tag=self.name)
        assembled = assembled if assembled is not None else self._assemble(batch)
        padded, n_rows, b = assembled[0], assembled[1], assembled[2]
        extras = tuple(assembled[3:])  # e.g. the multiplex lane-id vector
        # compile accounting brackets THIS dispatch: the watermark counters
        # are process-wide, so a baseline taken at warmup end would blame
        # this server for another server's later load-time compiles (any
        # compile our own dispatch triggers finishes inside entry.call —
        # cached_call waits on the pool job before running).  A window that
        # overlapped any concurrent warmup (epoch moved / warm active) skips
        # attribution entirely — see _warm_scope.
        active0, epoch0 = _warm_snapshot()
        mark0 = _compile_watermark() if self._warmed else 0
        t0 = profiling.now()
        try:
            with self._x64_scope(), profiling.span(
                f"serve.{self.name}.dispatch",
                rows=n_rows, bucket=b, requests=len(batch),
            ):
                out = self._entry.call(padded, *extras)
        except BaseException as exc:  # noqa: BLE001 - relayed to every waiter
            profiling.incr_counter(f"{self.ns}.errors")
            rec = watch.recorder()
            if rec is not None:
                # ring-record the model error (cheap, no dump: per-batch
                # model errors are relayed to callers, not process fatal)
                rec.record_exception(exc, f"serve-{self.name}")
            for r in batch:
                resolve_future(r.future, exc=exc)
            return
        dt = profiling.now() - t0
        profiling.record_duration(f"serve.{self.name}.dispatch", dt)
        profiling.record_duration(f"serve.{self.name}.occupancy", float(len(batch)))
        if self._warmed:
            delta = _compile_watermark() - mark0
            if delta > 0:
                active1, epoch1 = _warm_snapshot()
                if active0 == 0 and active1 == 0 and epoch0 == epoch1:
                    profiling.incr_counter(f"{self.ns}.steady_compiles", delta)
                    self._steady_compiles += delta
                else:
                    profiling.incr_counter(
                        f"{self.ns}.unattributed_compiles", delta
                    )
        done_t = profiling.now()
        off = 0
        for r in batch:
            sl = slice(off, off + r.n_rows)
            off += r.n_rows
            result = {c: np.asarray(v[sl]) for c, v in out.items()}
            if resolve_future(r.future, result):
                profiling.record_duration(
                    f"serve.{self.name}.latency", done_t - r.enqueue_t
                )

    # -- lifecycle / observability ------------------------------------------
    def drain(self, timeout_s: float = 60.0) -> None:
        """Flush pending partial batches immediately and wait until every
        queued request has resolved (quiescence).  The server keeps running
        only in the sense that the worker stays alive for shutdown(); new
        submits are rejected once draining starts."""
        with self._health_lock:
            self._drain_begun = True
            if self._state != UNHEALTHY:
                self._state = DRAINING
        self._batcher.begin_drain()
        if not self._batcher.wait_quiescent(timeout_s=timeout_s):
            raise TimeoutError(
                f"{self.ns}: drain timed out with "
                f"{self._batcher.outstanding()} request(s) unresolved"
            )

    def shutdown(self, drain: bool = True, timeout_s: float = 60.0) -> None:
        with self._health_lock:
            # any in-flight recovery observes this and stands down instead
            # of resurrecting a worker on a server being torn down
            self._shutdown_begun = True
        try:
            if drain:
                try:
                    self.drain(timeout_s=timeout_s)
                finally:
                    self._batcher.stop()
            else:
                self._batcher.stop()
            self._worker.join(timeout=timeout_s)
            asm = self._asm
            if asm is not None:
                asm.join(timeout=timeout_s)
        finally:
            # close the lifetime trace session (writes the Perfetto file
            # when SRML_TRACE_DIR is set; no-op otherwise)
            self._trace_stack.close()

    def __enter__(self) -> "ModelServer":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    def assert_steady_state(self) -> None:
        """Zero-new-compiles contract: every post-warmup dispatch ran on an
        already-compiled executable.  Raises AssertionError otherwise —
        used by the CI serving gate and available to deployments that treat
        a steady-state compile as an SLO breach."""
        assert self._steady_compiles == 0, (
            f"{self.ns}: {self._steady_compiles} executable compile(s) "
            "after warmup — a serving bucket or kernel geometry was not "
            "covered by the warm set"
        )

    def state(self) -> str:
        """Current lifecycle state (wedge detection applied lazily)."""
        self._check_wedged()
        return self._state

    # -- router-facing surface (serving/scheduler.py reads these) ------------
    def outstanding(self) -> int:
        """Admitted requests without an outcome yet — the least-outstanding
        dispatch signal."""
        return self._batcher.outstanding()

    def queued_rows(self) -> int:
        return self._batcher.queued_rows()

    def queue_depth(self) -> int:
        return self._batcher.queue_depth

    # burn-verdict cache TTL: effective_state() sits on the ROUTER'S
    # dispatch hot path (scheduler.pick calls it per candidate per submit),
    # and the naive burn computation copies + scans the whole latency ring
    # (up to the 64k sample cap) under the global durations lock — per
    # request, that is throughput collapse exactly at the QPS where routing
    # matters.  Rotation decisions don't need sub-quarter-second burn
    # freshness, so one scan per TTL per replica amortizes it away.
    _BURN_CACHE_S = 0.25

    def _slo_burn(self) -> float:
        """Burn fraction over the latency window vs SRML_SERVE_SLO_MS
        (0.0 with no SLO configured or no samples), cached _BURN_CACHE_S."""
        slo_ms = _slo_ms()
        if slo_ms <= 0:
            return 0.0
        now = profiling.now()
        t, cached = self._burn_cache  # tuple read: GIL-atomic
        if now - t < self._BURN_CACHE_S:
            return cached
        samples = profiling.durations(f"serve.{self.name}.latency").get(
            f"serve.{self.name}.latency", []
        )
        burn = 0.0
        if samples:
            met = sum(1 for s in samples if s * 1000.0 <= slo_ms)
            burn = 1.0 - met / len(samples)
        self._burn_cache = (now, burn)
        return burn

    def slo_burn(self) -> float:
        """Public read of the cached SLO burn fraction — the autoscaler's
        scale-up signal (serving/autoscale.py).  Same windowed verdict the
        DEGRADED overlay and health() score against, amortized by the
        _BURN_CACHE_S cache so a policy loop polling every replica every
        tick never pays the latency-ring scan per call."""
        return self._slo_burn()

    def effective_state(self) -> str:
        """Lifecycle state with the SLO-burn DEGRADED overlay applied —
        the router's rotation signal.  state() alone never reports
        DEGRADED: burn is a derived, windowed verdict that health()
        computes; the router needs the same verdict without the rest of
        the health document."""
        state = self.state()
        if state == READY and self._slo_burn() > _slo_burn_budget():
            return DEGRADED
        return state

    def health(self) -> Dict[str, Any]:
        """SLO-scored health: lifecycle state, p99 vs SRML_SERVE_SLO_MS,
        and the burn fraction (share of window requests OVER the SLO) —
        Prometheus-style burn-rate health over the latency sample window.
        With no SLO configured attainment is vacuously 1.0; a READY server
        whose burn exceeds SRML_SERVE_SLO_BURN reports DEGRADED."""
        self._check_wedged()
        slo_ms = _slo_ms()
        samples = profiling.durations(f"serve.{self.name}.latency").get(
            f"serve.{self.name}.latency", []
        )
        if slo_ms > 0 and samples:
            met = sum(1 for s in samples if s * 1000.0 <= slo_ms)
            attainment = met / len(samples)
        else:
            attainment = 1.0
        burn = 1.0 - attainment
        state = self._state
        if state == READY and burn > _slo_burn_budget():
            state = DEGRADED
        lat = profiling.percentiles(f"serve.{self.name}.latency")
        busy = self._busy_since
        return {
            "name": self.name,
            "state": state,
            "state_code": STATE_CODES[state],
            "slo_ms": slo_ms,
            "attainment": round(attainment, 6),
            "burn": round(burn, 6),
            "burn_budget": _slo_burn_budget(),
            "window_count": len(samples),
            "p99_ms": (
                round(lat["p99"] * 1000.0, 3) if lat else None
            ),
            "queued_rows": self._batcher.queued_rows(),
            "queued_requests": self._batcher.queued_requests(),
            "outstanding": self._batcher.outstanding(),
            "busy_s": (
                round(profiling.now() - busy, 3) if busy is not None else 0.0
            ),
            "steady_compiles": self._steady_compiles,
            "restarts": self._restarts,
        }

    def stats(self) -> Dict[str, Any]:
        """One self-describing snapshot: queue gauges, batching counters,
        latency percentiles, and the compile watermark."""
        lat = profiling.percentiles(f"serve.{self.name}.latency")
        disp = profiling.percentiles(f"serve.{self.name}.dispatch")
        occ = profiling.percentiles(f"serve.{self.name}.occupancy")
        return {
            "name": self.name,
            "state": self.state(),
            "entry": self._entry.name,
            "out_cols": list(self._entry.out_cols),
            "buckets": list(self.buckets),
            "max_batch": self._batcher.max_batch,
            "max_wait_ms": self._batcher.max_wait_s * 1000.0,
            "queue_depth": self._batcher.queue_depth,
            "inflight_depth": self.inflight_depth,
            "queued_rows": self._batcher.queued_rows(),
            "queued_requests": self._batcher.queued_requests(),
            "counters": profiling.counters(self.ns + "."),
            "latency": lat,
            "dispatch": disp,
            "batch_occupancy": occ,
            "steady_compiles": self._steady_compiles,
            "restarts": self._restarts,
            **({"info": self._entry.info} if self._entry.info else {}),
        }
