#
# srml-serve: the online inference subsystem.
#
# The fit engines of PRs 1-4 built everything an online request path needs —
# an AOT executable cache keyed on pow2 shape buckets (ops/precompile),
# device-resident model state, and staged transform kernels — but nothing
# composed them: every transform() was a one-shot batch call.  This package
# is that composition (docs/serving.md):
#
#   batcher.py   dynamic micro-batching: bounded queue, coalesce-until-
#                deadline, fast ServerOverloaded rejection, per-request
#                deadlines
#   entry.py     the model <-> engine contract (ServingEntry) + the single
#                pow2 row-bucket rule shared by dispatch and warmup
#   engine.py    ModelServer: dedicated dispatch worker, bucket-warmed
#                executables (steady state = zero new compiles, asserted),
#                latency percentiles through profiling
#   registry.py  named servers over in-memory or core.load'ed models
#
from .batcher import MicroBatcher, RequestTimeout, ServerOverloaded
from .engine import (
    DEGRADED,
    DRAINING,
    READY,
    RECOVERING,
    SEVERITY,
    STATE_CODES,
    UNHEALTHY,
    WARMING,
    ModelServer,
    ServerRecovering,
    ServerUnhealthy,
)
from .entry import ServingEntry, bucket_rows, entry_for, kernel_entry, serve_buckets
from .registry import ModelRegistry, default_registry

__all__ = [
    "DEGRADED",
    "DRAINING",
    "MicroBatcher",
    "ModelRegistry",
    "ModelServer",
    "READY",
    "RECOVERING",
    "RequestTimeout",
    "SEVERITY",
    "STATE_CODES",
    "ServerOverloaded",
    "ServerRecovering",
    "ServerUnhealthy",
    "ServingEntry",
    "UNHEALTHY",
    "WARMING",
    "bucket_rows",
    "default_registry",
    "entry_for",
    "kernel_entry",
    "serve_buckets",
]
