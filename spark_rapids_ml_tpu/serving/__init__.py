#
# srml-serve: the online inference subsystem.
#
# The fit engines of PRs 1-4 built everything an online request path needs —
# an AOT executable cache keyed on pow2 shape buckets (ops/precompile),
# device-resident model state, and staged transform kernels — but nothing
# composed them: every transform() was a one-shot batch call.  This package
# is that composition (docs/serving.md):
#
#   batcher.py   dynamic micro-batching: bounded queue, coalesce-until-
#                deadline, fast ServerOverloaded rejection, per-request
#                deadlines
#   entry.py     the model <-> engine contract (ServingEntry) + the single
#                pow2 row-bucket rule shared by dispatch and warmup
#   engine.py    ModelServer: dedicated dispatch worker, bucket-warmed
#                executables (steady state = zero new compiles, asserted),
#                latency percentiles through profiling
#   registry.py  named servers over in-memory or core.load'ed models, plus
#                zero-downtime hot swap (swap(name, new_model))
#   scheduler.py admission/priority classes + least-outstanding dispatch
#                policy (pure functions over replica state)
#   router.py    srml-router: N replicas per model over disjoint mesh
#                slices, health-aware routing, load shedding, rolling swap
#   multiplex.py srml-lanes: K same-shape model variants stacked on a pow2
#                lane axis behind ONE kernel per micro-batch, with LRU
#                lane paging (host-RAM spill, zero-recompile page-in)
#   slicepool.py srml-elastic capacity ledger: fixed-size, group-aware,
#                DISJOINT device slices leased to replicas across ALL
#                served models; typed CapacityExhausted over silent
#                oversubscription
#   autoscale.py srml-elastic policy loop: signal-driven scale-up/down
#                with hysteresis + cooldowns, and preemption repair
#                (terminal replica -> re-slice + re-warm) through
#                Router.scale_to / Router.replace_replica
#
from .autoscale import Autoscaler, AutoscalePolicy
from .batcher import (
    MicroBatcher,
    RequestTimeout,
    ServerDraining,
    ServerOverloaded,
)
from .engine import (
    DEGRADED,
    DRAINING,
    READY,
    RECOVERING,
    SEVERITY,
    STATE_CODES,
    UNHEALTHY,
    WARMING,
    ModelServer,
    ServerRecovering,
    ServerUnhealthy,
)
from .entry import ServingEntry, bucket_rows, entry_for, kernel_entry, serve_buckets
from .multiplex import LaneEntry, MultiplexServer, lane_entry_for, lane_signature
from .registry import ModelRegistry, default_registry
from .router import Router
from .scheduler import (
    DEFAULT_CLASS,
    PRIORITY_CLASSES,
    NoReplicaAvailable,
    RequestShed,
)
from .slicepool import CapacityExhausted, SliceLease, SlicePool

__all__ = [
    "Autoscaler",
    "AutoscalePolicy",
    "CapacityExhausted",
    "SliceLease",
    "SlicePool",
    "DEFAULT_CLASS",
    "DEGRADED",
    "DRAINING",
    "LaneEntry",
    "MicroBatcher",
    "ModelRegistry",
    "ModelServer",
    "MultiplexServer",
    "NoReplicaAvailable",
    "PRIORITY_CLASSES",
    "READY",
    "RECOVERING",
    "RequestShed",
    "RequestTimeout",
    "Router",
    "SEVERITY",
    "STATE_CODES",
    "ServerDraining",
    "ServerOverloaded",
    "ServerRecovering",
    "ServerUnhealthy",
    "ServingEntry",
    "UNHEALTHY",
    "WARMING",
    "bucket_rows",
    "default_registry",
    "entry_for",
    "kernel_entry",
    "lane_entry_for",
    "lane_signature",
    "serve_buckets",
]
