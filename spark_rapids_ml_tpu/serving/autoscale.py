#
# srml-elastic policy loop: signal-driven autoscaling over the router.
#
# ROADMAP open item 1 ("the router learns to scale itself"): PR 11 gave N
# replicas behind health-aware dispatch, PR 15 zero-downtime refresh, PR 19
# group-major slice carving — but the replica count stayed a constructor
# constant.  On preemptible-TPU economics that is wrong twice over: traffic
# is diurnal (capacity must follow it) and replica LOSS is the common case
# (preemption is how the discount is paid for), not a degraded mode.
#
# The Autoscaler is a deliberately small control loop with three rules:
#
#   SIGNALS ONLY FROM THE EXPORTED SURFACE.  Every input is something
#   operators already see on a dashboard: per-replica SLO burn over the
#   serve.<replica>.latency window (engine.slo_burn — the same verdict the
#   DEGRADED overlay scores), the admission fill fraction
#   (scheduler.aggregate_fill — what shedding keys on), occupancy
#   (scheduler.aggregate_occupancy — busyness including in-flight rows),
#   and router.<model>.shed* counter deltas.  No private channels: if the
#   autoscaler can see pressure, so can the on-call.
#
#   HYSTERESIS, ASYMMETRIC ON PURPOSE.  Scale UP fast — any shed in the
#   window, or windowed fill/burn over the up-thresholds, adds one replica
#   subject to a short cooldown (sheds mean admitted traffic is already
#   being refused; waiting is the expensive branch).  Scale DOWN slow —
#   only after fill, burn, sheds AND occupancy stay under the idle
#   thresholds for the whole (longer) down-window, behind a long cooldown.
#   Flapping burns the warmup bill twice and the p99 both times.
#
#   PREEMPTION IS REPAIR, NOT SCALING.  A replica that goes terminal
#   (UNHEALTHY with its restart budget spent — a killed worker under
#   SRML_FAULTS, a preempted slice, a lease expiry on the SRML_CP=tcp
#   plane) is replaced THROUGH Router.replace_replica on the next tick:
#   release the slice, lease a fresh one, re-warm from the retained AOT
#   executable cache (zero new compiles), atomic slot cut-over.  The
#   target count never changes; the decision journal records a "repair".
#
# Every decision — scale_up / scale_down / repair / hold — bumps an
# autoscale.<model>.* counter and (for actions) lands in a bounded
# decision journal with its reason string, so "why did we scale at 3am"
# is a journal read, not a log dig.  docs/serving.md §srml-elastic has
# the policy table and knob reference.
#

from __future__ import annotations

import logging
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from .. import profiling, sanitize
from . import scheduler
from .engine import UNHEALTHY
from .slicepool import CapacityExhausted

logger = logging.getLogger("spark_rapids_ml_tpu.serving")

# knob defaults; every one overridable via SRML_AUTOSCALE_* (docs/serving.md)
INTERVAL_ENV = "SRML_AUTOSCALE_INTERVAL_S"
_DEFAULT_INTERVAL_S = 0.25
MIN_ENV = "SRML_AUTOSCALE_MIN"
_DEFAULT_MIN = 1
MAX_ENV = "SRML_AUTOSCALE_MAX"
_DEFAULT_MAX = 4
WINDOW_ENV = "SRML_AUTOSCALE_WINDOW_S"
_DEFAULT_WINDOW_S = 2.0
DOWN_WINDOW_ENV = "SRML_AUTOSCALE_DOWN_WINDOW_S"
_DEFAULT_DOWN_WINDOW_S = 5.0
UP_FILL_ENV = "SRML_AUTOSCALE_UP_FILL"
_DEFAULT_UP_FILL = 0.5
UP_BURN_ENV = "SRML_AUTOSCALE_UP_BURN"
_DEFAULT_UP_BURN = 0.1
DOWN_FILL_ENV = "SRML_AUTOSCALE_DOWN_FILL"
_DEFAULT_DOWN_FILL = 0.05
DOWN_OCCUPANCY_ENV = "SRML_AUTOSCALE_DOWN_OCCUPANCY"
_DEFAULT_DOWN_OCCUPANCY = 0.25
UP_COOLDOWN_ENV = "SRML_AUTOSCALE_UP_COOLDOWN_S"
_DEFAULT_UP_COOLDOWN_S = 1.0
DOWN_COOLDOWN_ENV = "SRML_AUTOSCALE_DOWN_COOLDOWN_S"
_DEFAULT_DOWN_COOLDOWN_S = 10.0

# consecutive ticks a replica must read UNHEALTHY before it is replaced:
# state() flips transient wedges to RECOVERING synchronously, but the
# worker-death window can expose a momentary UNHEALTHY that the bounded
# supervisor is about to recover in place — replacing THAT replica would
# waste a warmup racing the restart.  Two reads one tick apart only ever
# see a replica whose restart budget is spent (terminal by construction).
_TERMINAL_STREAK = 2


@dataclass(frozen=True)
class AutoscalePolicy:
    """One model's scaling policy; from_env() reads the SRML_AUTOSCALE_*
    knobs so deployments tune without code."""

    min_replicas: int = _DEFAULT_MIN
    max_replicas: int = _DEFAULT_MAX
    window_s: float = _DEFAULT_WINDOW_S
    down_window_s: float = _DEFAULT_DOWN_WINDOW_S
    up_fill: float = _DEFAULT_UP_FILL
    up_burn: float = _DEFAULT_UP_BURN
    down_fill: float = _DEFAULT_DOWN_FILL
    down_occupancy: float = _DEFAULT_DOWN_OCCUPANCY
    up_cooldown_s: float = _DEFAULT_UP_COOLDOWN_S
    down_cooldown_s: float = _DEFAULT_DOWN_COOLDOWN_S

    @classmethod
    def from_env(cls) -> "AutoscalePolicy":
        from ..utils import env_float

        return cls(
            min_replicas=max(1, int(env_float(MIN_ENV, _DEFAULT_MIN))),
            max_replicas=max(1, int(env_float(MAX_ENV, _DEFAULT_MAX))),
            window_s=env_float(WINDOW_ENV, _DEFAULT_WINDOW_S),
            down_window_s=env_float(DOWN_WINDOW_ENV, _DEFAULT_DOWN_WINDOW_S),
            up_fill=env_float(UP_FILL_ENV, _DEFAULT_UP_FILL),
            up_burn=env_float(UP_BURN_ENV, _DEFAULT_UP_BURN),
            down_fill=env_float(DOWN_FILL_ENV, _DEFAULT_DOWN_FILL),
            down_occupancy=env_float(
                DOWN_OCCUPANCY_ENV, _DEFAULT_DOWN_OCCUPANCY
            ),
            up_cooldown_s=env_float(UP_COOLDOWN_ENV, _DEFAULT_UP_COOLDOWN_S),
            down_cooldown_s=env_float(
                DOWN_COOLDOWN_ENV, _DEFAULT_DOWN_COOLDOWN_S
            ),
        )


class _ModelScaleState:
    """Per-model sliding window + hysteresis clocks (touched only under
    the autoscaler's state lock)."""

    def __init__(self):
        self.window: deque = deque()  # (t, fill, burn, shed_delta, occupancy)
        self.last_shed: Optional[float] = None  # counter watermark
        self.last_up: float = float("-inf")
        self.last_down: float = float("-inf")
        self.unhealthy_streak: Dict[int, int] = {}  # id(replica) -> ticks


class Autoscaler:
    """The srml-elastic policy loop: one daemon thread ticking every
    `interval_s`, reading the exported signal surface for every routed
    model (or the explicit `names` subset) and actuating through
    Router.scale_to / Router.replace_replica.  `tick()` is public and
    thread-safe so tests drive the policy deterministically without the
    thread.  Use as a context manager, or start()/stop()."""

    def __init__(
        self,
        router: Any,
        policy: Optional[AutoscalePolicy] = None,
        interval_s: Optional[float] = None,
        names: Optional[List[str]] = None,
    ):
        from ..utils import env_float

        self._router = router
        self._policy = policy or AutoscalePolicy.from_env()
        self._interval_s = (
            interval_s
            if interval_s is not None
            else env_float(INTERVAL_ENV, _DEFAULT_INTERVAL_S)
        )
        self._names = list(names) if names is not None else None
        self._lock = sanitize.lockdep_lock("serve.autoscale.state")
        self._states: Dict[str, _ModelScaleState] = {}
        self._journal: deque = deque(maxlen=256)
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "Autoscaler":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop_event.clear()
            self._thread = threading.Thread(
                target=self._run, name="srml-autoscale", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout_s: float = 10.0) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop_event.set()
        if thread is not None:
            thread.join(timeout=timeout_s)

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop_event.wait(self._interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the loop must outlive one bad tick
                logger.exception("autoscale: tick failed; continuing")
                profiling.incr_counter("autoscale.tick_errors")

    # -- the policy tick ------------------------------------------------------
    def tick(self) -> None:
        """One policy evaluation over every watched model."""
        names = self._names if self._names is not None else self._router.names()
        now = profiling.now()
        for name in names:
            try:
                self._tick_model(name, now)
            except KeyError:
                continue  # unrouted between names() and the read — skip

    def _tick_model(self, name: str, now: float) -> None:
        reps = self._router.replicas(name)
        if not reps:
            return
        with self._lock:
            st = self._states.setdefault(name, _ModelScaleState())
        # -- repair: preemption as the common case ------------------------
        reps = self._repair(name, st, reps)
        # -- signals (the exported surface only) --------------------------
        fill = scheduler.aggregate_fill(reps)
        occupancy = scheduler.aggregate_occupancy(reps)
        burn = max(
            (r.slo_burn() for r in reps if hasattr(r, "slo_burn")),
            default=0.0,
        )
        shed_total = profiling.counter(f"router.{name}.shed")
        with self._lock:
            shed_delta = (
                0.0 if st.last_shed is None else shed_total - st.last_shed
            )
            st.last_shed = shed_total
            st.window.append((now, fill, burn, shed_delta, occupancy))
            horizon = max(self._policy.window_s, self._policy.down_window_s)
            while st.window and now - st.window[0][0] > horizon:
                st.window.popleft()
            decision, target, reason = self._decide(
                name, st, now, len(reps)
            )
        if decision == "hold":
            profiling.incr_counter(f"autoscale.{name}.holds")
            if reason is not None:  # pressured hold (cooldown/capacity)
                self._record(now, name, "hold", reason, len(reps), len(reps))
            return
        try:
            self._router.scale_to(name, target)
        except CapacityExhausted as exc:
            profiling.incr_counter(f"autoscale.{name}.holds")
            profiling.incr_counter(f"autoscale.{name}.capacity_exhausted")
            self._record(
                now, name, "hold", f"capacity exhausted: {exc}",
                len(reps), len(reps),
            )
            return
        except KeyError:
            return  # unrouted mid-decision
        with self._lock:
            if decision == "scale_up":
                st.last_up = now
            else:
                st.last_down = now
        profiling.incr_counter(f"autoscale.{name}.{decision}")
        self._record(now, name, decision, reason, len(reps), target)
        logger.info(
            "autoscale.%s: %s %d -> %d (%s)",
            name, decision, len(reps), target, reason,
        )

    def _repair(self, name: str, st: _ModelScaleState, reps: List[Any]):
        """Replace replicas terminal for _TERMINAL_STREAK consecutive
        ticks; returns the refreshed replica snapshot."""
        dead: List[Any] = []
        with self._lock:
            seen = set()
            for r in reps:
                state = r.state()
                key = id(r)
                seen.add(key)
                if state == UNHEALTHY:
                    streak = st.unhealthy_streak.get(key, 0) + 1
                    st.unhealthy_streak[key] = streak
                    if streak >= _TERMINAL_STREAK:
                        dead.append(r)
                else:
                    st.unhealthy_streak.pop(key, None)
            for key in list(st.unhealthy_streak):
                if key not in seen:  # replaced/scaled away
                    st.unhealthy_streak.pop(key, None)
        replaced = 0
        for r in dead:
            incoming = self._router.replace_replica(name, r)
            if incoming is not None:
                replaced += 1
                with self._lock:
                    st.unhealthy_streak.pop(id(r), None)
                profiling.incr_counter(f"autoscale.{name}.repairs")
                self._record(
                    profiling.now(), name, "repair",
                    f"replica {r.name} terminal (preempted/restart budget "
                    "spent); re-sliced and re-warmed from the AOT cache",
                    len(reps), len(reps),
                )
        if replaced:
            return self._router.replicas(name)
        return reps

    def _decide(self, name, st, now, cur):
        """(decision, target, reason) under the hysteresis policy; caller
        holds the state lock.  decision "hold" with reason=None is a quiet
        steady-state hold; a non-None reason is a pressured hold worth
        journaling."""
        p = self._policy
        up_w = [e for e in st.window if now - e[0] <= p.window_s]
        reason = None
        if up_w:
            avg_fill = sum(e[1] for e in up_w) / len(up_w)
            max_burn = max(e[2] for e in up_w)
            sheds = sum(e[3] for e in up_w)
            if sheds > 0:
                reason = f"shed {sheds:.0f} request(s) in {p.window_s}s window"
            elif avg_fill > p.up_fill:
                reason = (
                    f"fill {avg_fill:.2f} > {p.up_fill} over {p.window_s}s"
                )
            elif max_burn > p.up_burn:
                reason = (
                    f"SLO burn {max_burn:.2f} > {p.up_burn} in window"
                )
            if reason is not None:
                if cur >= p.max_replicas:
                    return "hold", cur, f"{reason}; at max_replicas"
                if now - st.last_up < p.up_cooldown_s:
                    return "hold", cur, f"{reason}; in up-cooldown"
                return "scale_up", cur + 1, reason
        # scale-down: sustained idle across the FULL down-window
        if cur > p.min_replicas:
            down_w = [e for e in st.window if now - e[0] <= p.down_window_s]
            spans = (
                down_w and now - down_w[0][0] >= p.down_window_s * 0.9
            )
            idle = spans and all(
                e[1] < p.down_fill
                and e[2] <= p.up_burn
                and e[3] == 0
                and e[4] < p.down_occupancy
                for e in down_w
            )
            cooled = (
                now - st.last_down >= p.down_cooldown_s
                and now - st.last_up >= p.down_cooldown_s
            )
            if idle and cooled:
                return (
                    "scale_down",
                    cur - 1,
                    f"idle {p.down_window_s}s (fill < {p.down_fill}, "
                    f"occupancy < {p.down_occupancy}, no sheds)",
                )
        return "hold", cur, reason

    # -- the decision journal -------------------------------------------------
    def _record(self, t, name, decision, reason, from_n, to_n) -> None:
        entry = {
            "t": round(t, 3),
            "model": name,
            "decision": decision,
            "reason": reason,
            "from_replicas": from_n,
            "to_replicas": to_n,
        }
        with self._lock:
            self._journal.append(entry)

    def journal(self) -> List[Dict[str, Any]]:
        """Snapshot of the bounded decision journal, oldest first —
        scale_up/scale_down/repair entries plus pressured holds, each
        with its reason string."""
        with self._lock:
            return list(self._journal)
