#
# srml-elastic slice pool: the capacity ledger under the autoscaling
# replica plane (serving/autoscale.py, docs/serving.md §srml-elastic).
#
# Before this module, every Router.serve() carved mesh slices over the
# WHOLE device list independently (parallel/mesh.slice_meshes), so two
# models on one router silently shared devices — exactly the XLA:CPU
# cross_module rendezvous hazard slice_meshes' own docstring warns about,
# and on TPU hardware a serialization of both models onto the same chips.
# The SlicePool makes slice ownership explicit: ONE ledger of fixed-size,
# disjoint, group-aware device slices (parallel/mesh.carve_device_slices —
# never straddling a host group, PR 19 topology) from which replicas of
# ALL served models allocate and release.  No slice is ever handed to two
# owners; when nothing is free the pool raises the typed CapacityExhausted
# instead of quietly doubling up, and oversubscription (single-device
# shared leases — single-device programs have no cross-program rendezvous,
# so sharing degrades to compute contention instead of deadlock) happens
# only under an explicit policy flag.
#
# The pool is deliberately dumb: no waiting, no priorities, no preemption
# of leases.  Deciding WHEN to take or give back a slice is the
# autoscaler's job (serving/autoscale.py); deciding WHO runs on a slice is
# the router's.  The pool only guarantees the invariant that makes both
# safe: at any instant, every multi-device slice has at most one owner.
#

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .. import profiling, sanitize

SLICE_DEVICES_ENV = "SRML_POOL_SLICE_DEVICES"


class CapacityExhausted(ValueError):
    """The pool has no free slice for this allocation.  A ValueError
    because asking for more disjoint slices than the hardware holds is a
    deployment-spec error — but retryable, because capacity is dynamic:
    a scale-down or an unroute elsewhere frees a slice.  Callers that can
    wait (the autoscaler's scale-up path) treat it as "hold and re-try
    next tick"; callers that cannot (Router.serve at deploy time) surface
    it with the allow_oversubscribe escape hatch named."""

    retryable = True


class SliceLease:
    """One granted slice: the mesh to build a replica on, plus the ledger
    bookkeeping to give it back.  Release through SlicePool.release (or
    lease.release()) — idempotent, so teardown paths may race."""

    __slots__ = ("pool", "index", "devices", "mesh", "owner", "shared",
                 "released")

    def __init__(self, pool, index, devices, mesh, owner, shared):
        self.pool = pool
        self.index = index  # ledger slot; -1 for oversubscribed leases
        self.devices = tuple(devices)
        self.mesh = mesh
        self.owner = owner
        self.shared = shared  # True: single-device oversubscription lease
        self.released = False

    def release(self) -> None:
        self.pool.release(self)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        kind = "shared" if self.shared else f"slice {self.index}"
        return (
            f"<SliceLease {kind} owner={self.owner!r} "
            f"devices={[getattr(d, 'id', d) for d in self.devices]} "
            f"released={self.released}>"
        )


def _default_slice_devices(n_devices: int) -> int:
    """Default carve granularity: a quarter of the fleet per slice (at
    least one device) — four-way scale headroom out of the box, which is
    what makes `Autoscaler` useful on a pool nobody tuned.  Override with
    SRML_POOL_SLICE_DEVICES or the ctor knob."""
    from ..utils import env_float

    configured = int(env_float(SLICE_DEVICES_ENV, 0))
    if configured >= 1:
        return configured
    return max(1, n_devices // 4)


class SlicePool:
    """Fixed-granularity allocator of disjoint, group-aware device slices.

    `allocate(owner)` grants a free slice as a SliceLease (its `.mesh` is
    a 1-D data mesh over the slice, ready for ModelServer); `release`
    returns it.  With every slice taken, allocate raises the typed
    CapacityExhausted — unless oversubscription is explicitly allowed
    (pool-wide `allow_oversubscribe=True` or per-call), in which case the
    overflow lease is a SINGLE device picked round-robin (marked
    `.shared`), mirroring slice_meshes' degradation rule: single-device
    programs cannot deadlock the XLA:CPU rendezvous, they only contend.

    Thread-safe under one lockdep-named lock; gauges (slicepool.*) ride
    the srml_elastic Prometheus family via a weak provider, so an
    abandoned pool is collectable."""

    def __init__(
        self,
        slice_devices: Optional[int] = None,
        devices: Optional[List[Any]] = None,
        *,
        allow_oversubscribe: bool = False,
    ):
        import jax
        from jax.sharding import Mesh

        from ..parallel.mesh import DATA_AXIS, carve_device_slices

        devs = list(devices) if devices is not None else jax.devices()
        if not devs:
            raise ValueError("SlicePool needs at least one device")
        self.slice_devices = (
            slice_devices
            if slice_devices is not None
            else _default_slice_devices(len(devs))
        )
        slices = carve_device_slices(devs, self.slice_devices)
        if not slices:
            raise ValueError(
                f"no {self.slice_devices}-device slice fits in "
                f"{len(devs)} device(s)"
            )
        self._devices = devs
        self._slices = slices
        self._meshes = [Mesh(np.array(s), (DATA_AXIS,)) for s in slices]
        self.stranded_devices = len(devs) - self.slice_devices * len(slices)
        self.allow_oversubscribe = allow_oversubscribe
        self._lock = sanitize.lockdep_lock("serve.slicepool")
        self._owners: List[Optional[str]] = [None] * len(slices)
        self._rr = 0  # round-robin cursor for oversubscribed leases
        self._shared = 0  # live oversubscribed leases
        import weakref

        self._gauge_key = f"serving-slicepool-{id(self):x}"
        ref = weakref.ref(self)

        def _provider():
            pool = ref()
            return pool._pool_gauges() if pool is not None else {}

        profiling.register_gauges(self._gauge_key, _provider)

    # -- ledger ---------------------------------------------------------------
    @property
    def capacity(self) -> int:
        return len(self._slices)

    def free(self) -> int:
        with self._lock:
            return sum(1 for o in self._owners if o is None)

    def holders(self) -> Dict[str, int]:
        """Live owners -> held slice count (oversubscribed leases are not
        ledger slots and do not appear)."""
        with self._lock:
            out: Dict[str, int] = {}
            for o in self._owners:
                if o is not None:
                    out[o] = out.get(o, 0) + 1
            return out

    def allocate(
        self, owner: str, *, oversubscribe: Optional[bool] = None
    ) -> SliceLease:
        """Grant a free slice to `owner`.  `oversubscribe` overrides the
        pool-wide policy for this call (None: inherit)."""
        allow = (
            self.allow_oversubscribe if oversubscribe is None else oversubscribe
        )
        with self._lock:
            for i, holder in enumerate(self._owners):
                if holder is None:
                    self._owners[i] = owner
                    profiling.incr_counter("slicepool.allocate")
                    return SliceLease(
                        self, i, self._slices[i], self._meshes[i], owner,
                        shared=False,
                    )
            if not allow:
                held: Dict[str, int] = {}
                for o in self._owners:
                    held[o] = held.get(o, 0) + 1
                profiling.incr_counter("slicepool.exhausted")
                raise CapacityExhausted(
                    f"slicepool: all {self.capacity} "
                    f"{self.slice_devices}-device slice(s) are held "
                    f"({held}); scale something down, or pass "
                    "allow_oversubscribe=True to accept single-device "
                    "shared slices (compute contention, no rendezvous "
                    "deadlock)"
                )
            dev = self._devices[self._rr % len(self._devices)]
            self._rr += 1
            self._shared += 1
        from jax.sharding import Mesh

        from ..parallel.mesh import DATA_AXIS

        profiling.incr_counter("slicepool.allocate")
        profiling.incr_counter("slicepool.oversubscribed")
        return SliceLease(
            self, -1, [dev], Mesh(np.array([dev]), (DATA_AXIS,)), owner,
            shared=True,
        )

    def release(self, lease: SliceLease) -> None:
        """Return a lease.  Idempotent: teardown paths (half-built replica
        sets, shutdown racing a scale-down) may release twice."""
        if lease.pool is not self:
            raise ValueError("lease belongs to a different SlicePool")
        with self._lock:
            if lease.released:
                return
            lease.released = True
            if lease.shared:
                self._shared -= 1
            else:
                self._owners[lease.index] = None
        profiling.incr_counter("slicepool.release")

    # -- observability --------------------------------------------------------
    def _pool_gauges(self) -> Dict[str, float]:
        with self._lock:
            free = sum(1 for o in self._owners if o is None)
            shared = self._shared
        return {
            "slicepool.slices": float(self.capacity),
            "slicepool.free": float(free),
            "slicepool.shared_leases": float(shared),
            "slicepool.stranded_devices": float(self.stranded_devices),
        }

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            owners = list(self._owners)
            shared = self._shared
        return {
            "slice_devices": self.slice_devices,
            "capacity": self.capacity,
            "free": sum(1 for o in owners if o is None),
            "owners": owners,
            "shared_leases": shared,
            "stranded_devices": self.stranded_devices,
        }

    def close(self) -> None:
        """Unregister the gauge provider (a Router that built its own
        pool closes it on shutdown; the weakref makes this optional for
        abandoned pools)."""
        profiling.unregister_gauges(self._gauge_key)
