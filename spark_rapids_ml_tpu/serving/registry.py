#
# ModelRegistry: named ModelServers over fitted models.
#
# Two admission paths: register(name, model) for models already in memory
# (a just-fitted estimator, a kNN model whose item frame lives in the
# process), and load(name, path) which resolves any saved model through the
# core persistence layer (core.load reads the class from metadata.json) and
# serves it.  Either way the server warms EVERY serving bucket at
# registration — model load time is where the compile bill is paid, so the
# first request is already steady state.
#

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from .engine import SEVERITY, STATE_CODES, WARMING, ModelServer


class ModelRegistry:
    """Thread-safe name -> ModelServer map with load-time warmup.

    `server_kwargs` are the defaults every server is built with
    (max_batch, max_wait_ms, queue_depth, ...); per-model overrides go on
    register/load.  Each registry registers a health gauge provider
    (srml-watch), so every server's state/attainment/burn flows through
    profiling.export_metrics() and the Prometheus rendering for as long as
    the registry lives."""

    def __init__(self, **server_kwargs: Any):
        self._defaults = dict(server_kwargs)
        self._lock = threading.Lock()
        self._servers: Dict[str, ModelServer] = {}
        import weakref

        from .. import profiling

        # the provider holds a WEAK reference: a registry abandoned without
        # shutdown() must not be pinned alive by the gauge registry (its
        # servers' __del__ backstops still run, and the provider degrades
        # to {} instead of scraping a ghost)
        self._gauge_key = f"serving-registry-{id(self):x}"
        ref = weakref.ref(self)

        def _provider():
            reg = ref()
            return reg._health_gauges() if reg is not None else {}

        profiling.register_gauges(self._gauge_key, _provider)

    def register(self, name: str, model: Any, **overrides: Any) -> ModelServer:
        """Serve an in-memory fitted model under `name` (warms buckets and
        starts the dispatch worker before returning).  The name is RESERVED
        before the warmup: a duplicate fails immediately instead of paying
        the whole compile bill first — and polluting the live server's
        serving.<name>.* metrics namespace with a doomed twin's warmup."""
        with self._lock:
            if name in self._servers:
                raise ValueError(f"model name {name!r} already registered")
            self._servers[name] = None  # reservation; filled below
        try:
            server = ModelServer(name, model, **{**self._defaults, **overrides})
        except BaseException:
            with self._lock:
                self._servers.pop(name, None)
            raise
        with self._lock:
            self._servers[name] = server
        return server

    def load(self, name: str, path: str, **overrides: Any) -> ModelServer:
        """Load a saved model from `path` via core persistence and serve it.
        Estimators (no transform surface) are rejected with a clear error."""
        from ..core import _TpuModel, load as core_load

        obj = core_load(path)
        if not isinstance(obj, _TpuModel):
            raise TypeError(
                f"{path!r} holds a {type(obj).__name__}, not a fitted model; "
                "only models are servable"
            )
        return self.register(name, obj, **overrides)

    def get(self, name: str) -> ModelServer:
        with self._lock:
            server = self._servers.get(name)
        if server is None:  # absent OR still warming (reservation)
            raise KeyError(f"no served model named {name!r}")
        return server

    def names(self) -> list:
        with self._lock:
            return sorted(n for n, s in self._servers.items() if s is not None)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return self._servers.get(name) is not None

    def unregister(self, name: str, drain: bool = True) -> None:
        with self._lock:
            server = self._servers.pop(name, None)
        if server is not None:
            server.shutdown(drain=drain)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            servers = {n: s for n, s in self._servers.items() if s is not None}
        return {name: s.stats() for name, s in sorted(servers.items())}

    def health(self) -> Dict[str, Any]:
        """Health of the whole serving plane: per-server SLO-scored health
        (serving/engine.ModelServer.health) plus the registry's overall
        state — the WORST server state, so one wedged worker turns the
        whole plane's headline red.  Servers still warming (reservations)
        report WARMING."""
        with self._lock:
            snapshot = dict(self._servers)
        models: Dict[str, Any] = {}
        for name, server in sorted(snapshot.items()):
            if server is None:  # reserved: register/load still warming
                models[name] = {
                    "name": name,
                    "state": WARMING,
                    "state_code": STATE_CODES[WARMING],
                }
            else:
                models[name] = server.health()
        # worst-state rollup over the SEVERITY order (not the stable gauge
        # codes): one wedged worker turns the whole plane's headline red,
        # and a RECOVERING server outranks a draining one
        worst = max(
            (m["state"] for m in models.values()),
            key=SEVERITY.index,
            default=WARMING,  # an empty registry is not unhealthy, just idle
        )
        return {
            "state": worst,
            # srml-shield rollup: total supervised restarts across the
            # plane — a restart-storm signal no single server's counter
            # shows (docs/robustness.md)
            "restarts": sum(m.get("restarts", 0) for m in models.values()),
            "models": models,
        }

    def _health_gauges(self) -> Dict[str, float]:
        """Gauge-provider view of health() for export_metrics()/Prometheus:
        health.<model>.{state_code,attainment,burn,p99_ms,queued_rows}."""
        out: Dict[str, float] = {}
        for name, h in self.health()["models"].items():
            out[f"health.{name}.state_code"] = float(h["state_code"])
            if "attainment" in h:
                out[f"health.{name}.attainment"] = float(h["attainment"])
                out[f"health.{name}.burn"] = float(h["burn"])
                out[f"health.{name}.queued_rows"] = float(h["queued_rows"])
                if h.get("p99_ms") is not None:
                    out[f"health.{name}.p99_ms"] = float(h["p99_ms"])
        return out

    def telemetry(self, since: Optional[Any] = None) -> Any:
        """TelemetrySnapshot of the whole serving plane: every
        serving.<name>.* counter plus mergeable digests of the serve.<name>.*
        duration series.  Pass a previous snapshot as `since` for a delta —
        counter differences and count/sum duration deltas — so a scrape loop
        (or a live-Spark executor shipping its registry state to the driver)
        reports "what moved this window" instead of process history.
        Snapshots from many processes merge() associatively driver-side,
        exactly like fit telemetry."""
        from .. import profiling

        snap = profiling.TelemetrySnapshot(
            counters=profiling.counters("serving."),
            durations=profiling.duration_digests("serve."),
        )
        if since is None:
            return snap
        ctr = {
            k: v - since.counters.get(k, 0)
            for k, v in snap.counters.items()
            if v != since.counters.get(k, 0)
        }
        dur = {}
        for k, d in snap.durations.items():
            prev = since.durations.get(k)
            if prev is None:
                dur[k] = dict(d)
                continue
            dc = d["count"] - prev["count"]
            if dc > 0:
                # min/max cannot be un-merged; the window keeps the current
                # extremes (documented in docs/observability.md)
                dur[k] = {
                    "count": dc,
                    "sum_s": d["sum_s"] - prev["sum_s"],
                    "min_s": d["min_s"],
                    "max_s": d["max_s"],
                }
        return profiling.TelemetrySnapshot(counters=ctr, durations=dur)

    def shutdown(self, drain: bool = True) -> None:
        from .. import profiling

        profiling.unregister_gauges(self._gauge_key)
        with self._lock:
            servers = [s for s in self._servers.values() if s is not None]
            self._servers.clear()
        for s in servers:
            s.shutdown(drain=drain)

    def __enter__(self) -> "ModelRegistry":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


_default: Optional[ModelRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> ModelRegistry:
    """Process-wide registry for embedders that want one shared serving
    plane (the analog of ops/precompile.global_precompiler)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = ModelRegistry()
        return _default
