#
# ModelRegistry: named ModelServers over fitted models.
#
# Two admission paths: register(name, model) for models already in memory
# (a just-fitted estimator, a kNN model whose item frame lives in the
# process), and load(name, path) which resolves any saved model through the
# core persistence layer (core.load reads the class from metadata.json) and
# serves it.  Either way the server warms EVERY serving bucket at
# registration — model load time is where the compile bill is paid, so the
# first request is already steady state.
#
# The registry is the SINGLE-server deployment surface (one ModelServer
# per name on the whole mesh).  Replicated, capacity-managed serving —
# slice-pool leases, scale_to, autoscaling, preemption repair — is the
# router plane (serving/router.py + serving/slicepool.py +
# serving/autoscale.py); a registry server's whole-mesh footprint is by
# design outside the slice pool's ledger.
#

from __future__ import annotations

from typing import Any, Dict, Optional

from .. import sanitize
from .engine import SEVERITY, STATE_CODES, WARMING, ModelServer


class ModelRegistry:
    """Thread-safe name -> ModelServer map with load-time warmup.

    `server_kwargs` are the defaults every server is built with
    (max_batch, max_wait_ms, queue_depth, ...); per-model overrides go on
    register/load.  Each registry registers a health gauge provider
    (srml-watch), so every server's state/attainment/burn flows through
    profiling.export_metrics() and the Prometheus rendering for as long as
    the registry lives."""

    def __init__(self, **server_kwargs: Any):
        self._defaults = dict(server_kwargs)
        self._lock = sanitize.lockdep_lock("serve.registry.state")
        self._servers: Dict[str, ModelServer] = {}
        import weakref

        from .. import profiling

        # the provider holds a WEAK reference: a registry abandoned without
        # shutdown() must not be pinned alive by the gauge registry (its
        # servers' __del__ backstops still run, and the provider degrades
        # to {} instead of scraping a ghost)
        self._gauge_key = f"serving-registry-{id(self):x}"
        ref = weakref.ref(self)

        def _provider():
            reg = ref()
            return reg._health_gauges() if reg is not None else {}

        profiling.register_gauges(self._gauge_key, _provider)

    def register(self, name: str, model: Any, **overrides: Any) -> ModelServer:
        """Serve an in-memory fitted model under `name` (warms buckets and
        starts the dispatch worker before returning).  The name is RESERVED
        before the warmup: a duplicate fails immediately instead of paying
        the whole compile bill first — and polluting the live server's
        serving.<name>.* metrics namespace with a doomed twin's warmup."""
        with self._lock:
            if name in self._servers:
                raise ValueError(f"model name {name!r} already registered")
            self._servers[name] = None  # reservation; filled below
        try:
            server = ModelServer(name, model, **{**self._defaults, **overrides})
        except BaseException:
            with self._lock:
                self._servers.pop(name, None)
            raise
        with self._lock:
            self._servers[name] = server
        return server

    def multiplex(
        self,
        name: str,
        models: Dict[str, Any],
        *,
        resident_lanes: Optional[int] = None,
        **overrides: Any,
    ) -> "ModelServer":
        """Serve K same-shape model variants behind ONE lane-batched server
        (srml-lanes): every micro-batch dispatches one kernel across the
        tenants' stacked parameters, and variants beyond `resident_lanes`
        page into the LRU'd device lane buffer on demand — thousands of
        registered variants on a fixed HBM budget.  The returned server is
        a MultiplexServer (a ModelServer subclass: health/stats/telemetry/
        swap-era lifecycle all apply); clients pass model_id to
        submit()/predict().  Name reservation mirrors register()."""
        from .multiplex import MultiplexServer

        with self._lock:
            if name in self._servers:
                raise ValueError(f"model name {name!r} already registered")
            self._servers[name] = None  # reservation; filled below
        try:
            server = MultiplexServer(
                name,
                models,
                resident_lanes=resident_lanes,
                **{**self._defaults, **overrides},
            )
        except BaseException:
            with self._lock:
                self._servers.pop(name, None)
            raise
        with self._lock:
            self._servers[name] = server
        return server

    def load(self, name: str, path: str, **overrides: Any) -> ModelServer:
        """Load a saved model from `path` via core persistence and serve it.
        Estimators (no transform surface) are rejected with a clear error."""
        from ..core import _TpuModel, load as core_load

        obj = core_load(path)
        if not isinstance(obj, _TpuModel):
            raise TypeError(
                f"{path!r} holds a {type(obj).__name__}, not a fitted model; "
                "only models are servable"
            )
        return self.register(name, obj, **overrides)

    def swap(
        self,
        name: str,
        new_model: Any,
        *,
        drain_timeout_s: float = 60.0,
        **overrides: Any,
    ) -> ModelServer:
        """Zero-downtime hot swap: warm a NEW server for `new_model` (its
        buckets compile — or, for a same-shape model class, re-warm from
        the retained AOT executable cache, zero new compiles), verify the
        serving signature matches the old generation, atomically cut the
        name over, then drain the old generation so its in-flight requests
        complete before teardown.  Traffic admitted after the cut-over
        lands on the new model; traffic admitted before it completes on
        the old one — no request is dropped, no submit window is closed.

        Raises KeyError for unknown/still-warming names and ValueError
        (from entry.check_swap_compatible) for a model whose feature
        width, dtype, or output columns differ — an incompatible upgrade
        is a register-under-a-new-name event, not a swap."""
        from .. import profiling
        from .entry import check_swap_compatible

        with self._lock:
            old = self._servers.get(name)
        if old is None:
            raise KeyError(f"no served model named {name!r} to swap")
        t0 = profiling.now()
        with profiling.span(f"serve.{name}.swap"):
            # warm BEFORE cut-over: the compile bill (zero for same-shape
            # classes — the retained AOT cache survives the old server) is
            # paid while the old generation still serves all traffic
            incoming = ModelServer(
                name, new_model, **{**self._defaults, **overrides}
            )
            try:
                check_swap_compatible(old._entry, incoming._entry, name)
                with self._lock:
                    if self._servers.get(name) is not old:
                        raise KeyError(
                            f"serving entry {name!r} changed during swap "
                            "(concurrent unregister/swap); aborting"
                        )
                    self._servers[name] = incoming  # the atomic cut-over
            except BaseException:
                incoming.shutdown(drain=False)
                raise
            # old generation: in-flight + already-queued requests drain to
            # completion, then clean teardown.  A drain timeout still tears
            # the old server down — the name already points at the new one.
            try:
                old.drain(timeout_s=drain_timeout_s)
            finally:
                old.shutdown(drain=False)
        profiling.incr_counter(f"serving.{name}.swaps")
        profiling.record_duration(f"serve.{name}.swap", profiling.now() - t0)
        return incoming

    def get(self, name: str) -> ModelServer:
        with self._lock:
            server = self._servers.get(name)
        if server is None:  # absent OR still warming (reservation)
            raise KeyError(f"no served model named {name!r}")
        return server

    def names(self) -> list:
        with self._lock:
            return sorted(n for n, s in self._servers.items() if s is not None)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return self._servers.get(name) is not None

    def unregister(self, name: str, drain: bool = True) -> None:
        with self._lock:
            server = self._servers.pop(name, None)
        if server is not None:
            server.shutdown(drain=drain)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            servers = {n: s for n, s in self._servers.items() if s is not None}
        return {name: s.stats() for name, s in sorted(servers.items())}

    def health(self) -> Dict[str, Any]:
        """Health of the whole serving plane: per-server SLO-scored health
        (serving/engine.ModelServer.health) plus the registry's overall
        state — the WORST server state, so one wedged worker turns the
        whole plane's headline red.  Servers still warming (reservations)
        report WARMING."""
        with self._lock:
            snapshot = dict(self._servers)
        models: Dict[str, Any] = {}
        for name, server in sorted(snapshot.items()):
            if server is None:  # reserved: register/load still warming
                models[name] = {
                    "name": name,
                    "state": WARMING,
                    "state_code": STATE_CODES[WARMING],
                }
            else:
                models[name] = server.health()
        # worst-state rollup over the SEVERITY order (not the stable gauge
        # codes): one wedged worker turns the whole plane's headline red,
        # and a RECOVERING server outranks a draining one
        worst = max(
            (m["state"] for m in models.values()),
            key=SEVERITY.index,
            default=WARMING,  # an empty registry is not unhealthy, just idle
        )
        return {
            "state": worst,
            # srml-shield rollup: total supervised restarts across the
            # plane — a restart-storm signal no single server's counter
            # shows (docs/robustness.md)
            "restarts": sum(m.get("restarts", 0) for m in models.values()),
            "models": models,
        }

    def _health_gauges(self) -> Dict[str, float]:
        """Gauge-provider view of health() for export_metrics()/Prometheus:
        health.<model>.{state_code,attainment,burn,p99_ms,queued_rows,
        restarts} — flattened by the shared srml-watch rule, so registry
        servers and router replicas render identically."""
        from .. import watch

        return watch.health_gauges(self.health()["models"])

    def telemetry(self, since: Optional[Any] = None) -> Any:
        """TelemetrySnapshot of the whole serving plane: every
        serving.<name>.* counter plus mergeable digests of the serve.<name>.*
        duration series.  Pass a previous snapshot as `since` for a delta —
        counter differences and count/sum duration deltas — so a scrape loop
        (or a live-Spark executor shipping its registry state to the driver)
        reports "what moved this window" instead of process history.
        Snapshots from many processes merge() associatively driver-side,
        exactly like fit telemetry."""
        from .. import profiling

        snap = profiling.TelemetrySnapshot(
            counters=profiling.counters("serving."),
            durations=profiling.duration_digests("serve."),
        )
        return snap if since is None else snap.delta(since)

    def shutdown(self, drain: bool = True) -> None:
        from .. import profiling

        profiling.unregister_gauges(self._gauge_key)
        with self._lock:
            servers = [s for s in self._servers.values() if s is not None]
            self._servers.clear()
        for s in servers:
            s.shutdown(drain=drain)

    def __enter__(self) -> "ModelRegistry":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


_default: Optional[ModelRegistry] = None
_default_lock = sanitize.lockdep_lock("serve.registry.default")


def default_registry() -> ModelRegistry:
    """Process-wide registry for embedders that want one shared serving
    plane (the analog of ops/precompile.global_precompiler)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = ModelRegistry()
        return _default
