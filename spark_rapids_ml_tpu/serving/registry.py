#
# ModelRegistry: named ModelServers over fitted models.
#
# Two admission paths: register(name, model) for models already in memory
# (a just-fitted estimator, a kNN model whose item frame lives in the
# process), and load(name, path) which resolves any saved model through the
# core persistence layer (core.load reads the class from metadata.json) and
# serves it.  Either way the server warms EVERY serving bucket at
# registration — model load time is where the compile bill is paid, so the
# first request is already steady state.
#

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from .engine import ModelServer


class ModelRegistry:
    """Thread-safe name -> ModelServer map with load-time warmup.

    `server_kwargs` are the defaults every server is built with
    (max_batch, max_wait_ms, queue_depth, ...); per-model overrides go on
    register/load."""

    def __init__(self, **server_kwargs: Any):
        self._defaults = dict(server_kwargs)
        self._lock = threading.Lock()
        self._servers: Dict[str, ModelServer] = {}

    def register(self, name: str, model: Any, **overrides: Any) -> ModelServer:
        """Serve an in-memory fitted model under `name` (warms buckets and
        starts the dispatch worker before returning).  The name is RESERVED
        before the warmup: a duplicate fails immediately instead of paying
        the whole compile bill first — and polluting the live server's
        serving.<name>.* metrics namespace with a doomed twin's warmup."""
        with self._lock:
            if name in self._servers:
                raise ValueError(f"model name {name!r} already registered")
            self._servers[name] = None  # reservation; filled below
        try:
            server = ModelServer(name, model, **{**self._defaults, **overrides})
        except BaseException:
            with self._lock:
                self._servers.pop(name, None)
            raise
        with self._lock:
            self._servers[name] = server
        return server

    def load(self, name: str, path: str, **overrides: Any) -> ModelServer:
        """Load a saved model from `path` via core persistence and serve it.
        Estimators (no transform surface) are rejected with a clear error."""
        from ..core import _TpuModel, load as core_load

        obj = core_load(path)
        if not isinstance(obj, _TpuModel):
            raise TypeError(
                f"{path!r} holds a {type(obj).__name__}, not a fitted model; "
                "only models are servable"
            )
        return self.register(name, obj, **overrides)

    def get(self, name: str) -> ModelServer:
        with self._lock:
            server = self._servers.get(name)
        if server is None:  # absent OR still warming (reservation)
            raise KeyError(f"no served model named {name!r}")
        return server

    def names(self) -> list:
        with self._lock:
            return sorted(n for n, s in self._servers.items() if s is not None)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return self._servers.get(name) is not None

    def unregister(self, name: str, drain: bool = True) -> None:
        with self._lock:
            server = self._servers.pop(name, None)
        if server is not None:
            server.shutdown(drain=drain)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            servers = {n: s for n, s in self._servers.items() if s is not None}
        return {name: s.stats() for name, s in sorted(servers.items())}

    def telemetry(self, since: Optional[Any] = None) -> Any:
        """TelemetrySnapshot of the whole serving plane: every
        serving.<name>.* counter plus mergeable digests of the serve.<name>.*
        duration series.  Pass a previous snapshot as `since` for a delta —
        counter differences and count/sum duration deltas — so a scrape loop
        (or a live-Spark executor shipping its registry state to the driver)
        reports "what moved this window" instead of process history.
        Snapshots from many processes merge() associatively driver-side,
        exactly like fit telemetry."""
        from .. import profiling

        snap = profiling.TelemetrySnapshot(
            counters=profiling.counters("serving."),
            durations=profiling.duration_digests("serve."),
        )
        if since is None:
            return snap
        ctr = {
            k: v - since.counters.get(k, 0)
            for k, v in snap.counters.items()
            if v != since.counters.get(k, 0)
        }
        dur = {}
        for k, d in snap.durations.items():
            prev = since.durations.get(k)
            if prev is None:
                dur[k] = dict(d)
                continue
            dc = d["count"] - prev["count"]
            if dc > 0:
                # min/max cannot be un-merged; the window keeps the current
                # extremes (documented in docs/observability.md)
                dur[k] = {
                    "count": dc,
                    "sum_s": d["sum_s"] - prev["sum_s"],
                    "min_s": d["min_s"],
                    "max_s": d["max_s"],
                }
        return profiling.TelemetrySnapshot(counters=ctr, durations=dur)

    def shutdown(self, drain: bool = True) -> None:
        with self._lock:
            servers = [s for s in self._servers.values() if s is not None]
            self._servers.clear()
        for s in servers:
            s.shutdown(drain=drain)

    def __enter__(self) -> "ModelRegistry":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


_default: Optional[ModelRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> ModelRegistry:
    """Process-wide registry for embedders that want one shared serving
    plane (the analog of ops/precompile.global_precompiler)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = ModelRegistry()
        return _default
