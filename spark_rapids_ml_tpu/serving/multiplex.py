#
# srml-lanes serving: multiplexed multi-tenant model serving.
#
# A dedicated ModelServer pays one dispatch — and one resident parameter
# buffer — per model variant.  MultiplexServer stacks K same-shape variants
# onto the pow2 lane axis of ONE parameter buffer (ops/lanes.stack_lanes)
# and dispatches one lane-batched kernel per micro-batch across different
# tenants' models: requests are routed (model_id -> lane) through the
# existing MicroBatcher (each request carries its lane id), the per-lane
# output scatter rides the existing Future-scatter (the kernel gathers
# parameters PER ROW, so the padded batch's output rows line up with the
# dedicated path's), and per-tenant counters ride the existing
# serving.<name>.* metric families under a .tenant.<model_id> suffix.
#
# HBM lane paging: variants beyond the resident lane budget live as host
# numpy leaves in `_registered`; a request for a non-resident model pages
# it into the least-recently-used idle lane with ONE H2D slice write per
# parameter leaf (ops/lanes.write_lane — traced lane index, zero new
# compiles; the PR 12 insight), so thousands of registered variants share
# a few dozen resident lanes.  A lane is only evicted when no queued or
# in-flight request rides it (`_lane_pending`); page-in replaces the
# stacked buffer tuple immutably, so an in-flight dispatch keeps the
# consistent values its rows were routed against.
#
# Exactness contract: the lane kernels run the exact per-row contraction
# of the dedicated kernels (SOLVER_PRECISION — see exact_gather_matmul),
# so on integer-exact data multiplexed outputs are bitwise-equal per
# tenant to dedicated per-model serving; the CI multiplex gate holds this.
#

from __future__ import annotations

import collections
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax

from .. import profiling, sanitize
from ..ops.lanes import lane_bucket, stack_lanes, write_lane
from .batcher import ServerOverloaded
from .engine import ModelServer, _warm_scope
from .entry import ServingEntry

PAGE_WAIT_ENV = "SRML_SERVE_PAGE_WAIT_S"
_DEFAULT_PAGE_WAIT_S = 5.0


def _page_wait_s() -> float:
    from ..utils import env_float

    return env_float(PAGE_WAIT_ENV, _DEFAULT_PAGE_WAIT_S)


@dataclass
class LaneEntry:
    """One model's MULTIPLEXED serving surface — what `_lane_entry` hooks
    return.  Unlike ServingEntry (a closed call over this model's device
    constants), a LaneEntry exposes the pieces the multiplex server needs
    to stack K variants behind one kernel: the host parameter `leaves`
    (stacked on a new leading lane axis), the lane-batched `kernel`
    (X, lanes, *stacked, **statics) -> device outputs, and the shared
    `postprocess` every variant's host-fetched output runs through.
    `meta` carries variant identity that must MATCH for two models to
    share a kernel and postprocess (e.g. logistic class labels); it rides
    lane_signature next to the shape/dtype/out_cols checks."""

    name: str                 # stable kernel-cache namespace, e.g. "lanes.linreg"
    n_cols: int
    dtype: np.dtype
    out_cols: List[str]
    leaves: tuple             # host np parameter leaves (this variant's values)
    kernel: Any               # (X, lanes, *stacked, **statics) -> device out
    statics: Dict[str, Any] = field(default_factory=dict)
    postprocess: Callable[[Any], Dict[str, np.ndarray]] = None
    meta: tuple = ()
    info: Dict[str, Any] = field(default_factory=dict)


def lane_signature(entry: "LaneEntry") -> tuple:
    """Everything two variants must agree on to share one lane buffer:
    kernel namespace, client contract (n_cols/dtype/out_cols), parameter
    leaf geometry, statics, and the model-class meta."""
    return (
        entry.name,
        int(entry.n_cols),
        str(np.dtype(entry.dtype)),
        tuple(sorted(entry.out_cols)),
        tuple((tuple(np.asarray(l).shape), str(np.asarray(l).dtype)) for l in entry.leaves),
        tuple(sorted(entry.statics.items())),
        entry.meta,
    )


def lane_entry_for(model: Any, mesh: Any = None) -> LaneEntry:
    """The model's multiplexed serving entry via its `_lane_entry` hook,
    with a uniform error for models that have no lane-batched path."""
    hook = getattr(model, "_lane_entry", None)
    if hook is None:
        raise TypeError(
            f"{type(model).__name__} is not multiplexable (no _lane_entry "
            "hook); serve it on a dedicated ModelServer instead"
        )
    entry = hook(mesh)
    if not isinstance(entry, LaneEntry):
        raise TypeError(
            f"{type(model).__name__}._lane_entry returned "
            f"{type(entry).__name__}, expected LaneEntry"
        )
    return entry


class _LaneStackModel:
    """Internal servable facade: hands ModelServer.__init__ the prebuilt
    multiplex ServingEntry through the standard _serving_entry hook, so
    the base engine (batcher, warmup, shield recovery, health) runs
    unchanged on the lane-batched entry."""

    def __init__(self, entry: ServingEntry):
        self._entry = entry

    def _serving_entry(self, mesh: Any = None) -> ServingEntry:
        return self._entry


class MultiplexServer(ModelServer):
    """One lane-batched server for K same-shape model variants.

    `models` is an ordered {model_id: fitted model}; every variant must
    produce an equal lane_signature (same model class, feature width,
    dtype, output columns, parameter geometry — a mismatch is a
    register-on-a-dedicated-server event, not a lane).  `resident_lanes`
    bounds the device lane budget: at most lane_bucket(resident_lanes)
    lane slots are stacked in HBM, and variants beyond it page in through
    the LRU (host-RAM spill is just `_registered` keeping every variant's
    numpy leaves).  Clients pass model_id to submit()/predict(); the rest
    of the ModelServer surface (health, stats, drain, shutdown, shield
    recovery) is inherited."""

    def __init__(
        self,
        name: str,
        models: Dict[str, Any],
        mesh: Any = None,
        *,
        resident_lanes: Optional[int] = None,
        **kwargs: Any,
    ):
        if not models:
            raise ValueError("MultiplexServer requires at least one model")
        entries = {mid: lane_entry_for(m, mesh) for mid, m in models.items()}
        ids = list(entries)
        proto = entries[ids[0]]
        sig0 = lane_signature(proto)
        for mid in ids[1:]:
            if lane_signature(entries[mid]) != sig0:
                raise ValueError(
                    f"multiplex({name!r}): variant {mid!r} is not "
                    f"lane-compatible with {ids[0]!r} (lane_signature "
                    "mismatch); same-shape variants only"
                )
        self._proto = proto
        # every registered variant's host leaves, cast once to the buffer
        # dtypes so a page-in is a pure H2D copy
        self._registered: "collections.OrderedDict[str, tuple]" = (
            collections.OrderedDict(
                # .reshape(np.shape(l)): ascontiguousarray promotes 0-d
                # leaves (scalar intercepts) to shape (1,), which would
                # silently widen the stacked buffer and break the kernel's
                # broadcast — preserve the declared leaf shape exactly
                (
                    mid,
                    tuple(
                        np.ascontiguousarray(np.asarray(l)).reshape(np.shape(l))
                        for l in e.leaves
                    ),
                )
                for mid, e in entries.items()
            )
        )
        want = int(resident_lanes) if resident_lanes else len(ids)
        want = max(1, min(want, len(ids)))
        self._n_lanes = lane_bucket(want)
        # lane state: model_id <-> lane maps, LRU order, per-lane pending
        # request counts (a lane with pending > 0 is never an eviction
        # victim — its queued/in-flight rows were routed against it)
        self._lane_lock = sanitize.lockdep_lock("serve.multiplex.lanes")
        self._lane_free = threading.Condition(self._lane_lock)
        self._lane_of: Dict[str, int] = {}
        self._lru: "collections.OrderedDict[str, int]" = collections.OrderedDict()
        self._lane_pending = [0] * self._n_lanes
        residents = ids[: min(self._n_lanes, len(ids))]
        self._stacked = stack_lanes(
            [self._registered[mid] for mid in residents], self._n_lanes
        )
        for i, mid in enumerate(residents):
            self._lane_of[mid] = i
            self._lru[mid] = i
        self._free_lanes = list(range(len(residents), self._n_lanes))
        # warm the per-leaf page-in write kernels before traffic by
        # rewriting lane 0 with its own values (idempotent): after this,
        # every page-in — any lane, any variant — is zero new compiles.
        # _warm_scope keeps any compile out of concurrent servers' steady-
        # state attribution windows.
        with _warm_scope():
            self._stacked = write_lane(
                self._stacked, 0, self._registered[residents[0]],
                name=proto.name,
            )
            jax.block_until_ready(self._stacked)
        super().__init__(name, _LaneStackModel(self._build_entry()), mesh, **kwargs)

    # -- the lane-batched ServingEntry ---------------------------------------
    def _build_entry(self) -> ServingEntry:
        from ..ops.precompile import (
            aval,
            cached_kernel,
            global_precompiler,
            kernel_cache_key,
        )

        proto = self._proto
        np_dtype = np.dtype(proto.dtype)
        n_cols = int(proto.n_cols)
        statics = dict(proto.statics)
        server = self  # the entry is owned by the server; plain closure is fine

        def call(batch: np.ndarray, lanes: np.ndarray) -> Dict[str, np.ndarray]:
            Xd = jax.device_put(np.ascontiguousarray(batch, dtype=np_dtype))
            ld = jax.device_put(np.ascontiguousarray(lanes, dtype=np.int32))
            # snapshot: page-in replaces the tuple immutably, and rows in
            # THIS batch only reference lanes whose pending count pinned
            # them — identical values in either snapshot
            stacked = server._stacked
            out = cached_kernel(proto.name, proto.kernel, Xd, ld, *stacked, **statics)
            return proto.postprocess(jax.device_get(out))

        def warm(buckets) -> list:
            pc = global_precompiler()
            stacked = server._stacked
            keys = []
            for b in buckets:
                args = (
                    aval((int(b), n_cols), np_dtype),
                    aval((int(b),), np.int32),
                ) + tuple(stacked)
                key = kernel_cache_key(proto.name, args, None, statics)
                pc.submit(key, proto.kernel, *args, **statics)
                keys.append(key)
            return keys

        return ServingEntry(
            name=proto.name,
            n_cols=n_cols,
            dtype=np_dtype,
            out_cols=list(proto.out_cols),
            call=call,
            warm=warm,
            info=dict(
                proto.info,
                lanes=self._n_lanes,
                registered=len(self._registered),
            ),
        )

    # -- lane paging ----------------------------------------------------------
    def _find_slot_locked(self) -> Optional[int]:
        """A lane to page into: a never-used free slot, else the least-
        recently-used resident whose pending count is zero (evicted).
        Returns None when every lane has in-flight traffic."""
        if self._free_lanes:
            return self._free_lanes.pop()
        for mid, lane in self._lru.items():  # oldest first
            if self._lane_pending[lane] == 0:
                del self._lane_of[mid]
                del self._lru[mid]
                profiling.incr_counter(f"{self.ns}.lanes.evictions")
                return lane
        return None

    def _lane_in(self, model_id: str) -> int:
        """Resolve model_id -> resident lane, paging it in if spilled, and
        pin the lane (pending += 1) until the request's future resolves."""
        with self._lane_lock:
            if model_id not in self._registered:
                known = sorted(self._registered)
                shown = known[:8] + ["..."] if len(known) > 8 else known
                raise KeyError(
                    f"{self.ns}: no registered variant {model_id!r} "
                    f"(registered: {shown})"
                )
            lane = self._lane_of.get(model_id)
            if lane is not None:
                self._lru.move_to_end(model_id)
                self._lane_pending[lane] += 1
                profiling.incr_counter(f"{self.ns}.lanes.hits")
                return lane
            deadline = profiling.now() + _page_wait_s()
            while True:
                lane = self._find_slot_locked()
                if lane is not None:
                    break
                remaining = deadline - profiling.now()
                if remaining <= 0:
                    raise ServerOverloaded(
                        f"{self.ns}: all {self._n_lanes} resident lanes "
                        "have in-flight traffic; retry with backoff "
                        f"(registered variants: {len(self._registered)})"
                    )
                # bounded wait (graftlint R9): a lost notify or a wedged
                # dispatch can never park a page-in forever — the deadline
                # above converts it into the typed retryable overload
                self._lane_free.wait(min(remaining, 1.0))
            t0 = profiling.now()
            stacked = write_lane(
                self._stacked, lane, self._registered[model_id],
                name=self._proto.name,
            )
            self._stacked = stacked
            self._lane_of[model_id] = lane
            self._lru[model_id] = lane
            self._lane_pending[lane] += 1
            profiling.incr_counter(f"{self.ns}.lanes.page_in")
        # Device sync OUTSIDE the critical section (graftlint R11): the pin
        # taken above keeps the lane resident, and any dispatch that snapshots
        # the new `_stacked` orders after the H2D write through jax's async
        # dispatch — blocking here only scores honest page-in wall time and
        # backpressures the paging tenant, never the other lanes' traffic.
        jax.block_until_ready(stacked)
        profiling.record_duration(
            f"serve.{self.name}.page_in", profiling.now() - t0
        )
        return lane

    def _lane_release(self, lane: int) -> None:
        with self._lane_lock:
            self._lane_pending[lane] -= 1
            if self._lane_pending[lane] == 0:
                self._lane_free.notify_all()

    # -- client API -----------------------------------------------------------
    def submit(
        self,
        features: np.ndarray,
        timeout_ms: Optional[float] = None,
        *,
        model_id: Optional[str] = None,
    ):
        """Enqueue one request for ONE tenant's model; returns a Future.
        `model_id` is required when more than one variant is registered
        (the single-variant case defaults to it, so a MultiplexServer of
        one model is submit-compatible with a dedicated server)."""
        if model_id is None:
            if len(self._registered) == 1:
                model_id = next(iter(self._registered))
            else:
                raise ValueError(
                    f"{self.ns}: multiplexed submit requires model_id= "
                    f"(one of {len(self._registered)} registered variants)"
                )
        resolved = self._lane_in(model_id)
        t0 = profiling.now()
        try:
            fut = super().submit(features, timeout_ms=timeout_ms, lane=resolved)
        except BaseException:
            self._lane_release(resolved)
            raise
        feats = np.asarray(features)
        n_rows = 1 if feats.ndim == 1 else int(feats.shape[0])
        tns = f"{self.ns}.tenant.{model_id}"
        profiling.incr_counter(f"{tns}.requests")
        profiling.incr_counter(f"{tns}.rows", n_rows)

        def _done(f) -> None:
            # runs on the resolving thread (dispatch scatter / recovery
            # shed): only counters + the pending decrement, never blocking
            self._lane_release(resolved)
            if not f.cancelled() and f.exception() is None:
                profiling.record_duration(
                    f"serve.{self.name}.tenant.{model_id}.latency",
                    profiling.now() - t0,
                )
            else:
                profiling.incr_counter(f"{tns}.errors")

        fut.add_done_callback(_done)
        return fut

    def predict(
        self,
        features: np.ndarray,
        timeout_ms: Optional[float] = None,
        *,
        model_id: Optional[str] = None,
    ) -> Dict[str, np.ndarray]:
        """Blocking convenience around submit(), per tenant."""
        fut = self.submit(features, timeout_ms=timeout_ms, model_id=model_id)
        wait_s = None
        if timeout_ms is not None and timeout_ms > 0:
            wait_s = timeout_ms / 1000.0 + 60.0  # dispatch slack
        return fut.result(timeout=wait_s)

    # -- engine hooks ----------------------------------------------------------
    def _synth_args(self, b: int) -> tuple:
        return (
            np.zeros((b, self._entry.n_cols), dtype=self._entry.dtype),
            np.zeros(b, dtype=np.int32),
        )

    def _assemble(self, batch) -> Tuple[np.ndarray, int, int, np.ndarray]:
        padded, n_rows, b = super()._assemble(batch)
        lanes = np.empty(b, dtype=np.int32)
        off = 0
        for r in batch:
            lanes[off : off + r.n_rows] = r.lane
            off += r.n_rows
        if b > n_rows:
            lanes[n_rows:] = 0  # pad rows ride lane 0; their output is sliced off
        return padded, n_rows, b, lanes

    # -- observability ---------------------------------------------------------
    def lanes(self) -> Dict[str, Any]:
        """Lane-plane snapshot: budget, residency, paging counters."""
        with self._lane_lock:
            resident = dict(self._lane_of)
            pending = list(self._lane_pending)
        return {
            "n_lanes": self._n_lanes,
            "registered": len(self._registered),
            "resident": len(resident),
            "resident_models": sorted(resident),
            "pending_by_lane": pending,
            "hits": profiling.counter(f"{self.ns}.lanes.hits"),
            "page_in": profiling.counter(f"{self.ns}.lanes.page_in"),
            "evictions": profiling.counter(f"{self.ns}.lanes.evictions"),
            "page_in_latency": profiling.percentiles(f"serve.{self.name}.page_in"),
        }

    def model_ids(self) -> list:
        return sorted(self._registered)

    def stats(self) -> Dict[str, Any]:
        out = super().stats()
        out["lanes"] = self.lanes()
        return out
