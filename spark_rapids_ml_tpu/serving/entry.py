#
# The model <-> serving-engine contract.
#
# A ServingEntry is what a fitted model hands the online inference engine:
# a `call` that runs ONE padded device batch end to end (upload -> cached
# executable -> host fetch -> output columns) and a `warm` that submits
# ahead-of-time compilations for every row bucket the engine will ever
# dispatch.  Models implement `_serving_entry(mesh)` (core._TpuModel hook);
# most build theirs through `kernel_entry` below, which wires a single
# jitted kernel into the process-wide AOT executable cache
# (ops/precompile.cached_kernel) exactly the way the batch transform paths
# of PRs 2-4 do — serving rides the same executables.
#
# The ONE bucketing rule: every flushed micro-batch is zero-padded to
# `bucket_rows(n)` — a power of two between SRML_SERVE_MIN_BUCKET and the
# batcher's max batch — so the steady state touches a handful of compiled
# geometries (all warmed at model-load time) instead of one compile per
# distinct batch length.
#

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Sequence

import numpy as np

MIN_BUCKET_ENV = "SRML_SERVE_MIN_BUCKET"
_DEFAULT_MIN_BUCKET = 16


def min_bucket() -> int:
    """Smallest serving row bucket (power of two enforced by bucket_rows'
    doubling walk; a non-pow2 setting rounds up implicitly)."""
    return max(1, int(os.environ.get(MIN_BUCKET_ENV, str(_DEFAULT_MIN_BUCKET))))


def bucket_rows(n: int, max_batch: int) -> int:
    """Power-of-two row bucket for a flushed batch of `n` valid rows —
    shared by the dispatch path and the warm path so a warmed executable is
    the exact entry the later dispatch looks up (the same contract
    ops/precompile.shape_bucket gives the batch transform paths)."""
    from ..ops.precompile import shape_bucket

    return shape_bucket(n, lo=min_bucket(), hi=max(min_bucket(), max_batch))


def serve_buckets(max_batch: int) -> List[int]:
    """Every bucket the engine can dispatch at `max_batch`: the doubling
    ladder min_bucket, 2*min_bucket, ..., bucket_rows(max_batch).  This is
    the warm set — steady state never meets a geometry outside it."""
    out, b = [], min_bucket()
    top = bucket_rows(max_batch, max_batch)
    while b < top:
        out.append(b)
        b *= 2
    out.append(top)
    return out


@dataclass
class ServingEntry:
    """One model's online-inference surface.

    `call` receives the PADDED (bucket, n_cols) float batch (pad rows are
    zeros) and returns {output column: host np array of bucket rows} — the
    engine slices to the valid row count and scatters per request.  `warm`
    submits AOT compilations for the given bucket sizes on the precompile
    worker pool and returns the submitted cache keys (possibly empty when a
    route has nothing soundly warmable — the engine then warms by
    dispatching one synthetic batch per bucket instead)."""

    name: str                 # stable kernel-cache namespace, e.g. "serve.kmeans"
    n_cols: int
    dtype: np.dtype
    out_cols: List[str]
    call: Callable[[np.ndarray], Dict[str, np.ndarray]]
    warm: Callable[[Sequence[int]], list]
    # optional extras a model wants surfaced in server stats
    info: Dict[str, Any] = field(default_factory=dict)


def kernel_entry(
    name: str,
    fn: Any,
    consts: tuple,
    statics: Dict[str, Any],
    postprocess: Callable[[Any], Dict[str, np.ndarray]],
    *,
    dtype: Any,
    n_cols: int,
    out_cols: List[str],
    info: Dict[str, Any] = None,
) -> ServingEntry:
    """ServingEntry for the common single-kernel models (kmeans/pca/linreg/
    logreg/forest): `fn` is a jitted kernel (X, *consts, **statics) -> device
    outputs, dispatched through the process-wide AOT executable cache under
    `name`; `postprocess` maps the HOST-fetched outputs to output columns
    (still at padded length — the engine slices)."""
    import jax

    from ..ops.precompile import (
        aval,
        cached_kernel,
        global_precompiler,
        kernel_cache_key,
    )

    np_dtype = np.dtype(dtype)

    def call(batch: np.ndarray) -> Dict[str, np.ndarray]:
        Xd = jax.device_put(np.ascontiguousarray(batch, dtype=np_dtype))
        out = cached_kernel(name, fn, Xd, *consts, **statics)
        return postprocess(jax.device_get(out))

    def warm(buckets: Sequence[int]) -> list:
        pc = global_precompiler()
        keys = []
        for b in buckets:
            args = (aval((int(b), n_cols), np_dtype),) + tuple(consts)
            key = kernel_cache_key(name, args, None, statics)
            pc.submit(key, fn, *args, **statics)
            keys.append(key)
        return keys

    return ServingEntry(
        name=name,
        n_cols=int(n_cols),
        dtype=np_dtype,
        out_cols=list(out_cols),
        call=call,
        warm=warm,
        info=dict(info or {}),
    )


def entry_signature(entry: "ServingEntry") -> tuple:
    """The client-visible serving contract of an entry: feature width,
    dtype, and output columns.  Two models with equal signatures are
    hot-swappable — every in-flight and future request that was valid
    against one is valid against the other."""
    return (
        int(entry.n_cols),
        str(np.dtype(entry.dtype)),
        tuple(sorted(entry.out_cols)),
    )


def check_swap_compatible(
    old: "ServingEntry", new: "ServingEntry", name: str
) -> None:
    """Raise ValueError naming every signature mismatch — the registry/
    router swap() guard.  A width or dtype change would make already-
    admitted requests dispatch garbage; an output-column change would break
    every client parsing the result dict.  Incompatible model upgrades are
    a REGISTER-under-a-new-name event, not a swap."""
    mismatches = []
    if int(old.n_cols) != int(new.n_cols):
        mismatches.append(f"n_cols {old.n_cols} -> {new.n_cols}")
    if np.dtype(old.dtype) != np.dtype(new.dtype):
        mismatches.append(f"dtype {np.dtype(old.dtype)} -> {np.dtype(new.dtype)}")
    if sorted(old.out_cols) != sorted(new.out_cols):
        mismatches.append(
            f"out_cols {sorted(old.out_cols)} -> {sorted(new.out_cols)}"
        )
    if mismatches:
        raise ValueError(
            f"swap({name!r}): incoming model is not serving-compatible "
            f"({'; '.join(mismatches)}); register it under a new name "
            "instead"
        )


def entry_for(model: Any, mesh: Any = None) -> ServingEntry:
    """The model's serving entry via its `_serving_entry` hook, with a
    uniform error for models that have no online-inference path."""
    hook = getattr(model, "_serving_entry", None)
    if hook is None:
        raise TypeError(
            f"{type(model).__name__} is not a servable model (no "
            "_serving_entry hook)"
        )
    entry = hook(mesh)
    if not isinstance(entry, ServingEntry):
        raise TypeError(
            f"{type(model).__name__}._serving_entry returned "
            f"{type(entry).__name__}, expected ServingEntry"
        )
    return entry
