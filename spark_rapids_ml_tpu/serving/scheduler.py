#
# Replica scheduling policy for the serving router (serving/router.py):
# WHICH replica takes a request, and WHETHER the request is admitted at all.
#
# The two policies are deliberately tiny, pure functions over observable
# state — no threads, no locks of their own — so the router's dispatch path
# stays one state snapshot + one comparison pass, and the policy is unit-
# testable without standing up a single replica:
#
#   ADMISSION (priority classes)  Every request carries a priority class
#     ("interactive" > "standard" > "batch").  Admission compares the
#     replica set's aggregate queue-fill fraction against a per-class
#     ceiling (SRML_SERVE_SHED_FRACTIONS, least-critical class first to
#     shed): interactive rides until the queues are hard-full, batch is
#     shed at half-full.  Load shedding therefore degrades the plane in
#     priority order instead of uniformly — the Clipper/Orca-style
#     admission control the ROADMAP's serving item calls for.
#
#   DISPATCH (least-outstanding, health-aware)  Among replicas IN ROTATION
#     (state READY), pick the one with the fewest outstanding requests —
#     the classic least-outstanding-requests balancer, which tracks real
#     per-replica speed differences (a replica slowed by a shared device
#     accumulates backlog and stops being picked).  A replica reporting
#     DEGRADED / RECOVERING / UNHEALTHY / DRAINING is OUT of rotation; when
#     *no* replica is READY the scheduler falls back to DEGRADED replicas
#     (single-replica degraded mode: an SLO-burning replica beats a hard
#     failure) before raising the typed retryable NoReplicaAvailable.
#
from __future__ import annotations

import os
from typing import Any, List, Sequence, Tuple

from .engine import DEGRADED, READY

# priority classes, most- to least-critical; index = shed order
PRIORITY_CLASSES = ("interactive", "standard", "batch")
DEFAULT_CLASS = "interactive"

SHED_FRACTIONS_ENV = "SRML_SERVE_SHED_FRACTIONS"
_DEFAULT_SHED_FRACTIONS = (1.0, 0.75, 0.5)


class NoReplicaAvailable(RuntimeError):
    """Every replica of the requested model is out of rotation (RECOVERING
    / UNHEALTHY / DRAINING, with not even a DEGRADED fallback).  Retryable:
    a supervised restart typically re-admits a replica within its sub-
    second re-warm window — callers retry with backoff rather than failing
    the client request outright."""

    retryable = True


class RequestShed(RuntimeError):
    """Admission control shed this request: the replica set's aggregate
    queue fill exceeded the request's priority-class ceiling.  Retryable
    with backoff — the queues drain at dispatch rate, and higher-priority
    traffic is deliberately still being admitted."""

    retryable = True


def shed_fractions() -> Tuple[float, ...]:
    """Per-class admission ceilings (fraction of aggregate queue depth),
    indexed like PRIORITY_CLASSES.  SRML_SERVE_SHED_FRACTIONS takes a
    comma list ("1.0,0.75,0.5"); short lists repeat their last value, junk
    falls back to the default — admission policy must never raise."""
    raw = os.environ.get(SHED_FRACTIONS_ENV, "")
    if not raw:
        return _DEFAULT_SHED_FRACTIONS
    vals: List[float] = []
    for part in raw.split(","):
        try:
            vals.append(max(0.0, min(1.0, float(part))))
        except ValueError:
            return _DEFAULT_SHED_FRACTIONS
    if not vals:
        return _DEFAULT_SHED_FRACTIONS
    while len(vals) < len(PRIORITY_CLASSES):
        vals.append(vals[-1])
    return tuple(vals[: len(PRIORITY_CLASSES)])


def class_index(priority: str) -> int:
    """Index of `priority` in PRIORITY_CLASSES; unknown classes raise (a
    typo'd class silently riding the batch ceiling would be a policy bug
    that only fires under overload — fail at submit time instead)."""
    try:
        return PRIORITY_CLASSES.index(priority)
    except ValueError:
        raise ValueError(
            f"unknown priority class {priority!r}; choose from "
            f"{PRIORITY_CLASSES}"
        ) from None


def admit(priority: str, fill_fraction: float) -> bool:
    """Admission verdict for one request: classes are admitted while the
    aggregate queue-fill fraction is UNDER their ceiling."""
    return fill_fraction < shed_fractions()[class_index(priority)]


def aggregate_fill(replicas: Sequence[Any]) -> float:
    """Aggregate queue-fill fraction over a replica set: total queued rows
    over total queue depth.  Terminal replicas (UNHEALTHY) still count in
    the denominator — their capacity is provisioned, just dark — so a
    half-dead set reads as fuller, shedding batch traffic earlier."""
    depth = sum(r.queue_depth() for r in replicas)
    if depth <= 0:
        return 1.0
    queued = sum(r.queued_rows() for r in replicas)
    return queued / depth


def aggregate_occupancy(replicas: Sequence[Any]) -> float:
    """Aggregate occupancy over a replica set: admitted-but-unresolved
    requests over total queue depth.  Where aggregate_fill counts only
    rows still WAITING in the queues (it collapses to zero the instant
    dispatch keeps up), occupancy also counts rows in flight on the
    devices, so it stays a truthful busyness signal for a set that is
    saturated but not backlogged — the autoscaler's scale-DOWN guard
    (serving/autoscale.py) and the router.<model>.occupancy gauge.  Can
    exceed 1.0 under deep continuous-batching pipelines; an empty set
    reads 0.0 (nothing is busy, unlike fill's defensive 1.0)."""
    depth = sum(r.queue_depth() for r in replicas)
    if depth <= 0:
        return 0.0
    return sum(r.outstanding() for r in replicas) / depth


def _state_of(r: Any) -> str:
    """A replica's rotation state: effective_state() (the SLO-burn-aware
    verdict) when the object offers it, plain state() otherwise."""
    fn = getattr(r, "effective_state", None)
    return fn() if fn is not None else r.state()


def pick(replicas: Sequence[Any]) -> Tuple[Any, str]:
    """Choose the dispatch target among `replicas` (objects with state()/
    effective_state() and outstanding()): least-outstanding among READY
    replicas, falling back to least-outstanding among DEGRADED ones
    (degraded mode), else raising the typed retryable NoReplicaAvailable.
    Returns (replica, mode) with mode in {"ready", "degraded"} so the
    router can count degraded-mode dispatches."""
    states = [(r, _state_of(r)) for r in replicas]
    ready = [r for r, s in states if s == READY]
    if ready:
        return min(ready, key=lambda r: r.outstanding()), "ready"
    degraded = [r for r, s in states if s == DEGRADED]
    if degraded:
        return min(degraded, key=lambda r: r.outstanding()), "degraded"
    raise NoReplicaAvailable(
        "no replica in rotation: "
        + ", ".join(f"{r.name}={s}" for r, s in states)
    )
