#
# Dynamic micro-batcher: the request path of the serving subsystem.
#
# Clients submit single rows or small feature batches from any thread; the
# engine's dispatch worker pops COALESCED batches.  Policy:
#
#   - bounded queue (SRML_SERVE_QUEUE_DEPTH rows): admission control — a
#     submit that would exceed the bound fails fast with ServerOverloaded
#     instead of growing an unbounded-latency backlog.  Overload is the
#     CALLER's signal to shed or retry; the queue never blocks producers.
#   - coalesce-until-deadline: a flush happens when the pending rows fill
#     SRML_SERVE_MAX_BATCH, or when the OLDEST pending request has waited
#     SRML_SERVE_MAX_WAIT_MS (the latency price of batching is bounded by
#     max_wait, paid only under light traffic).  Quiescent partial batches
#     therefore flush at the deadline; drain()/shutdown flush immediately.
#   - per-request deadlines: a request whose timeout expires while queued is
#     failed with RequestTimeout at batch assembly (never dispatched).
#
# Results travel back through concurrent.futures.Future: the worker scatters
# each flushed batch's output columns to its requests' futures, so a blocked
# client wakes exactly when its rows are done, not when the whole queue is.
#

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from concurrent.futures import Future, InvalidStateError

from .. import profiling, sanitize


def resolve_future(fut: "Future", result: Any = None, exc: Any = None) -> bool:
    """set_result/set_exception tolerating a concurrent client-side
    cancel(): checking fut.cancelled() first is a TOCTOU race — the cancel
    can land between the check and the set, and the resulting
    InvalidStateError must never kill the dispatch worker.  Returns whether
    the outcome was delivered."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)
        return True
    except InvalidStateError:  # cancelled (or already resolved) under us
        return False

MAX_BATCH_ENV = "SRML_SERVE_MAX_BATCH"
MAX_WAIT_ENV = "SRML_SERVE_MAX_WAIT_MS"
QUEUE_DEPTH_ENV = "SRML_SERVE_QUEUE_DEPTH"
TIMEOUT_ENV = "SRML_SERVE_TIMEOUT_MS"

_DEFAULT_MAX_BATCH = 256
_DEFAULT_MAX_WAIT_MS = 5.0
_DEFAULT_QUEUE_DEPTH = 4096  # rows


# take(cancelled=...) sentinel: a superseded consumer left without
# consuming (distinct from None = stopped-and-drained)
CANCELLED = object()


class ServerDraining(RuntimeError):
    """Raised by submit() once drain()/shutdown() has begun: this server
    generation is completing its admitted work and admitting nothing new.
    Typed (and a RuntimeError subclass, so pre-router callers that matched
    RuntimeError still do) because the router's swap path NEEDS to tell
    "this replica is leaving rotation" from a real failure: a submit that
    races the rolling-swap cut-over onto the outgoing generation must fail
    over to the incoming one, not surface to the client."""

    retryable = True


class ServerOverloaded(RuntimeError):
    """Raised by submit() when the bounded request queue is full — the
    fast-rejection half of admission control (callers shed or retry with
    backoff; queueing would only convert overload into unbounded latency)."""

    retryable = True  # with backoff — the queue drains at dispatch rate


class RequestTimeout(TimeoutError):
    """Set on a request's future when its deadline expires while queued."""

    retryable = True  # the request was never dispatched


class _Request:
    __slots__ = ("features", "n_rows", "future", "enqueue_t", "deadline_t", "lane")

    def __init__(
        self, features: np.ndarray, timeout_s: Optional[float], lane: int = 0
    ):
        self.features = features
        self.n_rows = int(features.shape[0])
        self.future: "Future[Dict[str, np.ndarray]]" = Future()
        self.enqueue_t = profiling.now()
        self.deadline_t = (
            self.enqueue_t + timeout_s if timeout_s and timeout_s > 0 else None
        )
        # srml-lanes: which lane of a multiplexed server's stacked parameter
        # buffer this request's rows score against (0 for dedicated servers
        # — the engine's assembly ignores it unless the entry takes lanes)
        self.lane = int(lane)


from ..utils import env_float as _env_float  # noqa: E402 - knob parsing


class MicroBatcher:
    """Bounded request queue + coalescing policy for ONE served model.

    Thread-safe: any number of producer threads submit; exactly one
    consumer (the engine's dispatch worker) calls take().  `counter_ns` is
    the profiling-counter namespace (e.g. "serving.kmeans")."""

    def __init__(
        self,
        n_cols: int,
        dtype: np.dtype,
        counter_ns: str,
        max_batch: Optional[int] = None,
        max_wait_ms: Optional[float] = None,
        queue_depth: Optional[int] = None,
        default_timeout_ms: Optional[float] = None,
    ):
        self.n_cols = int(n_cols)
        self.dtype = np.dtype(dtype)
        self.ns = counter_ns
        self.max_batch = int(max_batch or _env_float(MAX_BATCH_ENV, _DEFAULT_MAX_BATCH))
        self.max_wait_s = (
            max_wait_ms
            if max_wait_ms is not None
            else _env_float(MAX_WAIT_ENV, _DEFAULT_MAX_WAIT_MS)
        ) / 1000.0
        self.queue_depth = int(
            queue_depth or _env_float(QUEUE_DEPTH_ENV, _DEFAULT_QUEUE_DEPTH)
        )
        if self.max_batch < 1 or self.queue_depth < 1:
            raise ValueError("max_batch and queue_depth must be >= 1")
        self._default_timeout_s = (
            default_timeout_ms
            if default_timeout_ms is not None
            else _env_float(TIMEOUT_ENV, 0.0)
        ) / 1000.0
        self._lock = sanitize.lockdep_lock("serve.batcher.queue")
        self._nonempty = threading.Condition(self._lock)
        self._queue: "collections.deque[_Request]" = collections.deque()
        self._queued_rows = 0
        self._stopped = False
        self._draining = False
        # quiescence accounting lives under its OWN lock: futures resolve
        # from arbitrary threads — including take() failing expired requests
        # while it holds _lock — and a done-callback re-acquiring _lock
        # would self-deadlock
        self._done_lock = sanitize.lockdep_lock("serve.batcher.done")
        self._quiescent = threading.Condition(self._done_lock)
        self._outstanding = 0  # admitted requests whose future is unresolved

    def _on_done(self, _fut) -> None:
        """Future done-callback: quiescence accounting (covers set_result,
        set_exception AND client-side cancellation, so drain can never hang
        on a request that already has an outcome)."""
        with self._done_lock:
            self._outstanding -= 1
            if self._outstanding == 0:
                self._quiescent.notify_all()

    # -- producer side ------------------------------------------------------
    def submit(
        self,
        features: np.ndarray,
        timeout_ms: Optional[float] = None,
        *,
        lane: int = 0,
    ) -> "Future[Dict[str, np.ndarray]]":
        """Enqueue one request ((D,) row or (n, D) block); returns its
        future.  `lane` tags the request's rows with a multiplexed server's
        lane id (srml-lanes; dedicated servers leave the default 0).
        Raises ServerOverloaded when the queue bound would be exceeded and
        ValueError on shape mismatch or oversized requests."""
        feats = np.asarray(features, dtype=self.dtype)
        if feats.ndim == 1:
            feats = feats[None, :]
        if feats.ndim != 2 or feats.shape[1] != self.n_cols:
            raise ValueError(
                f"request features must be ({self.n_cols},) or "
                f"(n, {self.n_cols}); got shape {np.asarray(features).shape}"
            )
        if feats.shape[0] == 0:
            raise ValueError("empty request (0 rows)")
        if feats.shape[0] > self.max_batch:
            raise ValueError(
                f"request of {feats.shape[0]} rows exceeds max_batch="
                f"{self.max_batch}; split it client-side (bulk scoring "
                "belongs on the batch transform path)"
            )
        timeout_s = (
            timeout_ms / 1000.0 if timeout_ms is not None else self._default_timeout_s
        )
        req = _Request(feats, timeout_s, lane)
        with self._lock:
            if self._stopped or self._draining:
                raise ServerDraining(
                    f"server {self.ns!r} is draining/shut down; "
                    "resubmit to its successor"
                )
            if self._queued_rows + req.n_rows > self.queue_depth:
                profiling.incr_counter(f"{self.ns}.rejected")
                raise ServerOverloaded(
                    f"{self.ns}: queue full ({self._queued_rows} rows "
                    f"queued, depth {self.queue_depth}); retry with backoff"
                )
            self._queue.append(req)
            self._queued_rows += req.n_rows
            # inside the admission critical section (nested _done_lock; the
            # done-callback only ever takes _done_lock, so no inversion):
            # incrementing after releasing _lock would let a concurrent
            # drain() see outstanding == 0 while this request sits queued
            with self._done_lock:
                self._outstanding += 1
            profiling.incr_counter(f"{self.ns}.requests")
            profiling.incr_counter(f"{self.ns}.rows", req.n_rows)
            self._nonempty.notify()
        # registered AFTER the increment on this thread: a future that
        # already resolved runs the callback inline, keeping the balance
        req.future.add_done_callback(self._on_done)
        return req.future

    # -- consumer side ------------------------------------------------------
    def take(
        self, cancelled=None, hold=None
    ) -> Optional[Tuple[List[_Request], str]]:
        """Block until a batch is ready under the coalescing policy; returns
        (requests, flush_reason) with at least one live request, or None
        when the batcher is stopped and drained.  Expired requests are
        failed here and never returned.

        `cancelled` (optional zero-arg predicate) is the SUPERSEDED-
        CONSUMER exit: a depth>1 assembly thread parks INSIDE take(), so
        when a recovery hands the batcher to a new worker generation the
        stale consumer must leave WITHOUT consuming a request the new
        generation owns.  When the predicate turns true, take() returns
        the CANCELLED sentinel at the next wait re-check, having popped
        nothing.

        `hold` (optional zero-arg predicate) is ITERATION-LEVEL continuous
        batching: while it returns True (the depth>1 staging slot is still
        occupied, i.e. the device has not consumed the previously staged
        batch), a deadline-expired partial batch stays OPEN to late
        arrivals instead of flushing — closing it early cannot make it
        dispatch sooner (a staged batch is already ahead of it) but would
        freeze its occupancy below max_batch.  Full/drain/stop flushes
        ignore `hold`; the consumer wakes promptly via kick() when the
        slot frees."""
        with self._lock:
            while True:
                while not self._queue and not self._stopped:
                    # bounded wait (graftlint R9): re-checking the predicate
                    # once a second costs nothing and means a lost notify —
                    # or a recovery path that swapped consumers — can never
                    # park this worker forever
                    if cancelled is not None and cancelled():
                        return CANCELLED
                    self._nonempty.wait(timeout=1.0)
                if cancelled is not None and cancelled():
                    return CANCELLED  # queued work belongs to the successor
                if not self._queue:
                    return None  # stopped and drained
                # coalesce-until-deadline, anchored at the OLDEST request:
                # its wait bounds the batching latency everyone else rides
                deadline = self._queue[0].enqueue_t + self.max_wait_s
                while True:
                    rows = sum(r.n_rows for r in self._queue)
                    if rows >= self.max_batch or self._draining or self._stopped:
                        reason = "full" if rows >= self.max_batch else "drain"
                        break
                    remaining = deadline - profiling.now()
                    if remaining <= 0:
                        if hold is None or not hold():
                            reason = "deadline"
                            break
                        # past the deadline but held: the staging slot is
                        # occupied, so keep coalescing — kick() (or the next
                        # submit) wakes this wait the moment that changes
                        profiling.incr_counter(f"{self.ns}.held_open")
                        remaining = 1.0
                    # bounded like the outer wait, so a consumer superseded
                    # mid-coalesce notices within a second even when no
                    # producer ever notifies again
                    self._nonempty.wait(min(remaining, 1.0))
                    if cancelled is not None and cancelled():
                        return CANCELLED
                    if not self._queue:
                        break  # everything expired/cancelled under us
                if not self._queue:
                    continue
                batch: List[_Request] = []
                taken_rows = 0
                now = profiling.now()
                while self._queue:
                    req = self._queue[0]
                    if req.deadline_t is not None and now > req.deadline_t:
                        self._queue.popleft()
                        self._queued_rows -= req.n_rows
                        profiling.incr_counter(f"{self.ns}.timeouts")
                        resolve_future(
                            req.future,
                            exc=RequestTimeout(
                                f"{self.ns}: request expired after "
                                f"{(now - req.enqueue_t) * 1e3:.1f} ms in queue"
                            ),
                        )
                        continue
                    if taken_rows + req.n_rows > self.max_batch:
                        break  # next request starts the following batch
                    self._queue.popleft()
                    self._queued_rows -= req.n_rows
                    taken_rows += req.n_rows
                    batch.append(req)
                if not batch:
                    continue  # all expired — wait for fresh traffic
                profiling.incr_counter(f"{self.ns}.batches")
                profiling.incr_counter(f"{self.ns}.flush_{reason}")
                if len(batch) > 1:
                    profiling.incr_counter(f"{self.ns}.coalesced_batches")
                return batch, reason

    def kick(self) -> None:
        """Wake a take() parked under `hold`: the depth>1 dispatcher calls
        this right after popping the staged batch, so a deadline-expired
        held batch flushes within one lock handoff of the slot freeing
        instead of one bounded-wait interval later."""
        with self._lock:
            self._nonempty.notify_all()

    # -- lifecycle ----------------------------------------------------------
    def queued_rows(self) -> int:
        with self._lock:
            return self._queued_rows

    def queued_requests(self) -> int:
        with self._lock:
            return len(self._queue)

    def outstanding(self) -> int:
        """Admitted requests whose future has not resolved yet (queued OR
        inside the in-flight dispatch)."""
        with self._done_lock:
            return self._outstanding

    def wait_quiescent(self, timeout_s: Optional[float] = None) -> bool:
        """Block until every admitted request has an outcome; True on
        quiescence, False on timeout."""
        deadline = (
            profiling.now() + timeout_s if timeout_s is not None else None
        )
        with self._done_lock:
            while self._outstanding > 0:
                remaining = None
                if deadline is not None:
                    remaining = deadline - profiling.now()
                    if remaining <= 0:
                        return False
                self._quiescent.wait(remaining)
            return True

    def fail_pending(self, exc: Exception) -> int:
        """Pop EVERY queued request and resolve its future with `exc` — the
        srml-shield recovery shed: queued work gets a typed retryable error
        the moment the supervisor restarts the worker, instead of waiting
        out a dead consumer.  Admission stays open (the recovered worker
        serves new traffic); returns the number of requests failed."""
        with self._lock:
            popped = list(self._queue)
            self._queue.clear()
            self._queued_rows = 0
        n = 0
        for req in popped:
            if resolve_future(req.future, exc=exc):
                n += 1
        return n

    def begin_drain(self) -> None:
        """Stop admitting; pending batches flush immediately (the worker's
        take() stops waiting for deadlines)."""
        with self._lock:
            self._draining = True
            self._nonempty.notify_all()

    def stop(self) -> None:
        """Stop admitting AND wake the consumer for exit; queued requests
        still flush (take() returns them until the queue is empty)."""
        with self._lock:
            self._stopped = True
            self._nonempty.notify_all()
