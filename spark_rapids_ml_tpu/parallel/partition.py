#
# Partition metadata shared by all ranks before a distributed fit.
#
# Behavioral analog of the reference's PartitionDescriptor
# (/root/reference/python/src/spark_rapids_ml/utils.py:133-196), which
# allGathers per-rank partition sizes over the Spark barrier control plane.
# Single-controller fits build it locally (one rank owns every partition);
# multi-controller fits use `gather`, which exchanges sizes over the
# runner's control plane exactly like the reference.
#

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, List


@dataclass
class PartitionDescriptor:
    """m: total rows, n: cols, rank: this worker, parts_rank_size: flat list of
    (rank, size) for every partition in rank order."""

    m: int
    n: int
    rank: int
    parts_rank_size: List[tuple] = field(default_factory=list)
    # per-rank extra payloads gathered alongside the sizes (rank order);
    # empty when built single-controller
    extras: List[dict] = field(default_factory=list)

    @classmethod
    def build(cls, partition_rows: List[int], total_cols: int, rank: int = 0) -> "PartitionDescriptor":
        """Single-controller constructor: partitions map 1:1 to mesh shards,
        so each is tagged with its own index (no control plane needed)."""
        parts = [(r, size) for r, size in enumerate(partition_rows)]
        return cls(
            m=sum(partition_rows), n=total_cols, rank=rank, parts_rank_size=parts
        )

    @classmethod
    def gather(
        cls,
        partition_rows: List[int],
        n_cols: int,
        rank: int,
        nranks: int,
        control_plane: Any,
        extra: dict = None,
    ) -> "PartitionDescriptor":
        """Multi-controller constructor: allGather every rank's partition
        sizes (and column count) over the control plane, mirroring the
        reference's PartitionDescriptor.build allGather (utils.py:178-196).

        A rank with no data reports n_cols=0; the global column count is the
        consensus of data-bearing ranks (disagreement raises).  `extra` is an
        optional JSON-safe dict gathered alongside and exposed per rank via
        `.extras` (the reference piggybacks extra metadata on the same
        allGather, e.g. knn.py:526-537)."""
        msg = json.dumps(
            {
                "rank": rank,
                "rows": partition_rows,
                "n_cols": n_cols,
                "extra": extra or {},
            }
        )
        gathered = sorted(
            (json.loads(m) for m in control_plane.allGather(msg)),
            key=lambda g: g["rank"],
        )
        if [g["rank"] for g in gathered] != list(range(nranks)):
            raise RuntimeError(
                f"partition allGather returned ranks "
                f"{[g['rank'] for g in gathered]}, expected 0..{nranks - 1}"
            )
        widths = {g["n_cols"] for g in gathered if g["n_cols"] > 0}
        if len(widths) > 1:
            raise ValueError(f"ranks disagree on feature width: {sorted(widths)}")
        parts = [(g["rank"], size) for g in gathered for size in g["rows"]]
        return cls(
            m=sum(s for _, s in parts),
            n=widths.pop() if widths else 0,
            rank=rank,
            parts_rank_size=parts,
            extras=[g.get("extra", {}) for g in gathered],
        )

    def rank_rows(self, rank: int) -> int:
        """Total rows held by `rank` across its partitions."""
        return sum(s for r, s in self.parts_rank_size if r == rank)
