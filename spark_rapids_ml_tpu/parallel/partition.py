#
# Partition metadata shared by all ranks before a distributed fit.
#
# Behavioral analog of the reference's PartitionDescriptor
# (/root/reference/python/src/spark_rapids_ml/utils.py:133-196), which
# allGathers per-rank partition sizes over the Spark barrier control plane.
# In the TPU build the "ranks" are mesh shards; sizes are known locally in
# single-controller mode and allGathered over the runner's control plane in
# multi-controller mode (see runtime/spark adapter).
#

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List


@dataclass
class PartitionDescriptor:
    """m: total rows, n: cols, rank: this worker, parts_rank_size: flat list of
    (rank, size) for every partition in rank order."""

    m: int
    n: int
    rank: int
    parts_rank_size: List[tuple] = field(default_factory=list)

    @classmethod
    def build(cls, partition_rows: List[int], total_cols: int, rank: int = 0) -> "PartitionDescriptor":
        parts = [(r, size) for r, size in enumerate(partition_rows)]
        return cls(
            m=sum(partition_rows), n=total_cols, rank=rank, parts_rank_size=parts
        )
