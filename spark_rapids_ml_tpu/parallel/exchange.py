#
# Length-prefixed binary array codec + bulk collectives over a string (or
# bytes-capable) control plane.
#
# TPU-native stand-in for the reference's UCX data-plane transfers inside
# NearestNeighborsMG (knn.py:452-560, cuml_context.py:99-146): where cuML
# ships query blocks and per-rank (Q, k) candidate lists as binary UCX
# frames point-to-point, this module frames ndarrays into length-prefixed
# binary payloads and moves them over whatever allGather the cluster offers
# (Spark's BarrierTaskContext RPC, the shared-FS FileControlPlane, or an
# in-process mock).
#
# Why not JSON+base64 per array (the round-4 transport): at reference scale
# (Q=1M, k=200, 8 ranks) round 2 of distributed_kneighbors made every rank
# parse ~8 x 2.4 GB of base64-JSON it mostly discarded.  Here
# (a) arrays ride one binary frame — no JSON parse, no per-array base64 on
#     bytes-capable planes, and
# (b) alltoall_bytes frames chunks PER DESTINATION, so a receiver only
#     materializes (base64-decodes + joins + unpacks) the chunks addressed
#     to it: per-rank decode volume is O(own share), matching the p2p shape
#     of the reference exchange even though a broadcast allGather carries
#     the wire bytes underneath.
#
# Every helper is a COLLECTIVE: all ranks must call it the same number of
# times, empty payloads included (a bailing rank would hang the barrier).
#

from __future__ import annotations

import base64
import contextlib
import json
import struct
from typing import Any, Iterator, List, Optional, Sequence

import numpy as np

from .. import profiling

# per-frame chunk bound: Spark's allGather rides the RPC channel
# (spark.rpc.message.maxSize default 128 MiB); 8 MiB keeps each frame far
# under the limit with base64 overhead (same bound as knn._allgather_large)
CHUNK_BYTES = 8 << 20

_MAGIC = b"SRX1"


# -- the ONE collective reporting wrapper -------------------------------------
# Every exchange primitive — host control-plane collective or in-mesh device
# collective — reports through section(): uniform `exchange.<name>.bytes` /
# `exchange.<name>.time_ns` / `exchange.<name>.calls` process counters plus a
# hierarchical span named `exchange.<name>` (srml-scope), so per-section
# byte/time accounting is one namespace regardless of which idiom moved the
# data (the first concrete step of ROADMAP item 5's unified comms layer).
#
# Host sections measure wall clock.  Device sections (psum_parts,
# allgather_rows, psum_merge_parts) run at TRACE time inside shard_map
# bodies, where wall clock is meaningless — they report the STATIC payload
# bytes of the traced shapes plus a trace count, and wrap the collective in
# jax.named_scope so the section shows up by name in xprof/HLO instead.
# Counters therefore move once per compiled geometry for device sections and
# once per call for host sections; docs/observability.md spells this out.


@contextlib.contextmanager
def section(name: str, nbytes: Optional[int] = None) -> Iterator[None]:
    """Host-side collective section: span + byte/time/call counters."""
    full = f"exchange.{name}"
    t0 = profiling.now()
    with profiling.span(full, **({"bytes": int(nbytes)} if nbytes else {})):
        yield
    dt = profiling.now() - t0
    profiling.incr_counter(f"{full}.calls")
    profiling.incr_counter(f"{full}.time_ns", int(dt * 1e9))
    if nbytes:
        profiling.incr_counter(f"{full}.bytes", int(nbytes))


def _static_nbytes(*arrays: Any) -> int:
    """Payload bytes of traced (or concrete) arrays from their STATIC
    shape/dtype — safe on tracers inside shard_map bodies."""
    total = 0
    for a in arrays:
        n = 1
        for s in a.shape:
            n *= int(s)
        total += n * np.dtype(a.dtype).itemsize
    return total


def device_section(name: str, *arrays: Any):
    """Device-side collective section: called at trace time inside a
    shard_map body.  Records the static payload bytes + a trace count and
    returns a jax.named_scope so the section is named in device traces
    (wall-clock for device sections lives in the xprof timeline, not the
    host counters)."""
    import jax

    full = f"exchange.{name}"
    profiling.incr_counter(f"{full}.traces")
    profiling.incr_counter(f"{full}.bytes", _static_nbytes(*arrays))
    return jax.named_scope(full)


def pack_arrays(arrays: Sequence[np.ndarray]) -> bytes:
    """One binary frame: magic, array count, then per array a dtype/shape
    header followed by the raw C-order buffer.  No base64, no JSON."""
    parts = [_MAGIC, struct.pack("<I", len(arrays))]
    bufs = []
    for a in arrays:
        a = np.asarray(a)
        if not a.flags.c_contiguous:
            # (ascontiguousarray would also promote 0-dim to 1-dim)
            a = np.ascontiguousarray(a)
        dt = a.dtype.str.encode("ascii")  # e.g. b'<f4' — endian-explicit
        parts.append(struct.pack("<B", len(dt)))
        parts.append(dt)
        parts.append(struct.pack("<B", a.ndim))
        parts.append(struct.pack(f"<{a.ndim}q", *a.shape))
        parts.append(struct.pack("<q", a.nbytes))
        bufs.append(a.tobytes())
    return b"".join(parts) + b"".join(bufs)


def unpack_arrays(buf: bytes) -> List[np.ndarray]:
    if buf[:4] != _MAGIC:
        raise ValueError("not an SRX1 frame")
    (count,) = struct.unpack_from("<I", buf, 4)
    off = 8
    metas = []
    for _ in range(count):
        (dl,) = struct.unpack_from("<B", buf, off)
        off += 1
        dt = np.dtype(buf[off : off + dl].decode("ascii"))
        off += dl
        (nd,) = struct.unpack_from("<B", buf, off)
        off += 1
        shape = struct.unpack_from(f"<{nd}q", buf, off)
        off += 8 * nd
        (nb,) = struct.unpack_from("<q", buf, off)
        off += 8
        metas.append((dt, shape, nb))
    out = []
    for dt, shape, nb in metas:
        out.append(
            np.frombuffer(buf, dtype=dt, count=nb // dt.itemsize, offset=off)
            .reshape(shape)
            .copy()
        )
        off += nb
    return out


def _chunks(payload: bytes, chunk: int) -> List[bytes]:
    return [payload[i : i + chunk] for i in range(0, len(payload), chunk)] or [
        b""
    ]


def _send(cp: Any, data: bytes, use_bytes: bool) -> List[Any]:
    if use_bytes:
        return cp.allGatherBytes(data)
    return cp.allGather(base64.b64encode(data).decode("ascii"))


def _recv(frame: Any, use_bytes: bool) -> bytes:
    if use_bytes:
        return frame
    out = base64.b64decode(frame)
    return out


def allgather_bytes(
    cp: Any, payload: bytes, chunk: int = CHUNK_BYTES
) -> List[bytes]:
    """Broadcast allGather of one binary payload per rank (every receiver
    materializes every rank's payload — use for data all sides need, e.g.
    the query broadcast).  Chunked under the transport frame limit.
    Wall-clock and payload bytes land in the "exchange.allgather" section
    (span + counters) so control-plane time is separable from device compute
    in fit reports and telemetry snapshots."""
    with section("allgather", nbytes=len(payload)):
        use_bytes = hasattr(cp, "allGatherBytes")
        mine = _chunks(payload, chunk)
        counts = [int(c) for c in cp.allGather(str(len(mine)))]
        parts: List[List[bytes]] = [[] for _ in counts]
        for r in range(max(counts)):
            got = _send(cp, mine[r] if r < len(mine) else b"", use_bytes)
            for s, g in enumerate(got):
                if r < counts[s]:
                    parts[s].append(_recv(g, use_bytes))
        return [b"".join(p) for p in parts]


# -- device-side collectives ---------------------------------------------------
# The helpers above move HOST bytes over whatever allGather the cluster
# control plane offers.  allgather_rows is their IN-MESH analog for code
# running inside shard_map bodies (a jax collective over ICI/DCN): the UMAP
# layout engine combines per-device head-block updates with one tiled
# all-gather per epoch, the same "partial result per rank -> full result
# everywhere" shape allgather_bytes gives the host planes.  Kept here so
# every exchange primitive — host or device — lives in one module.


def allgather_rows(x, axis_name: str = None):
    """Concatenate per-device row blocks along axis 0 (lax.all_gather,
    tiled).  Call ONLY inside a shard_map body bound over `axis_name`."""
    import jax

    from .mesh import DATA_AXIS

    with device_section("allgather_rows", x):
        return jax.lax.all_gather(
            x, axis_name or DATA_AXIS, axis=0, tiled=True
        )


def psum_parts(x, axis_name: str = None):
    """Element-wise sum of per-device partial arrays (lax.psum) — the
    "partial result per shard -> full result everywhere" reduction shape of
    the forest engine's histogram combine: each device builds per-node
    histograms over ITS row shard and one psum per level yields the global
    histograms replicated on every device (ops/forest._forest_block_kernel,
    ops/forest_hist.node_histograms_sharded).  Call ONLY inside a shard_map
    body bound over `axis_name`."""
    import jax

    from .mesh import DATA_AXIS

    with device_section("psum_parts", *jax.tree_util.tree_leaves(x)):
        return jax.lax.psum(x, axis_name or DATA_AXIS)


def psum_merge_parts(x, axis_name: str = None):
    """Stack per-device candidate blocks into one (n_dev, ...) slab via a
    single psum — the IVF-Flat probed search's ONE cross-shard collective
    (ops-level: each shard scatters its local top-k into its slot of a
    zeros slab; the psum leaves the full slab replicated everywhere).
    Bitwise-safe as a gather: every slab element receives exactly one
    shard's value plus zeros, and x + 0.0 is exact for the finite/+inf
    distances and int32 positions the merge carries (no -0.0, no NaN by
    construction).  Call ONLY inside a shard_map body bound over
    `axis_name`."""
    import jax
    import jax.numpy as jnp

    from .mesh import DATA_AXIS

    axis = axis_name or DATA_AXIS
    with device_section("psum_merge_parts", x):
        n_dev = jax.lax.psum(1, axis)
        idx = jax.lax.axis_index(axis)
        slab = jnp.zeros((n_dev,) + x.shape, x.dtype).at[idx].set(x)
        return jax.lax.psum(slab, axis)


def alltoall_bytes(
    cp: Any,
    rank: int,
    nranks: int,
    dests: Sequence[bytes],
    chunk: int = CHUNK_BYTES,
) -> List[bytes]:
    """All-to-all of per-destination binary payloads: rank s passes
    dests[d] for every destination d and receives the nranks payloads
    addressed to IT (result[s] = what rank s sent to this rank).

    The wire rides the broadcast allGather (the only collective a Spark
    barrier offers), but chunks are framed dest-major with a counts
    round first, so a receiver b64-decodes/joins ONLY the chunk rounds
    addressed to it and drops the rest by reference — per-rank decode
    volume is O(own share), the p2p shape of the reference's UCX return
    (knn.py:549-560: each query partition's results land only on their
    owning rank)."""
    if len(dests) != nranks:
        raise ValueError(f"need {nranks} destination payloads, got {len(dests)}")
    with section("alltoall", nbytes=sum(len(d) for d in dests)):
        use_bytes = hasattr(cp, "allGatherBytes")
        frames = [_chunks(d, chunk) for d in dests]
        meta = json.dumps([len(f) for f in frames])
        all_meta = [json.loads(s) for s in cp.allGather(meta)]  # [src][dest]
        # canonical send order: dest-major concatenation of each source's
        # chunks
        my_seq = [c for f in frames for c in f]
        # position range of (src -> me) chunks inside src's send sequence
        lo = [sum(all_meta[s][:rank]) for s in range(nranks)]
        hi = [lo[s] + all_meta[s][rank] for s in range(nranks)]
        rounds = max(sum(m) for m in all_meta)
        mine: List[List[bytes]] = [[] for _ in range(nranks)]
        for r in range(rounds):
            got = _send(cp, my_seq[r] if r < len(my_seq) else b"", use_bytes)
            for s in range(nranks):
                if lo[s] <= r < hi[s]:
                    mine[s].append(_recv(got[s], use_bytes))
        return [b"".join(p) for p in mine]
