#
# Length-prefixed binary array codec + bulk collectives over a string (or
# bytes-capable) control plane.
#
# TPU-native stand-in for the reference's UCX data-plane transfers inside
# NearestNeighborsMG (knn.py:452-560, cuml_context.py:99-146): where cuML
# ships query blocks and per-rank (Q, k) candidate lists as binary UCX
# frames point-to-point, this module frames ndarrays into length-prefixed
# binary payloads and moves them over whatever allGather the cluster offers
# (Spark's BarrierTaskContext RPC, the shared-FS FileControlPlane, or an
# in-process mock).
#
# Why not JSON+base64 per array (the round-4 transport): at reference scale
# (Q=1M, k=200, 8 ranks) round 2 of distributed_kneighbors made every rank
# parse ~8 x 2.4 GB of base64-JSON it mostly discarded.  Here
# (a) arrays ride one binary frame — no JSON parse, no per-array base64 on
#     bytes-capable planes, and
# (b) alltoall_bytes frames chunks PER DESTINATION, so a receiver only
#     materializes (base64-decodes + joins + unpacks) the chunks addressed
#     to it: per-rank decode volume is O(own share), matching the p2p shape
#     of the reference exchange even though a broadcast allGather carries
#     the wire bytes underneath.
#
# Every helper is a COLLECTIVE: all ranks must call it the same number of
# times, empty payloads included (a bailing rank would hang the barrier).
#

from __future__ import annotations

import base64
import contextlib
import json
import struct
from typing import Any, Iterator, List, Optional, Sequence

import numpy as np

from .. import profiling
from . import faults

# per-frame chunk bound: Spark's allGather rides the RPC channel
# (spark.rpc.message.maxSize default 128 MiB); 8 MiB keeps each frame far
# under the limit with base64 overhead (same bound as knn._allgather_large)
CHUNK_BYTES = 8 << 20

_MAGIC = b"SRX1"


# -- the ONE collective reporting wrapper -------------------------------------
# Every exchange primitive — host control-plane collective or in-mesh device
# collective — reports through section(): uniform `exchange.<name>.bytes` /
# `exchange.<name>.time_ns` / `exchange.<name>.calls` process counters plus a
# hierarchical span named `exchange.<name>` (srml-scope), so per-section
# byte/time accounting is one namespace regardless of which idiom moved the
# data (the first concrete step of ROADMAP item 5's unified comms layer).
#
# Host sections measure wall clock.  Device sections (psum_parts,
# allgather_rows, psum_merge_parts) run at TRACE time inside shard_map
# bodies, where wall clock is meaningless — they report the STATIC payload
# bytes of the traced shapes plus a trace count, and wrap the collective in
# jax.named_scope so the section shows up by name in xprof/HLO instead.
# Counters therefore move once per compiled geometry for device sections and
# once per call for host sections; docs/observability.md spells this out.


@contextlib.contextmanager
def section(name: str, nbytes: Optional[int] = None) -> Iterator[None]:
    """Host-side collective section: span + byte/time/call counters."""
    full = f"exchange.{name}"
    t0 = profiling.now()
    with profiling.span(full, **({"bytes": int(nbytes)} if nbytes else {})):
        yield
    dt = profiling.now() - t0
    profiling.incr_counter(f"{full}.calls")
    profiling.incr_counter(f"{full}.time_ns", int(dt * 1e9))
    if nbytes:
        profiling.incr_counter(f"{full}.bytes", int(nbytes))


def _static_nbytes(*arrays: Any) -> int:
    """Payload bytes of traced (or concrete) arrays from their STATIC
    shape/dtype — safe on tracers inside shard_map bodies."""
    total = 0
    for a in arrays:
        n = 1
        for s in a.shape:
            n *= int(s)
        total += n * np.dtype(a.dtype).itemsize
    return total


def device_section(name: str, *arrays: Any):
    """Device-side collective section: called at trace time inside a
    shard_map body.  Records the static payload bytes + a trace count and
    returns a jax.named_scope so the section is named in device traces
    (wall-clock for device sections lives in the xprof timeline, not the
    host counters)."""
    import jax

    full = f"exchange.{name}"
    profiling.incr_counter(f"{full}.traces")
    profiling.incr_counter(f"{full}.bytes", _static_nbytes(*arrays))
    return jax.named_scope(full)


def _record_link_bytes(name: str, ici: int, dcn: int) -> None:
    """Per-link split counters, ADDITIVE to the legacy `.bytes` total:
    `exchange.<name>.ici_bytes` / `.dcn_bytes` are whole-mesh byte MODELS
    of the schedule that traced (topology.link_split_*), where `.bytes`
    stays the per-shard payload.  The `_bytes` suffix keeps them out of
    byte_totals()'s `.bytes` scan; link_totals() rolls them up."""
    if ici:
        profiling.incr_counter(f"exchange.{name}.ici_bytes", int(ici))
    if dcn:
        profiling.incr_counter(f"exchange.{name}.dcn_bytes", int(dcn))


def pack_arrays(arrays: Sequence[np.ndarray]) -> bytes:
    """One binary frame: magic, array count, then per array a dtype/shape
    header followed by the raw C-order buffer.  No base64, no JSON."""
    parts = [_MAGIC, struct.pack("<I", len(arrays))]
    bufs = []
    for a in arrays:
        a = np.asarray(a)
        if not a.flags.c_contiguous:
            # (ascontiguousarray would also promote 0-dim to 1-dim)
            a = np.ascontiguousarray(a)
        dt = a.dtype.str.encode("ascii")  # e.g. b'<f4' — endian-explicit
        parts.append(struct.pack("<B", len(dt)))
        parts.append(dt)
        parts.append(struct.pack("<B", a.ndim))
        parts.append(struct.pack(f"<{a.ndim}q", *a.shape))
        parts.append(struct.pack("<q", a.nbytes))
        bufs.append(a.tobytes())
    return b"".join(parts) + b"".join(bufs)


def unpack_arrays(buf: bytes) -> List[np.ndarray]:
    if buf[:4] != _MAGIC:
        raise ValueError("not an SRX1 frame")
    (count,) = struct.unpack_from("<I", buf, 4)
    off = 8
    metas = []
    for _ in range(count):
        (dl,) = struct.unpack_from("<B", buf, off)
        off += 1
        dt = np.dtype(buf[off : off + dl].decode("ascii"))
        off += dl
        (nd,) = struct.unpack_from("<B", buf, off)
        off += 1
        shape = struct.unpack_from(f"<{nd}q", buf, off)
        off += 8 * nd
        (nb,) = struct.unpack_from("<q", buf, off)
        off += 8
        metas.append((dt, shape, nb))
    out = []
    for dt, shape, nb in metas:
        out.append(
            np.frombuffer(buf, dtype=dt, count=nb // dt.itemsize, offset=off)
            .reshape(shape)
            .copy()
        )
        off += nb
    return out


def _chunks(payload: bytes, chunk: int) -> List[bytes]:
    return [payload[i : i + chunk] for i in range(0, len(payload), chunk)] or [
        b""
    ]


def _send(cp: Any, data: bytes, use_bytes: bool) -> List[Any]:
    if use_bytes:
        return cp.allGatherBytes(data)
    return cp.allGather(base64.b64encode(data).decode("ascii"))


def _recv(frame: Any, use_bytes: bool) -> bytes:
    if use_bytes:
        return frame
    out = base64.b64decode(frame)
    return out


def allgather_bytes(
    cp: Any, payload: bytes, chunk: int = CHUNK_BYTES
) -> List[bytes]:
    """Broadcast allGather of one binary payload per rank (every receiver
    materializes every rank's payload — use for data all sides need, e.g.
    the query broadcast).  Chunked under the transport frame limit.
    Wall-clock and payload bytes land in the "exchange.allgather" section
    (span + counters) so control-plane time is separable from device compute
    in fit reports and telemetry snapshots."""
    with section("allgather", nbytes=len(payload)):
        use_bytes = hasattr(cp, "allGatherBytes")
        mine = _chunks(payload, chunk)
        counts = [int(c) for c in cp.allGather(str(len(mine)))]
        parts: List[List[bytes]] = [[] for _ in counts]
        for r in range(max(counts)):
            got = _send(cp, mine[r] if r < len(mine) else b"", use_bytes)
            for s, g in enumerate(got):
                if r < counts[s]:
                    parts[s].append(_recv(g, use_bytes))
        return [b"".join(p) for p in parts]


# -- device-side collectives: typed sections -----------------------------------
# The helpers above move HOST bytes over whatever allGather the cluster
# control plane offers.  DeviceSection is their IN-MESH analog for code
# running inside shard_map bodies (jax collectives over ICI/DCN), as TYPED
# SECTIONS: every engine names its call site (`device_collective("umap.
# layout_rows")`, `device_collective("knn.ring_q")`, ...) and gets the same
# uniform `exchange.<name>.bytes/traces` counters regardless of which idiom
# moved the data — the consolidated comms layer of ROADMAP item 5.  The
# legacy module-level functions (allgather_rows/psum_parts/psum_merge_parts)
# remain as un-named-section shims over the same implementations.
#
# ring_shift is the one NEW idiom: a +shift neighbor permute along the mesh
# ring.  On TPU hardware it lowers to a Pallas `pltpu.make_async_remote_copy`
# kernel (neighbor-to-neighbor ICI DMA, the SNIPPETS.md exemplar) — the ONLY
# module allowed to touch the remote-DMA API (graftlint R8).  Every other
# backend (XLA:CPU meshes, interpret mode, remote-DMA disabled via
# SRML_EXCHANGE_REMOTE_DMA=0) takes the identical-semantics lax.ppermute
# fallback, which is what the tier-1 parity gates run everywhere.


class DeviceSection:
    """Typed handle for one named in-mesh collective section.  Construct
    via device_collective(name[, topo]); every method must be called ONLY
    inside a shard_map body bound over `axis_name` (default DATA_AXIS).

    With a hierarchical `topology.TopologyMap` attached, the gather-class
    collectives run the two-level schedule (gather within the host group,
    ONE gateway exchange across groups, broadcast back within the group)
    and ring_shift follows the gateway-aware cycle; every method also
    splits its modeled traffic into `.ici_bytes`/`.dcn_bytes`.  The map is
    STATIC data — callers must carry it in their jit/cache keys (the kNN
    kernels pass it through kernel_cache_key statics), never read it from
    the environment at trace time."""

    __slots__ = ("name", "topo")

    def __init__(self, name: str, topo=None):
        self.name = name
        self.topo = topo

    def _resolved(self, n_dev: int):
        """The attached map when it matches this mesh's axis size, else
        the trivial flat map (a mismatched map would mis-schedule; the
        kNN dispatch derives per-mesh so this only guards foreign
        reuse)."""
        from . import topology

        if self.topo is not None and self.topo.n_devices == int(n_dev):
            return self.topo
        return topology.flat_topology(int(n_dev))

    def _hier_slab(self, x, axis: str, topo):
        """The (n_dev, ...) all-shards slab via the two-level schedule:
        gather within the host group (ICI), scatter the group's blocks
        into a zeros slab on the GATEWAY only, then ONE full-axis psum so
        each group's slab-part crosses DCN once and lands replicated
        (which also keeps shard_map's replication inference sound —
        grouped gathers alone are opaque to it).  Every slab element is
        one shard's value plus zeros exactly like the flat zeros-slab
        psum, so the result is BITWISE equal to the flat schedule."""
        import jax
        import jax.numpy as jnp

        gmat = jnp.asarray(np.asarray(topo.groups, dtype=np.int32))
        gof = jnp.asarray(np.asarray(topo.group_of, dtype=np.int32))
        gate = jnp.asarray(np.asarray(topo.gateways, dtype=np.int32))
        idx = jax.lax.axis_index(axis)
        gid = jnp.take(gof, idx)
        intra = jax.lax.all_gather(
            x, axis, axis_index_groups=[list(g) for g in topo.groups]
        )
        rows = jnp.take(gmat, gid, axis=0)
        slab = (
            jnp.zeros((topo.n_devices,) + x.shape, x.dtype)
            .at[rows].set(intra)
        )
        part = jnp.where(
            (idx == jnp.take(gate, gid)).reshape((1,) * slab.ndim),
            slab,
            jnp.zeros_like(slab),
        )
        return jax.lax.psum(part, axis)

    def allgather_rows(self, x, axis_name: str = None):
        """Concatenate per-device row blocks along axis 0 (tiled)."""
        import jax

        from . import topology
        from .mesh import DATA_AXIS

        axis = axis_name or DATA_AXIS
        with device_section(self.name, x):
            n_dev = jax.lax.psum(1, axis)
            topo = self._resolved(n_dev)
            _record_link_bytes(
                self.name, *topology.link_split_gather(topo, _static_nbytes(x))
            )
            if topo.is_hierarchical:
                slab = self._hier_slab(x, axis, topo)
                return slab.reshape((n_dev * x.shape[0],) + x.shape[1:])
            return jax.lax.all_gather(x, axis, axis=0, tiled=True)

    def gather_stack(self, x, axis_name: str = None):
        """Stack per-device blocks into a leading (n_dev, ...) axis —
        the candidate-list gather shape of the exact kNN block kernel."""
        import jax

        from . import topology
        from .mesh import DATA_AXIS

        axis = axis_name or DATA_AXIS
        with device_section(self.name, x):
            n_dev = jax.lax.psum(1, axis)
            topo = self._resolved(n_dev)
            _record_link_bytes(
                self.name, *topology.link_split_gather(topo, _static_nbytes(x))
            )
            if topo.is_hierarchical:
                return self._hier_slab(x, axis, topo)
            return jax.lax.all_gather(x, axis)

    def psum(self, x, axis_name: str = None):
        """Element-wise sum of per-device partials (lax.psum).  The
        hierarchical schedule reduces within the group first and crosses
        DCN with the group-reduced partial; summation is re-associated, so
        (unlike the movement-only collectives) it is NOT bitwise-pinned to
        the flat schedule for non-exact dtypes — the forest/stat engines
        that need exactness keep the flat default."""
        import jax
        import jax.numpy as jnp

        from . import topology
        from .mesh import DATA_AXIS

        axis = axis_name or DATA_AXIS
        leaves = jax.tree_util.tree_leaves(x)
        with device_section(self.name, *leaves):
            n_dev = jax.lax.psum(1, axis)
            topo = self._resolved(n_dev)
            _record_link_bytes(
                self.name,
                *topology.link_split_reduce(topo, _static_nbytes(*leaves)),
            )
            if topo.is_hierarchical:
                gof = jnp.asarray(np.asarray(topo.group_of, dtype=np.int32))
                gate = jnp.asarray(np.asarray(topo.gateways, dtype=np.int32))
                idx = jax.lax.axis_index(axis)
                is_gate = idx == jnp.take(gate, jnp.take(gof, idx))
                groups = [list(g) for g in topo.groups]

                def _leaf(leaf):
                    intra = jax.lax.all_gather(
                        leaf, axis, axis_index_groups=groups
                    )
                    part = jnp.sum(intra, axis=0)
                    part = jnp.where(
                        is_gate.reshape((1,) * part.ndim),
                        part,
                        jnp.zeros_like(part),
                    )
                    return jax.lax.psum(part, axis)

                return jax.tree_util.tree_map(_leaf, x)
            return jax.lax.psum(x, axis)

    def psum_merge(self, x, axis_name: str = None):
        """Stack per-device candidate blocks into one (n_dev, ...) slab via
        a single psum (zeros-slab scatter; exact as a gather — every element
        receives one shard's value plus zeros, and x + 0.0 is exact for the
        finite/+inf distances and int32 positions the merges carry).  The
        hierarchical schedule (_hier_slab) keeps the identical one-value-
        plus-zeros summand structure, so both schedules are BITWISE equal."""
        import jax
        import jax.numpy as jnp

        from . import topology
        from .mesh import DATA_AXIS

        axis = axis_name or DATA_AXIS
        with device_section(self.name, x):
            n_dev = jax.lax.psum(1, axis)
            topo = self._resolved(n_dev)
            _record_link_bytes(
                self.name, *topology.link_split_gather(topo, _static_nbytes(x))
            )
            if topo.is_hierarchical:
                return self._hier_slab(x, axis, topo)
            idx = jax.lax.axis_index(axis)
            slab = jnp.zeros((n_dev,) + x.shape, x.dtype).at[idx].set(x)
            return jax.lax.psum(slab, axis)

    def ring_shift(self, x, axis_name: str = None, shift: int = 1):
        """Send this shard's block to its ring successor and receive its
        predecessor's — the ring-permute hop of the kNN candidate
        exchange.  Counters record the per-hop payload, so a full ring
        pass shows n_dev x block bytes (vs the n_dev^2 x block an
        all-gather replicates).  With a hierarchical topology the cycle
        tours each host group's ICI neighbors consecutively with exactly
        one gateway edge per group pair on DCN (topology.ring_cycle);
        flat keeps the +shift rotation (mesh.ring_permutation, the ONE
        flat ring order).  TPU: Pallas remote-DMA kernel for the uniform
        flat rotation; the hierarchical cycle and every non-TPU backend
        ride lax.ppermute (identical semantics, the tier-1/parity path)."""
        import jax

        from . import topology
        from .mesh import DATA_AXIS

        axis = axis_name or DATA_AXIS
        with device_section(self.name, x):
            n_dev = jax.lax.psum(1, axis)
            if n_dev == 1:
                return x
            topo = self._resolved(n_dev)
            _record_link_bytes(
                self.name,
                *topology.link_split_ring_hop(topo, _static_nbytes(x)),
            )
            if topo.is_hierarchical:
                # the remote-DMA kernel computes dst = my + shift analytically,
                # which only matches the uniform rotation; the gateway cycle
                # rides ppermute on every backend (XLA schedules TPU ppermute
                # over ICI fine — the dedicated gateway DMA kernel is
                # accelerator-session work)
                return jax.lax.ppermute(
                    x, axis, topology.ring_cycle(topo, shift)
                )
            if _remote_dma_enabled():
                return _ring_shift_remote_dma(x, axis, shift, n_dev)
            from .mesh import ring_permutation

            return jax.lax.ppermute(x, axis, ring_permutation(n_dev, shift))


def device_collective(name: str, topo=None) -> DeviceSection:
    """The typed-section constructor: one named handle per call site.
    `topo` (a topology.TopologyMap) opts the section into the
    hierarchical schedules — pass it ONLY from code that also carries it
    in its compilation cache key."""
    return DeviceSection(name, topo)


# remote-DMA gate: TPU hardware with pallas enabled, unless explicitly
# disabled.  Interpret-mode and CPU meshes cannot run remote copies, so the
# ppermute fallback is also what every tier-1 test exercises; the two paths
# are semantics-identical by construction (one block in, the left
# neighbor's block out).
_REMOTE_DMA_ENV = "SRML_EXCHANGE_REMOTE_DMA"


def _remote_dma_enabled() -> bool:
    import os

    import jax

    if os.environ.get(_REMOTE_DMA_ENV, "1") == "0":
        return False
    try:
        from ..ops.pallas_tpu import pallas_enabled
    except ImportError:  # pragma: no cover - circular-import guard
        return False
    return jax.default_backend() == "tpu" and pallas_enabled()


def _ring_shift_remote_dma(x, axis_name: str, shift: int, n_dev: int):
    """+shift ring permute as a Pallas remote-DMA kernel (the SNIPPETS.md
    `make_async_remote_copy` exemplar, generalized to any shift): the whole
    block rides one neighbor-to-neighbor ICI DMA with send/recv semaphores
    providing the synchronization — no cross-chip collective schedule, no
    replication.  Runs on TPU hardware only (guarded by callers); this
    module is the single audited home of the remote-DMA API (graftlint
    R8)."""
    import jax
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        my = jax.lax.axis_index(axis_name)
        dst = jax.lax.rem(my + shift + n_dev, n_dev)
        copy = pltpu.make_async_remote_copy(
            src_ref=x_ref,
            dst_ref=o_ref,
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id=(dst,),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        copy.start()
        # the wait covers BOTH directions: send_sem fires when the local
        # block has left, recv_sem when the left neighbor's block landed in
        # o_ref — the hop's compute/communicate overlap happens at the
        # caller (the next hop's block is in flight while this hop merges)
        copy.wait()  # graftlint: disable=R9 (DMA completion has no timeout; R8 requires the start/wait pair)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        in_specs=[pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 2,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid_spec=grid_spec,
    )(x)


# -- legacy un-named-section shims ---------------------------------------------


def allgather_rows(x, axis_name: str = None, section: str = "allgather_rows"):
    """Concatenate per-device row blocks along axis 0 (lax.all_gather,
    tiled).  Call ONLY inside a shard_map body bound over `axis_name`."""
    return device_collective(section).allgather_rows(x, axis_name)


def psum_parts(x, axis_name: str = None, section: str = "psum_parts"):
    """Element-wise sum of per-device partial arrays (lax.psum) — the
    "partial result per shard -> full result everywhere" reduction shape of
    the forest engine's histogram combine: each device builds per-node
    histograms over ITS row shard and one psum per level yields the global
    histograms replicated on every device (ops/forest._forest_block_kernel,
    ops/forest_hist.node_histograms_sharded).  Call ONLY inside a shard_map
    body bound over `axis_name`."""
    return device_collective(section).psum(x, axis_name)


def psum_merge_parts(x, axis_name: str = None, section: str = "psum_merge_parts"):
    """Stack per-device candidate blocks into one (n_dev, ...) slab via a
    single psum — the IVF-Flat probed search's ONE cross-shard collective.
    Call ONLY inside a shard_map body bound over `axis_name`."""
    return device_collective(section).psum_merge(x, axis_name)


def ring_shift(x, axis_name: str = None, shift: int = 1,
               section: str = "ring_shift"):
    """Module-level shim over DeviceSection.ring_shift (docstring there)."""
    return device_collective(section).ring_shift(x, axis_name, shift)


def byte_totals(prefix: str = "exchange."):
    """(total_bytes, {section: bytes}) over every exchange section counter —
    host sections count per call, device sections per compiled geometry
    (trace time).  bench.py snapshots this around each arm so the round
    standings can print a `bytes moved` column and make the all-gather ->
    ring traffic reduction a captured artifact.  The per-LINK rollup of
    the same namespace lives in link_totals() — the `.ici_bytes`/
    `.dcn_bytes` split counters carry an underscore suffix precisely so
    this scan never double-counts them."""
    per = {}
    for name, v in profiling.counters(prefix).items():
        if name.endswith(".bytes"):
            per[name[len(prefix):-len(".bytes")]] = int(v)
    return sum(per.values()), per


def link_totals(prefix: str = "exchange."):
    """{"ici": bytes, "dcn": bytes} rollup of the per-section link-split
    counters (`exchange.<name>.ici_bytes` / `.dcn_bytes`) — the link-
    pressure view of byte_totals().  Surfaced continuously through
    export_metrics()["gauges"] (the `exchange.link.*` provider below) and
    rendered as the `srml_exchange_bytes{link="ici|dcn"}` Prometheus
    family, so the serving plane's dashboards see DCN pressure without a
    bench round."""
    out = {"ici": 0, "dcn": 0}
    for name, v in profiling.counters(prefix).items():
        if name.endswith(".ici_bytes"):
            out["ici"] += int(v)
        elif name.endswith(".dcn_bytes"):
            out["dcn"] += int(v)
    return out


def _link_gauges():
    links = link_totals()
    return {
        "exchange.link.ici_bytes": float(links["ici"]),
        "exchange.link.dcn_bytes": float(links["dcn"]),
    }


profiling.register_gauges("exchange.link", _link_gauges)


def ring_pass_bytes(
    cp: Any,
    rank: int,
    nranks: int,
    payload: bytes,
    chunk: int = CHUNK_BYTES,
    src: Optional[int] = None,
    link: Optional[str] = None,
) -> bytes:
    """One ring hop over the control plane: contribute `payload` and
    return the payload received from `src` (default the flat-ring
    predecessor, (rank - 1) % nranks) — the HOST-plane analog of
    DeviceSection.ring_shift, used by distributed_kneighbors' ring route
    to rotate query blocks + running candidate lists between ranks as
    binary frames.  A non-default `src` lets the caller follow a
    topology-aware cycle (topology.ring_cycle over ranks): every rank
    must apply the SAME cycle and pass its own predecessor in it — the
    broadcast transport carries every frame regardless, so routing IS the
    receiver's decode choice.  `link` ("ici" | "dcn") attributes this
    hop's outgoing payload to the `exchange.ring.<link>_bytes` split
    counter when the caller knows the edge's link class.

    The wire rides the broadcast allGather (the only collective a Spark
    barrier offers) but the decode is p2p-shaped: a receiver b64-decodes /
    joins ONLY its predecessor's chunks and drops the rest by reference, so
    per-rank decode volume is O(one neighbor's payload) per hop instead of
    O(sum of all ranks').  COLLECTIVE: every rank must call it once per
    hop, empty payloads included."""
    # srml-shield: corrupt here flips bytes in the outgoing frame (the
    # receiver's SRX1 magic check must fail loudly); die/raise simulate a
    # rank lost mid-ring
    payload = faults.site("exchange.ring_pass", rank=rank, payload=payload)
    if link in ("ici", "dcn") and payload:
        profiling.incr_counter(f"exchange.ring.{link}_bytes", len(payload))
    with section("ring", nbytes=len(payload)):
        use_bytes = hasattr(cp, "allGatherBytes")
        if src is None:
            src = (rank - 1) % nranks
        mine = _chunks(payload, chunk)
        counts = [int(c) for c in cp.allGather(str(len(mine)))]
        parts: List[bytes] = []
        for r in range(max(counts)):
            got = _send(cp, mine[r] if r < len(mine) else b"", use_bytes)
            if r < counts[src]:
                parts.append(_recv(got[src], use_bytes))
        return b"".join(parts)


def alltoall_bytes(
    cp: Any,
    rank: int,
    nranks: int,
    dests: Sequence[bytes],
    chunk: int = CHUNK_BYTES,
) -> List[bytes]:
    """All-to-all of per-destination binary payloads: rank s passes
    dests[d] for every destination d and receives the nranks payloads
    addressed to IT (result[s] = what rank s sent to this rank).

    The wire rides the broadcast allGather (the only collective a Spark
    barrier offers), but chunks are framed dest-major with a counts
    round first, so a receiver b64-decodes/joins ONLY the chunk rounds
    addressed to it and drops the rest by reference — per-rank decode
    volume is O(own share), the p2p shape of the reference's UCX return
    (knn.py:549-560: each query partition's results land only on their
    owning rank)."""
    if len(dests) != nranks:
        raise ValueError(f"need {nranks} destination payloads, got {len(dests)}")
    with section("alltoall", nbytes=sum(len(d) for d in dests)):
        use_bytes = hasattr(cp, "allGatherBytes")
        frames = [_chunks(d, chunk) for d in dests]
        meta = json.dumps([len(f) for f in frames])
        all_meta = [json.loads(s) for s in cp.allGather(meta)]  # [src][dest]
        # canonical send order: dest-major concatenation of each source's
        # chunks
        my_seq = [c for f in frames for c in f]
        # position range of (src -> me) chunks inside src's send sequence
        lo = [sum(all_meta[s][:rank]) for s in range(nranks)]
        hi = [lo[s] + all_meta[s][rank] for s in range(nranks)]
        rounds = max(sum(m) for m in all_meta)
        mine: List[List[bytes]] = [[] for _ in range(nranks)]
        for r in range(rounds):
            got = _send(cp, my_seq[r] if r < len(my_seq) else b"", use_bytes)
            for s in range(nranks):
                if lo[s] <= r < hi[s]:
                    mine[s].append(_recv(got[s], use_bytes))
        return [b"".join(p) for p in mine]
