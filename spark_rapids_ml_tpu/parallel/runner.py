#
# Launcher-agnostic multi-controller fit execution.
#
# This is the executor-side half of the reference's barrier fit
# (/root/reference/python/src/spark_rapids_ml/core.py:488-640): one process
# per Spark barrier task (= TPU-VM worker), each holding its own row
# partitions, cooperating through a small string control plane
# (BarrierTaskContext.allGather on Spark; FileControlPlane for plain process
# launchers and tests).  The flow per rank:
#
#   1. TpuContext bootstraps jax.distributed (coordinator address allGathered
#      like the reference's NCCL uid, cuml_context.py:75-103)
#   2. a GLOBAL 1-D mesh is built over every device in the pod, ordered
#      process-major so rank r's rows land on rank r's chips
#   3. per-rank partition sizes are allGathered into a PartitionDescriptor
#      (reference utils.py:159-196) to size the global padded array
#   4. each rank's local rows become its process-local shards of one global
#      row-sharded jax.Array (jax.make_array_from_process_local_data), padded
#      rows masked through the weight vector
#   5. the SAME pure-jax fit function used single-controller runs on every
#      rank; GSPMD collectives ride ICI within a host and DCN across ranks
#   6. results are replicated; every rank materializes them, rank 0's are
#      yielded to the driver (JSON-safe encoded)
#
# Unlike the reference there is no second code path for the distributed
# case — the solvers cannot tell a pod mesh from a single-host mesh.
#

from __future__ import annotations

import base64
import contextlib
import json
import os
import random
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np
import pandas as pd

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import faults
from .context import ControlPlane, LocalControlPlane, RemoteRankError, TpuContext
from .mesh import DATA_AXIS
from .partition import PartitionDescriptor

from .. import profiling

# -- srml-shield control-plane knobs (docs/robustness.md) ---------------------
# Per-ROUND bounded timeout: every gather round gets its own budget instead
# of one session-wide 300 s cliff, so a wedged round is diagnosed at round
# granularity.  Retries: shared-FS I/O (NFS on TPU-VM pods) throws transient
# OSErrors under churn; each read/write retries with exponential backoff and
# deterministic per-rank jitter before giving up.  The knobs and the parsed
# RetryPolicy live in parallel/context.py (ONE policy shared by the file and
# TCP planes); the names are re-exported here for compatibility.
from .context import (  # noqa: E402 - knob re-exports
    BACKOFF_ENV,
    ControlPlaneTimeout,
    RETRIES_ENV,
    ROUND_TIMEOUT_ENV,
    RetryPolicy,
    _DEFAULT_ROUND_TIMEOUT_S,
)

from ..utils import env_float as _env_float  # noqa: E402 - knob parsing

# which control plane make_control_plane builds: "file" (default, shared
# filesystem) or "tcp" (srml-wire socket plane, parallel/netplane.py)
CP_ENV = "SRML_CP"


def make_control_plane(
    root: str, rank: int, nranks: int, timeout: Optional[float] = None
):
    """Control-plane factory honoring SRML_CP: the process launchers and
    multicontroller workers build their plane through this ONE chokepoint,
    so the whole fit/kneighbors matrix reruns on the TCP plane by flipping
    an env var (the conformance contract: same surface, same math,
    bitwise-equal results — tests/test_multicontroller.py gates it)."""
    kind = os.environ.get(CP_ENV, "file").strip().lower() or "file"
    if kind == "file":
        return FileControlPlane(root, rank, nranks, timeout=timeout)
    if kind == "tcp":
        from .netplane import bootstrap_tcp_plane

        return bootstrap_tcp_plane(root, rank, nranks, timeout=timeout)
    raise ValueError(f"{CP_ENV}={kind!r}: known planes are 'file' and 'tcp'")


class FileControlPlane:
    """Control plane over a shared filesystem: allGather by atomic per-rank
    message files in numbered rounds, barrier as an empty gather.

    Stands in for Spark's BarrierTaskContext wherever there is no Spark —
    subprocess launchers, mpirun-style deployments with a shared FS, and the
    multi-controller tests.  Rendezvous root must be empty per job.

    srml-shield fast-abort surface (docs/robustness.md):

      - every plane writes an `alive_rank<k>.pid` liveness file at
        construction and holds an EXCLUSIVE flock on it for the process
        lifetime; gather waits probe peers' locks (the kernel releases a
        dead process's locks even while it is an unreaped zombie, which a
        bare `kill(pid, 0)` cannot see) with a pid check as fallback, so a
        rank KILLED mid-collective (no marker, no teardown — the
        SIGKILL/OOM shape) is detected within one poll interval and
        surfaces as RemoteRankError naming the dead rank, not as a
        round-timeout 300 s later.
      - abort(payload) atomically publishes an `abort-r<k>.json` marker (the
        encoded exception + failing span, written by TpuContext.__exit__ on
        the exception path); gather waits poll for foreign markers and raise
        RemoteRankError quoting the origin rank, exception type, and span.
      - close() removes this rank's presence files (alive + heartbeat) and
        reaps those of peers whose process is gone — the no-orphan-files
        half of the teardown contract."""

    def __init__(self, root: str, rank: int, nranks: int,
                 timeout: Optional[float] = None, poll: float = 0.02):
        self._root = root
        self._rank = rank
        self._nranks = nranks
        self._round = 0
        self._timeout = (
            timeout
            if timeout is not None
            else _env_float(ROUND_TIMEOUT_ENV, _DEFAULT_ROUND_TIMEOUT_S)
        )
        self._poll = poll
        # deterministic per-rank backoff jitter (explicitly seeded: R4);
        # the retry policy is parsed ONCE here (matching _timeout) and
        # shared-by-contract with the TCP plane (context.RetryPolicy)
        self._jitter = random.Random(10007 + rank)
        self._retry = RetryPolicy.from_env()
        os.makedirs(root, exist_ok=True)
        # liveness: pid + an exclusive flock held for the process lifetime.
        # The LOCK is the primary death signal — the kernel releases it the
        # instant the process exits, including the unreaped-zombie window
        # where kill(pid, 0) still succeeds.  The pid is the fallback (and
        # the error message's evidence) for filesystems without working
        # flock, recorded in the file so peers know which probe to trust.
        self._alive_fd: Optional[int] = None
        self._register_alive()

    # -- file paths ----------------------------------------------------------
    def _alive_path(self, rank: int) -> str:
        return os.path.join(self._root, f"alive_rank{rank:05d}.pid")

    def _abort_path(self, rank: int) -> str:
        return os.path.join(self._root, f"abort-r{rank:05d}.json")

    def _register_alive(self) -> None:
        """Publish `<pid> flock|nolock` and (where the FS supports it) hold
        an exclusive flock on the file for the process lifetime — the mode
        word tells peers which death probe to trust.  A sibling plane
        instance of this SAME process (thread-mocked rank harnesses) may
        already hold the path's lock; replacing its inode would orphan
        that lock, so an entry already naming our pid is left alone."""
        path = self._alive_path(self._rank)
        try:
            with open(path) as f:
                parts = f.read().split()
            if parts and parts[0] == str(os.getpid()):
                return  # a sibling instance of this process registered us
        except OSError:
            pass
        self._write_atomic(path, f"{os.getpid()} nolock")
        try:
            import fcntl

            fd = os.open(path, os.O_RDWR)
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                os.close(fd)
                return
            self._alive_fd = fd  # held until close() / process death
            content = f"{os.getpid()} flock".encode()
            os.pwrite(fd, content, 0)
            os.ftruncate(fd, len(content))
        except (ImportError, OSError):
            pass

    # -- retrying I/O ---------------------------------------------------------
    def _retry_io(self, fn, what: str):
        """Run `fn` retrying transient OSErrors with exponential backoff +
        deterministic jitter — the construction-parsed RetryPolicy
        (SRML_CP_RETRIES / SRML_CP_BACKOFF_S), NOT a per-call env re-read."""
        return self._retry.run(fn, self._jitter)

    def _write_atomic(self, path: str, text_or_bytes) -> None:
        data = (
            text_or_bytes.encode("utf-8")
            if isinstance(text_or_bytes, str)
            else text_or_bytes
        )
        tmp = path + f".tmp{os.getpid()}"

        def _write():
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)  # atomic publish

        self._retry_io(_write, path)

    def _read_bytes(self, path: str) -> bytes:
        def _read():
            with open(path, "rb") as f:
                return f.read()

        return self._retry_io(_read, path)

    # -- the gather protocol --------------------------------------------------
    def allGather(self, message: str) -> List[str]:
        return [
            b.decode("utf-8")
            for b in self._gather_round(message.encode("utf-8"))
        ]

    def allGatherBytes(self, message: bytes) -> List[bytes]:
        """Binary gather round — shared-FS planes move raw frames without
        the base64 detour the string-only Spark RPC transport needs
        (parallel/exchange.py picks this path up by hasattr)."""
        return self._gather_round(message)

    def _gather_round(self, message: bytes) -> List[bytes]:
        r = self._round
        self._round += 1
        message = faults.site("cp.gather", rank=self._rank, payload=message)
        path = os.path.join(self._root, f"round{r:05d}_rank{self._rank:05d}.msg")
        self._write_atomic(path, message)
        expected = [
            os.path.join(self._root, f"round{r:05d}_rank{i:05d}.msg")
            for i in range(self._nranks)
        ]
        deadline = time.monotonic() + self._timeout
        while not all(os.path.exists(p) for p in expected):
            missing = [
                i for i, p in enumerate(expected) if not os.path.exists(p)
            ]
            # fast-abort scan: a foreign abort marker or a dead peer ends
            # the wait within ONE poll interval, naming the culprit —
            # instead of the full round timeout naming nobody
            self._raise_if_aborted()
            self._raise_if_peer_dead(missing)
            if time.monotonic() > deadline:
                raise ControlPlaneTimeout(
                    "FileControlPlane", r, missing, self._timeout
                )
            time.sleep(self._poll)
        out = []
        for p in expected:
            out.append(self._read_bytes(p))
        return out

    def barrier(self) -> None:
        faults.site("cp.barrier", rank=self._rank)
        self.allGather("")

    # -- srml-shield abort protocol -------------------------------------------
    def abort(self, payload: str) -> None:
        """Atomically publish this rank's abort marker (JSON: rank, etype,
        message, span).  Fire-and-forget like publish_health: no rank ever
        waits on it — peers polling in a gather wait pick it up and raise
        RemoteRankError within one poll interval."""
        profiling.incr_counter("cp.abort_markers")
        self._write_atomic(self._abort_path(self._rank), payload)

    def check_abort(self) -> Optional[Dict[str, Any]]:
        """First foreign abort marker's decoded payload, or None.  Never
        blocks; a torn/garbled marker degrades to a minimal payload naming
        the origin rank (the marker's existence IS the abort signal)."""
        for i in range(self._nranks):
            if i == self._rank:
                continue
            p = self._abort_path(i)
            if not os.path.exists(p):
                continue
            try:
                info = json.loads(self._read_bytes(p).decode("utf-8"))
                if isinstance(info, dict):
                    info.setdefault("rank", i)
                    return info
            except (OSError, ValueError):
                pass
            return {"rank": i}
        return None

    def _raise_if_aborted(self) -> None:
        info = self.check_abort()
        if info is None:
            return
        profiling.incr_counter("cp.remote_aborts")
        raise RemoteRankError(
            rank=int(info.get("rank", -1)),
            message=info.get("message", "aborted"),
            span=info.get("span"),
            etype=info.get("etype"),
        )

    @staticmethod
    def _pid_alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except (PermissionError, OSError):
            return True  # exists but not ours (or unknowable): assume alive
        return True

    def _peer_dead_reason(self, rank: int) -> Optional[str]:
        """Why rank `rank` is believed dead, or None (alive / not yet
        registered).  Primary signal: its liveness flock is FREE (the
        kernel releases it at process exit — including the unreaped-zombie
        window where kill(pid, 0) still succeeds); fallback for nolock
        registrations: the pid is gone."""
        path = self._alive_path(rank)
        try:
            with open(path) as f:
                parts = f.read().split()
        except OSError:
            return None  # not registered yet (or already cleanly closed)
        try:
            pid = int(parts[0])
        except (IndexError, ValueError):
            return None  # torn write: the retry-backed publisher fixes it
        if len(parts) > 1 and parts[1] == "flock":
            # the mode word says the registrant HOLDS the lock: the probe is
            # authoritative (and works across hosts on lock-honoring shared
            # FS).  The local pid check must NOT run first here — on a
            # multi-host deployment a remote rank's pid means nothing to
            # this kernel and kill(pid, 0) would declare a healthy peer
            # dead.  Only an unprobeable lock falls through to the pid.
            try:
                import fcntl

                fd = os.open(path, os.O_RDONLY)
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                except OSError:
                    return None  # lock held: alive
                else:
                    fcntl.flock(fd, fcntl.LOCK_UN)
                    return (
                        f"process (pid {pid}) released its liveness lock "
                        "(exited; possibly an unreaped zombie)"
                    )
                finally:
                    os.close(fd)
            except (ImportError, OSError):
                pass  # cannot probe: fall through to the pid best-effort
        if not self._pid_alive(pid):
            return f"process (pid {pid}) is gone"
        return None

    def _raise_if_peer_dead(self, missing_ranks: List[int]) -> None:
        """A rank that REGISTERED (alive file present) but is provably gone
        died without a marker — killed, OOMed, segfaulted.  Only ranks we
        are actually waiting on are scanned; a rank that has not
        registered yet is merely slow (the round timeout still bounds
        it)."""
        for i in missing_ranks:
            reason = self._peer_dead_reason(i)
            if reason is None:
                continue
            profiling.incr_counter("cp.dead_peers")
            raise RemoteRankError(
                rank=i,
                message=(
                    f"{reason} mid-collective without an abort marker "
                    "(killed / OOM / segfault)"
                ),
            )

    def close(self) -> None:
        """Release this rank's liveness lock, remove its presence files
        (alive pid + heartbeat), and — ONLY once no other survivor remains
        — reap dead peers' too.  A dead rank's alive file is the death
        EVIDENCE every still-blocked survivor polls to raise its own
        RemoteRankError: the first survivor to close must not destroy it,
        or the slower survivors ride out the full round timeout (the exact
        hang this plane exists to kill).  The LAST closer sees no live
        registered peer left and sweeps, so after every surviving rank
        closes, no alive_*/health_* file remains for any rank (the
        no-orphan-files teardown contract; gated by the chaos tests).
        Round messages and abort markers are the session's record and are
        left for the per-job rendezvous root to be deleted wholesale."""
        for path in (
            self._alive_path(self._rank),
            os.path.join(self._root, f"health_rank{self._rank:05d}.json"),
        ):
            with contextlib.suppress(OSError):
                os.remove(path)
        if self._alive_fd is not None:
            with contextlib.suppress(OSError):
                os.close(self._alive_fd)  # releases the flock
            self._alive_fd = None
        # a peer whose alive file is present AND whose death probe says
        # "alive" is a survivor that has not closed yet: leave the dead
        # ranks' evidence for it
        for i in range(self._nranks):
            if i == self._rank:
                continue
            if (
                os.path.exists(self._alive_path(i))
                and self._peer_dead_reason(i) is None
            ):
                return
        for i in range(self._nranks):
            if i == self._rank:
                continue
            for path in (
                self._alive_path(i),
                os.path.join(self._root, f"health_rank{i:05d}.json"),
            ):
                with contextlib.suppress(OSError):
                    os.remove(path)

    # -- srml-watch health surface (NON-collective, unlike the gathers) ------
    def publish_health(self, payload: str) -> None:
        """Atomically overwrite this rank's heartbeat file.  Unlike the
        numbered gather rounds this is fire-and-forget: no rank ever waits
        on it, so a wedged rank cannot stall the health plane — which is
        the whole point (watch.HeartbeatPublisher calls this on its own
        thread while the fit thread may be stuck in a collective)."""
        path = os.path.join(self._root, f"health_rank{self._rank:05d}.json")
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)

    def read_health(self) -> Dict[int, str]:
        """Latest heartbeat payload per rank (missing ranks absent) — the
        watchdog's read side; never blocks."""
        out: Dict[int, str] = {}
        for i in range(self._nranks):
            p = os.path.join(self._root, f"health_rank{i:05d}.json")
            try:
                with open(p) as f:
                    out[i] = f.read()
            except OSError:
                continue
        return out


def global_mesh() -> Mesh:
    """1-D data mesh over EVERY device in the (possibly multi-process)
    runtime, ordered process-major so the row sharding assigns rank r's
    contiguous global row block to rank r's local devices."""
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    return Mesh(np.array(devs), (DATA_AXIS,))


# -- JSON-safe model-attribute transport -------------------------------------
# The driver gets model attributes back through Spark rows (strings), so
# arrays ride as base64 raw bytes + dtype/shape (the reference ships cuML
# attrs as JSON text rows the same way, core.py:625-630).

def _encode_value(v: Any) -> Any:
    if isinstance(v, jax.Array):
        v = jax.device_get(v)  # explicit fetch: sanitize-scope clean
    if isinstance(v, np.ndarray):
        return {
            "__ndarray__": base64.b64encode(
                np.ascontiguousarray(v).tobytes()
            ).decode("ascii"),
            "dtype": str(v.dtype),
            "shape": list(v.shape),
        }
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, dict):
        return {k: _encode_value(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_encode_value(x) for x in v]
    return v


def _decode_value(v: Any) -> Any:
    if isinstance(v, dict):
        if "__ndarray__" in v:
            return (
                np.frombuffer(
                    base64.b64decode(v["__ndarray__"]), dtype=np.dtype(v["dtype"])
                )
                .reshape(v["shape"])
                .copy()
            )
        return {k: _decode_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode_value(x) for x in v]
    return v


def encode_attrs(attrs: Dict[str, Any]) -> Dict[str, Any]:
    return {k: _encode_value(v) for k, v in attrs.items()}


def allgather_ndarray(
    control_plane: Any, rank: int, arr: np.ndarray
) -> List[np.ndarray]:
    """Rank-ordered allGather of one ndarray over the string control plane,
    riding the same base64 codec as the model-attribute transport (the
    reference ships whole serialized models through its barrier allGather
    the same way, tree.py:316-363).  Every rank receives the identical
    rank-ordered list, so derived quantities (bin edges, class sets) are
    bitwise-consistent across ranks."""
    msg = json.dumps({"rank": rank, "v": _encode_value(np.asarray(arr))})
    blocks = sorted(
        (json.loads(m) for m in control_plane.allGather(msg)),
        key=lambda g: g["rank"],
    )
    return [_decode_value(g["v"]) for g in blocks]


def decode_attrs(attrs: Dict[str, Any]) -> Dict[str, Any]:
    return {k: _decode_value(v) for k, v in attrs.items()}


# -- the distributed fit session ---------------------------------------------

class DistributedFitSession:
    """One jax.distributed lifetime; fits any number of estimators over the
    pod-wide mesh (the per-fit NCCL create/destroy of the reference,
    cuml_context.py:109-166, generalized so callers can amortize the
    bootstrap across fits)."""

    def __init__(self, rank: int, nranks: int, control_plane: ControlPlane):
        self.rank = rank
        self.nranks = nranks
        self.control_plane = control_plane
        self.mesh = global_mesh()

    # FitInputs construction (executor-side analog of
    # _TpuCaller._build_fit_inputs, which is single-controller)
    def build_fit_inputs(self, estimator: Any, df: Any) -> Any:
        from ..core import FitInputs

        # A rank can legitimately hold ZERO rows (fewer rows than barrier
        # tasks, skewed repartition).  It must still join every gather —
        # bailing out locally would hang the other ranks — so it reports
        # empty sizes and takes its dtype from the data-bearing ranks.
        rank_has_rows = any(len(p) > 0 for p in df.partitions)
        if rank_has_rows:
            feats, labels, weights, dtype = estimator._pre_process_data(df)
        else:
            feats, weights, dtype = [], None, None
            labels = [] if estimator._fit_label_col() is not None else None
        partition_rows = [f.shape[0] for f in feats]
        nonempty = [f for f in feats if f.shape[0] > 0]
        n_loc = sum(partition_rows)
        n_cols_loc = nonempty[0].shape[1] if nonempty else 0
        pdesc = PartitionDescriptor.gather(
            partition_rows, n_cols_loc, self.rank, self.nranks,
            self.control_plane,
            extra={"dtype": str(dtype) if dtype is not None else ""},
        )
        if pdesc.m == 0:
            raise RuntimeError("Dataset is empty; cannot fit")
        n_cols = pdesc.n
        dtypes = {e["dtype"] for e in pdesc.extras if e.get("dtype")}
        if len(dtypes) > 1:
            raise ValueError(f"ranks disagree on input dtype: {sorted(dtypes)}")
        if dtype is None:
            dtype = np.dtype(dtypes.pop())

        n_total_dev = self.mesh.devices.size
        if n_total_dev % self.nranks != 0:
            raise RuntimeError(
                f"{n_total_dev} devices do not divide evenly over "
                f"{self.nranks} ranks"
            )
        local_dev = n_total_dev // self.nranks
        # every rank contributes the same padded share so the global array is
        # evenly row-sharded; the share covers the LARGEST rank (unbalanced
        # partitions cost padding, not correctness — Spark's repartition
        # keeps them near-equal anyway)
        max_rank_rows = max(pdesc.rank_rows(r) for r in range(self.nranks))
        share = -(-max_rank_rows // local_dev) * local_dev
        n_pad = share * self.nranks

        # labels/weights ride >= float32 buffers regardless of a low-
        # precision FEATURE dtype — same rule as the single-controller
        # ingest (core._pre_process_data): a bf16 buffer would round
        # integer class labels above the half-precision mantissa
        ldtype = np.dtype(np.float32) if np.dtype(dtype).itemsize < 4 else dtype

        def _to_global(
            local_cols: int, fill: Optional[np.ndarray], is_2d: bool,
            buf_dtype=None,
        ):
            shape = (share, local_cols) if is_2d else (share,)
            buf = np.zeros(shape, dtype=buf_dtype or dtype)
            if fill is not None and fill.shape[0]:
                buf[: fill.shape[0]] = fill
            gshape = (n_pad, local_cols) if is_2d else (n_pad,)
            return jax.make_array_from_process_local_data(
                NamedSharding(self.mesh, P(DATA_AXIS)), buf, global_shape=gshape
            )

        X_loc = (
            np.concatenate(nonempty, axis=0)
            if nonempty
            else np.zeros((0, n_cols), dtype=dtype)
        )
        if X_loc.shape[0] and X_loc.shape[1] != n_cols:
            raise ValueError(
                f"rank {self.rank} has {X_loc.shape[1]} feature columns, "
                f"other ranks have {n_cols}"
            )
        Xs = _to_global(n_cols, X_loc if X_loc.shape[0] else None, is_2d=True)

        w_loc = (
            np.concatenate(weights)
            if weights  # None or [] (empty rank) -> valid-row ones mask
            else np.ones(n_loc, dtype=ldtype)
        )
        ws = _to_global(0, w_loc, is_2d=False, buf_dtype=ldtype)

        ys = None
        if labels is not None:
            y_loc = (
                np.concatenate(labels) if labels else np.zeros(0, dtype=ldtype)
            )
            ys = _to_global(0, y_loc, is_2d=False, buf_dtype=ldtype)

        return FitInputs(
            X=Xs,
            weight=ws,
            y=ys,
            n_rows=pdesc.m,
            n_cols=n_cols,
            mesh=self.mesh,
            pdesc=pdesc,
            dtype=dtype,
            rank=self.rank,
            nranks=self.nranks,
            control_plane=self.control_plane,
        )

    def fit(
        self,
        estimator: Any,
        partitions: Sequence[pd.DataFrame],
        extra_params: Optional[List[Dict[str, Any]]] = None,
    ) -> List[Dict[str, Any]]:
        """Run the estimator's fit function over the pod mesh; returns the
        JSON-safe encoded model-attribute dict(s) (one per param map)."""
        from ..dataframe import DataFrame

        if self.nranks > 1 and not getattr(
            estimator, "_supports_multicontroller_fit", True
        ):
            raise NotImplementedError(
                f"{type(estimator).__name__} does not yet support "
                "multi-process (barrier) training: its fit function "
                "host-fetches row-sharded inputs. Train with num_workers=1 "
                "or SRML_SPARK_COLLECT=1 (driver-local fit)."
            )
        df = DataFrame(list(partitions))
        from .. import profiling, watch
        from ..sanitize import sanitize_scope

        profiling.reset_phase_times()
        counters0 = profiling.counters()
        tag = f"fit-{type(estimator).__name__}-rank{self.rank}"
        # srml-watch: every rank heartbeats through the control plane's
        # non-collective publish surface (rank 0 also runs the stall
        # watchdog when SRML_WATCH_STALL_S > 0), and an unhandled exception
        # inside the fit task dumps the flight ring before propagating —
        # the two failure modes (wedge, crash) that previously died silent.
        health = watch.start_fit_health(self.control_plane, self.rank, self.nranks)
        try:
            with watch.flight_scope(tag), profiling.trace_session(tag):
                # srml-shield: the fit-task injection site (action=die here
                # is the chaos matrix's "rank killed mid-fit"; action=raise
                # exercises the abort-marker broadcast in TpuContext)
                faults.site("runner.fit", rank=self.rank)
                with profiling.phase("runner.build_inputs"):
                    inputs = self.build_fit_inputs(estimator, df)
                fit_func = estimator._get_tpu_fit_func(df, extra_params)
                with sanitize_scope(), profiling.phase("runner.fit"):
                    result = fit_func(inputs, dict(estimator._tpu_params))
        finally:
            health.stop()
        # Telemetry snapshot at fit-task exit, merged ACROSS RANKS through
        # the control plane before rank 0's results leave for the driver —
        # this is how the driver-side model sees where every executor's fit
        # spent its time (the reference's per-task NVTX/log lines die on the
        # executors; a mergeable rollup is the only thing that can ride the
        # model-attribute wire).  One extra string gather round; every rank
        # participates (collective contract).
        snap = profiling.TelemetrySnapshot.capture(counters0, rank=self.rank)
        merged = snap
        if self.nranks > 1:
            gathered = self.control_plane.allGather(json.dumps(snap.to_dict()))
            snaps = sorted(
                (json.loads(m) for m in gathered),
                key=lambda d: d.get("meta", {}).get("ranks", [0]),
            )
            merged = profiling.TelemetrySnapshot.from_dict(snaps[0])
            for d in snaps[1:]:
                merged = merged.merge(profiling.TelemetrySnapshot.from_dict(d))
        self.control_plane.barrier()
        results = result if isinstance(result, list) else [result]
        encoded = [encode_attrs(r) for r in results]
        from ..core import TELEMETRY_ATTR

        for e in encoded:
            e[TELEMETRY_ATTR] = merged.to_dict()
        return encoded


@contextlib.contextmanager
def distributed_session(
    rank: int, nranks: int, control_plane: Optional[ControlPlane] = None
) -> Iterator[DistributedFitSession]:
    cp = control_plane or LocalControlPlane()
    # Opt-in on-disk executable cache (SRML_COMPILE_CACHE): every executor
    # process of a barrier job — and every LATER job at the same kernel
    # geometries — deserializes executables a sibling already compiled
    # instead of recompiling them, the fleet-wide cold_sec lever (rf_clf
    # was 50.4 s cold, almost all XLA compilation).  Best-effort no-op
    # when the env var is unset or jax already has a cache configured.
    from ..ops.precompile import initialize_persistent_cache

    initialize_persistent_cache()
    try:
        with TpuContext(rank, nranks, cp):
            yield DistributedFitSession(rank, nranks, cp)
    finally:
        # srml-shield teardown contract: remove this rank's control-plane
        # presence files (alive pid, heartbeat) and reap dead peers' — runs
        # AFTER TpuContext.__exit__ so an abort marker broadcast on the
        # exception path is already published
        closer = getattr(cp, "close", None)
        if closer is not None:
            closer()


def run_distributed_fit(
    estimator: Any,
    partitions: Sequence[pd.DataFrame],
    rank: int,
    nranks: int,
    control_plane: Optional[ControlPlane] = None,
    extra_params: Optional[List[Dict[str, Any]]] = None,
) -> List[Dict[str, Any]]:
    """One-shot: bootstrap the distributed runtime, fit, tear down.  This is
    what the Spark barrier UDF calls per task (spark/adapter.run_barrier_fit);
    the reference equivalent is the body of _train_udf at core.py:558-632."""
    with distributed_session(rank, nranks, control_plane) as session:
        return session.fit(estimator, partitions, extra_params)
