#
# srml-wire: the TCP control plane (ROADMAP item 2, first half).
#
# Every multicontroller path so far rode FileControlPlane — a shared
# filesystem, 50 ms polls, and flock liveness.  That proves the robustness
# contract (typed RemoteRankError naming the culprit rank/span within one
# detection interval) only on one machine with a shared FS.  This module
# carries the SAME ControlPlane surface (allGather / allGatherBytes /
# barrier / publish_health / read_health / abort / check_abort / close)
# over a coordinator socket server with length-prefixed binary frames, so
# srml-watch heartbeats, srml-shield abort markers, and exchange.py's
# binary gathers run unchanged across hosts that share nothing but a
# network — the jax.distributed-era replacement for the reference's
# NCCL-uid string bootstrap (PAPER.md L4, core.py:488-640).
#
# What the wire buys over the file plane:
#
#   - PUSHED aborts and death notices: the coordinator broadcasts an abort
#     marker / dead-rank notice the moment it learns of it, so a blocked
#     gather wakes in ~one RTT instead of the file plane's 50 ms poll
#     floor (benchmark/bench_control_plane.py measures both).
#   - LEASES with session-epoch fencing replacing flock liveness: every
#     member holds a coordinator lease refreshed by any frame (pings ride
#     at lease/3); an expired lease — SIGKILL, OOM, network partition —
#     surfaces to every survivor as RemoteRankError naming the rank.  Each
#     incarnation of a rank gets a session EPOCH; once a rank is declared
#     dead its epoch is fenced, and a rejoining zombie (stale epoch, or a
#     fresh join for a fenced rank) is rejected with the typed
#     StaleEpochError — never silently readmitted mid-session (the
#     split-brain shape flock could not express).
#   - COORDINATOR-ALLOCATED jax.distributed ports: allocate_port() hands
#     out coordinator-reserved ports, so concurrent sessions through one
#     coordinator can never race each other for the same port (the
#     _free_port rebind race noted at parallel/context.py).
#   - Typed loss of the coordinator itself: a closed/silent coordinator
#     connection raises CoordinatorLost (never a bare socket.error, never
#     an untyped hang).
#
# Topology: the CoordinatorServer is a pure control-plane rendezvous — it
# moves kilobyte frames at collective-round rates, NOT data (bulk traffic
# rides jax collectives over ICI/DCN).  bootstrap_tcp_plane() hosts it in
# rank 0's process and publishes host:port through the job directory (the
# one out-of-band channel every launcher already has); production
# launchers may equally run it standalone and pass the address explicitly.
#
# Fault injection (docs/robustness.md): cp.net.send / cp.net.recv wrap
# every wire frame, so SRML_FAULTS can drop single frames (action=drop),
# sever a rank bidirectionally (action=partition), corrupt frames on the
# wire (the receiver's magic/bounds checks fail loudly), or delay them.
# The chaos matrix (tests/test_netplane.py) runs all of it on real OS
# processes over real sockets.
#
# graftlint R10 confines the raw socket API to THIS module; every recv/
# accept below a settimeout so no wait is unbounded (R9's socket analog).
#

from __future__ import annotations

import contextlib
import json
import os
import random
import socket
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from .. import profiling, sanitize
from ..utils import env_float as _env_float
from ..utils import get_logger
from . import faults
from .context import (
    ControlPlaneTimeout,
    RemoteRankError,
    RetryPolicy,
    ROUND_TIMEOUT_ENV,
    _DEFAULT_ROUND_TIMEOUT_S,
)

_log = get_logger("srml.netplane")

# -- knobs (docs/robustness.md §wire knobs) -----------------------------------
# The lease is the wire plane's detection interval: a member whose last
# frame is older than the lease is declared dead.  Default couples to the
# srml-watch heartbeat (1.5 heartbeats) so the chaos contract "a lost rank
# is named within 2 heartbeat intervals" holds by construction: detection
# latency <= lease + lease/4 (scan poll) = 1.875 heartbeats.  Client pings
# ride at lease/3, so a healthy link refreshes the lease ~4x per expiry.
LEASE_ENV = "SRML_CP_LEASE_S"

_MAGIC = b"SRCP"
_HEADER = struct.Struct("<4scIQ")  # magic, frame type, meta len, blob len
_MAX_META = 1 << 20          # sanity bound: corrupt length fields fail loudly
_MAX_BLOB = 1 << 40
_IDLE_POLL_S = 0.25          # socket timeout granularity for liveness checks

# frame types: client -> coordinator
_HELLO, _GATHER, _ABORT, _HEALTH, _READ_HEALTH = b"H", b"G", b"A", b"E", b"R"
_PING, _ALLOC_PORT, _LEAVE, _GATHER_STATE = b"P", b"O", b"L", b"S"
# frame types: coordinator -> client
_WELCOME, _FENCED, _GATHER_RESULT = b"W", b"F", b"g"
_ABORT_PUSH, _DEAD_PUSH, _HEALTH_SNAPSHOT, _PORT, _PONG = (
    b"a", b"d", b"h", b"o", b"q"
)


def lease_interval_s() -> float:
    """The membership lease (seconds): SRML_CP_LEASE_S, defaulting to 1.5x
    the srml-watch heartbeat so lease expiry + scan poll stays under the
    documented 2-heartbeat detection bound."""
    from .. import watch

    return _env_float(LEASE_ENV, 1.5 * watch.heartbeat_interval_s())


class ProtocolError(RuntimeError):
    """A wire frame failed the magic/bounds checks — corruption (or a
    non-SRCP speaker).  Always loud: garbage is never decoded silently."""


class StaleEpochError(RuntimeError):
    """The coordinator fenced this connection: the presented session epoch
    belongs to a previous incarnation of the rank (or the rank was already
    declared dead this session).  A fenced process must NOT rejoin the
    collective — its peers have already been told it is gone."""

    def __init__(self, rank: int, epoch: Optional[int], reason: str):
        self.rank = int(rank)
        self.epoch = epoch
        super().__init__(
            f"rank {rank} fenced by coordinator (epoch {epoch}): {reason}"
        )


class CoordinatorLost(RuntimeError):
    """The coordinator connection closed or fell silent past the lease —
    the control plane is gone, so no collective can complete.  Typed so
    survivors of a killed coordinator fail in bounded time naming the
    culprit (the coordinator), never with a bare socket error or a hang."""

    def __init__(self, address: str, reason: str):
        self.address = address
        super().__init__(f"coordinator {address} lost: {reason}")


# -- helpers ------------------------------------------------------------------


def _local_ip() -> str:
    """Routable local IP: a UDP connect() selects the egress interface without
    sending packets, avoiding /etc/hosts entries that pin the hostname to
    127.0.x.1 (common on Debian TPU-VMs)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


def _free_port() -> int:
    # NOTE: inherently racy (the caller rebinds the port after we release
    # it) — kept only as the fallback for planes WITHOUT allocate_port();
    # the coordinator's reservation ledger is the race-free path.
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _pack_frame(ftype: bytes, meta: Dict[str, Any], blob: bytes = b"") -> bytes:
    mbytes = json.dumps(meta, separators=(",", ":")).encode("utf-8")
    return _HEADER.pack(_MAGIC, ftype, len(mbytes), len(blob)) + mbytes + blob


def _send_all(sock: socket.socket, frame: bytes, deadline_s: float) -> None:
    """Write the whole frame with explicit partial-send tracking.  NEVER
    sendall here: the socket carries the _IDLE_POLL_S timeout (recv poll
    granularity), and a sendall that times out mid-frame loses the count
    of bytes already written — a permanently desynced stream.  send()
    either writes >= 1 byte or raises socket.timeout having written NONE,
    so looping it keeps the frame boundary exact; `deadline_s` bounds the
    total stall (a receiver that stops draining for that long is dead)."""
    deadline = time.monotonic() + deadline_s
    view = memoryview(frame)
    off = 0
    while off < len(view):
        try:
            off += sock.send(view[off:])
        except socket.timeout:
            if time.monotonic() > deadline:
                raise OSError(
                    f"send stalled: peer drained nothing for {deadline_s}s "
                    f"({off}/{len(view)} bytes written)"
                )


def _parse_header(hdr: bytes) -> Tuple[bytes, int, int]:
    magic, ftype, mlen, blen = _HEADER.unpack(hdr)
    if magic != _MAGIC:
        raise ProtocolError(f"bad frame magic {magic!r} (corrupt wire frame)")
    if mlen > _MAX_META or blen > _MAX_BLOB:
        raise ProtocolError(
            f"implausible frame lengths meta={mlen} blob={blen} (corrupt)"
        )
    return ftype, mlen, blen


def _read_exact(sock: socket.socket, n: int, stop: threading.Event) -> bytes:
    """Read exactly n bytes; socket timeouts mid-buffer keep accumulating
    (the per-recv settimeout is liveness granularity, not a deadline) until
    `stop` is set.  b'' from the kernel means the peer closed: OSError."""
    sock.settimeout(_IDLE_POLL_S)  # every recv is poll-bounded (R10)
    buf = bytearray()
    while len(buf) < n:
        if stop.is_set():
            raise OSError("connection shut down locally")
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            continue
        if not chunk:
            raise OSError("connection closed by peer")
        buf.extend(chunk)
    return bytes(buf)


def _read_frame(
    sock: socket.socket, stop: threading.Event
) -> Optional[Tuple[bytes, Dict[str, Any], bytes, bytes]]:
    """One whole frame (type, meta, blob, raw bytes), or None when the
    socket idled through a poll interval with no data (the caller's chance
    to run liveness checks).  Raw bytes are returned so wire fault sites
    can corrupt/drop the frame as ONE unit."""
    sock.settimeout(_IDLE_POLL_S)  # every recv is poll-bounded (R10)
    try:
        first = sock.recv(1)
    except socket.timeout:
        return None
    if not first:
        raise OSError("connection closed by peer")
    hdr = first + _read_exact(sock, _HEADER.size - 1, stop)
    ftype, mlen, blen = _parse_header(hdr)
    rest = _read_exact(sock, mlen + blen, stop)
    try:
        meta = json.loads(rest[:mlen].decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as exc:
        raise ProtocolError(f"corrupt frame meta: {exc}") from exc
    return ftype, meta, rest[mlen:], hdr + rest


def _reparse_frame(raw: bytes) -> Tuple[bytes, Dict[str, Any], bytes]:
    """Re-parse a (possibly fault-corrupted) raw frame: the magic/bounds/
    JSON checks are the loud-failure contract for corrupt wire bytes."""
    ftype, mlen, blen = _parse_header(raw[: _HEADER.size])
    body = raw[_HEADER.size:]
    if len(body) != mlen + blen:
        raise ProtocolError("frame length mismatch (corrupt wire frame)")
    try:
        meta = json.loads(body[:mlen].decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as exc:
        raise ProtocolError(f"corrupt frame meta: {exc}") from exc
    return ftype, meta, body[mlen:]


# -- the coordinator ----------------------------------------------------------


@dataclass
class _Member:
    rank: int
    epoch: int
    conn: socket.socket
    # class-level lockdep name: every member's send lock is one node (order
    # is a code discipline); the static R11 pass can't follow this lock
    # through _send_to's parameter, so the runtime check carries it alone
    send_lock: Any = field(
        default_factory=lambda: sanitize.lockdep_lock("net.coord.member_send")
    )
    last_seen: float = 0.0


class CoordinatorServer:
    """The rendezvous side of the wire plane: tracks membership by lease,
    collects gather rounds, rebroadcasts aborts/deaths as pushes, fences
    stale epochs, and reserves jax.distributed ports.  Hosted in rank 0's
    process by bootstrap_tcp_plane(), or standalone by a launcher."""

    def __init__(
        self,
        nranks: int,
        host: str = "",
        advertise_host: Optional[str] = None,
        port: int = 0,
        lease_s: Optional[float] = None,
    ):
        self._nranks = int(nranks)
        self._host = host
        self._advertise_host = advertise_host
        self._port = port
        self._lease_s = lease_s if lease_s is not None else lease_interval_s()
        self._lock = sanitize.lockdep_lock("net.coord.state")
        self._members: Dict[int, _Member] = {}
        self._next_epoch: Dict[int, int] = {}
        self._dead: Dict[int, str] = {}            # rank -> reason
        self._aborts: Dict[int, bytes] = {}        # rank -> abort payload
        self._health: Dict[int, str] = {}
        self._rounds: Dict[int, Dict[int, bytes]] = {}
        self._handed_ports: Set[int] = set()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._listener: Optional[socket.socket] = None
        self._address = ""

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> str:
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self._host, self._port))
        self._listener.listen(max(8, 2 * self._nranks))
        self._listener.settimeout(_IDLE_POLL_S)
        host = self._advertise_host or _local_ip()
        self._address = f"{host}:{self._listener.getsockname()[1]}"
        for name, target in (
            ("srml-netcp-accept", self._accept_loop),
            ("srml-netcp-scan", self._scan_loop),
        ):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            with self._lock:
                self._threads.append(t)
        return self._address

    @property
    def address(self) -> str:
        return self._address

    def stop(self, grace_s: float = 2.0) -> None:
        """Shut the coordinator down: wait up to grace_s for members to
        LEAVE (so sibling ranks' clean closes are not misread as a lost
        coordinator), then close everything and join every thread — the
        no-orphan-sockets/threads half of the teardown contract."""
        deadline = time.monotonic() + grace_s
        while time.monotonic() < deadline:
            with self._lock:
                if not self._members:
                    break
            time.sleep(0.01)
        self._stop.set()
        if self._listener is not None:
            with contextlib.suppress(OSError):
                self._listener.close()
        with self._lock:
            members = list(self._members.values())
            self._members.clear()
        for m in members:
            with contextlib.suppress(OSError):
                m.conn.close()
        with self._lock:
            threads, self._threads = list(self._threads), []
        for t in threads:  # join OUTSIDE the lock (R11: no waits under it)
            t.join(timeout=5.0)

    # -- accept / per-connection reader --------------------------------------
    def _accept_loop(self) -> None:
        self._listener.settimeout(_IDLE_POLL_S)  # accept is poll-bounded (R10)
        while not self._stop.is_set():
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed: shutting down
            conn.settimeout(_IDLE_POLL_S)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name=f"srml-netcp-conn-{conn.fileno()}", daemon=True,
            )
            t.start()
            # prune finished per-connection threads as we go: reconnect /
            # fence churn must not grow the list (or stop()'s join sweep)
            # without bound over a long coordinator lifetime.  Under the
            # state lock: stop()'s join sweep snapshots this list from
            # another thread (graftlint R12)
            with self._lock:
                self._threads = [x for x in self._threads if x.is_alive()]
                self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        member: Optional[_Member] = None
        try:
            member = self._handshake(conn)
            if member is None:
                return
            while not self._stop.is_set():
                got = _read_frame(conn, self._stop)
                if got is None:
                    continue
                ftype, meta, blob, _raw = got
                with self._lock:
                    if self._members.get(member.rank) is not member:
                        return  # fenced/superseded mid-read: drop the frame
                    member.last_seen = time.monotonic()
                if ftype == _LEAVE:
                    self._remove_member(member.rank, member.epoch)
                    return
                self._dispatch(member, ftype, meta, blob)
        except ProtocolError as exc:
            # corrupt frames from a member are a death sentence for that
            # member — the codec contract is fail-loud, never decode-garbage
            if member is not None:
                self._declare_dead(member, f"protocol violation: {exc}")
        except OSError:
            # connection dropped without LEAVE: the SIGKILL/crash shape —
            # declare the member dead NOW (the kernel's FIN beats the lease)
            if member is not None:
                self._declare_dead(
                    member,
                    "connection closed without leave (killed / crashed)",
                )
        finally:
            with contextlib.suppress(OSError):
                conn.close()

    def _handshake(self, conn: socket.socket) -> Optional[_Member]:
        got = None
        deadline = time.monotonic() + self._lease_s * 2
        while got is None:
            if time.monotonic() > deadline:
                return None
            got = _read_frame(conn, self._stop)
        ftype, meta, _blob, _raw = got
        if ftype != _HELLO:
            raise ProtocolError(f"expected HELLO, got {ftype!r}")
        rank = int(meta["rank"])
        nranks = int(meta["nranks"])
        epoch = meta.get("epoch")
        if nranks != self._nranks:
            self._send_to(conn, threading.Lock(), _FENCED, {
                "reason": f"nranks mismatch: job has {self._nranks}, "
                          f"rank {rank} claims {nranks}",
            })
            return None
        with self._lock:
            reason = self._fence_reason(rank, epoch)
            if reason is None:
                if epoch is None:
                    epoch = self._next_epoch.get(rank, 0) + 1
                    self._next_epoch[rank] = epoch
                member = _Member(rank=rank, epoch=int(epoch), conn=conn,
                                 last_seen=time.monotonic())
                self._members[rank] = member
        if reason is not None:
            profiling.incr_counter("cp.net.fenced_rejoins")
            self._send_to(conn, threading.Lock(), _FENCED, {
                "rank": rank, "stale_epoch": epoch, "reason": reason,
            })
            return None
        self._send_to(member.conn, member.send_lock, _WELCOME, {
            "epoch": member.epoch, "lease_s": self._lease_s,
        })
        # a joiner must learn of failures that predate it (it may be a
        # straggler connecting into an already-failing session)
        with self._lock:
            dead = dict(self._dead)
            aborts = dict(self._aborts)
        for r, why in dead.items():
            self._send_to(member.conn, member.send_lock, _DEAD_PUSH,
                          {"rank": r, "reason": why})
        for r, payload in aborts.items():
            if r != rank:
                self._send_to(member.conn, member.send_lock, _ABORT_PUSH,
                              {"rank": r}, payload)
        return member

    def _fence_reason(self, rank: int, epoch) -> Optional[str]:
        """Why this (rank, epoch) join must be fenced, or None.  Caller
        holds the lock."""
        if rank in self._dead:
            return (
                f"rank {rank} was already declared dead this session "
                f"({self._dead[rank]}); a rejoining zombie is fenced"
            )
        current = self._members.get(rank)
        if epoch is None:
            if current is not None:
                return (
                    f"rank {rank} already has a live member (epoch "
                    f"{current.epoch}); a duplicate fresh join is fenced"
                )
            return None
        if current is not None and current.epoch == int(epoch):
            # the reconnect path: same incarnation resuming after a
            # transient drop — replace the connection
            with contextlib.suppress(OSError):
                current.conn.close()
            profiling.incr_counter("cp.net.reconnects")
            return None
        latest = self._next_epoch.get(rank, 0)
        return (
            f"epoch {epoch} is stale (latest incarnation is {latest}); "
            "a previous-incarnation zombie is fenced"
        )

    # -- frame dispatch -------------------------------------------------------
    def _dispatch(
        self, member: _Member, ftype: bytes, meta: Dict[str, Any], blob: bytes
    ) -> None:
        if ftype == _PING:
            self._send_to(member.conn, member.send_lock, _PONG, {})
        elif ftype == _GATHER:
            self._on_gather(member, int(meta["round"]), blob)
        elif ftype == _ABORT:
            self._on_abort(member.rank, blob)
        elif ftype == _HEALTH:
            with self._lock:
                self._health[member.rank] = blob.decode("utf-8")
        elif ftype == _READ_HEALTH:
            with self._lock:
                snap = {str(r): p for r, p in self._health.items()}
            self._send_to(member.conn, member.send_lock, _HEALTH_SNAPSHOT,
                          {"seq": meta["seq"], "health": snap})
        elif ftype == _ALLOC_PORT:
            port = self._allocate_port()
            self._send_to(member.conn, member.send_lock, _PORT,
                          {"seq": meta["seq"], "port": port})
        elif ftype == _GATHER_STATE:
            # on-demand progress introspection: ONLY a timing-out client
            # asks (a per-post broadcast would cost nranks^2 frames per
            # round on the happy path for data read once per failure)
            with self._lock:
                posted = sorted(self._rounds.get(int(meta["round"]), {}))
            self._send_to(member.conn, member.send_lock, _HEALTH_SNAPSHOT,
                          {"seq": meta["seq"], "posted": posted})
        else:
            raise ProtocolError(f"unknown frame type {ftype!r}")

    def _on_gather(self, member: _Member, round_no: int, payload: bytes) -> None:
        complete = None
        with self._lock:
            posts = self._rounds.setdefault(round_no, {})
            posts[member.rank] = payload
            if len(posts) == self._nranks:
                complete = [posts[r] for r in range(self._nranks)]
                del self._rounds[round_no]
            targets = list(self._members.values())
        if complete is not None:
            blob = b"".join(complete)
            meta = {"round": round_no, "counts": [len(p) for p in complete]}
            for m in targets:
                self._send_to(m.conn, m.send_lock, _GATHER_RESULT, meta, blob)

    def _on_abort(self, rank: int, payload: bytes) -> None:
        with self._lock:
            self._aborts[rank] = payload
            targets = [m for r, m in self._members.items() if r != rank]
        profiling.incr_counter("cp.net.pushed_aborts")
        for m in targets:
            self._send_to(m.conn, m.send_lock, _ABORT_PUSH, {"rank": rank},
                          payload)

    def _allocate_port(self) -> int:
        """Reserve a currently-free port and record it in the hand-out
        ledger: two sessions served by this coordinator can never receive
        the same port, which is the race _free_port() could not close.
        (A process OUTSIDE the coordinator's tenancy can still grab it —
        the ledger removes the common intra-job race, not the OS.)"""
        for _ in range(128):
            with socket.socket() as s:
                s.bind((self._host, 0))
                port = s.getsockname()[1]
            with self._lock:
                if port not in self._handed_ports:
                    self._handed_ports.add(port)
                    profiling.incr_counter("cp.net.alloc_ports")
                    return port
        raise RuntimeError("coordinator could not reserve a fresh port")

    # -- membership ----------------------------------------------------------
    def _remove_member(self, rank: int, epoch: int) -> None:
        with self._lock:
            m = self._members.get(rank)
            if m is not None and m.epoch == epoch:
                del self._members[rank]

    def _declare_dead(self, member: _Member, reason: str) -> None:
        rank = member.rank
        with self._lock:
            if self._members.get(rank) is not member or rank in self._dead:
                return  # a superseded conn of a resumed member, or already dead
            del self._members[rank]
            self._dead[rank] = reason
            # the dead incarnation's epoch is now fenced: _fence_reason
            # rejects any rejoin for a dead rank this session
            targets = list(self._members.values())
        profiling.incr_counter("cp.net.dead_pushes")
        _log.error("coordinator: rank %d declared dead: %s", rank, reason)
        # tell the FENCED member first (a lease-expired-but-resumed rank
        # must learn it was fenced, not keep posting), then sever its
        # connection so its frames can never land in a round again — the
        # enforcement half of "never silently readmitted"
        self._send_to(member.conn, member.send_lock, _DEAD_PUSH,
                      {"rank": rank, "reason": reason})
        with contextlib.suppress(OSError):
            member.conn.close()
        for m in targets:
            self._send_to(m.conn, m.send_lock, _DEAD_PUSH,
                          {"rank": rank, "reason": reason})

    def _scan_loop(self) -> None:
        poll = max(0.01, self._lease_s / 4.0)
        while not self._stop.wait(poll):
            now = time.monotonic()
            with self._lock:
                expired = [
                    (m, now - m.last_seen)
                    for m in self._members.values()
                    if now - m.last_seen > self._lease_s
                ]
            for m, age in expired:
                profiling.incr_counter("cp.net.lease_expiries")
                self._declare_dead(
                    m,
                    f"lease expired ({age:.2f}s > {self._lease_s}s without "
                    f"a frame; {LEASE_ENV}) — killed, wedged, or partitioned",
                )

    def _send_to(
        self, conn: socket.socket, lock: threading.Lock,
        ftype: bytes, meta: Dict[str, Any], blob: bytes = b"",
    ) -> None:
        frame = _pack_frame(ftype, meta, blob)
        try:
            with lock:
                _send_all(conn, frame, deadline_s=max(10.0, 4 * self._lease_s))
        except OSError:
            # the member is gone or stopped draining; a partially-written
            # frame would desync the stream, so the connection must DIE —
            # its reader thread then owns the death diagnosis
            with contextlib.suppress(OSError):
                conn.close()


# -- the client plane ---------------------------------------------------------


class TcpControlPlane:
    """ControlPlane over one coordinator socket: the srml-wire counterpart
    of FileControlPlane, same surface, same injection sites (cp.gather /
    cp.barrier) plus the wire sites (cp.net.send / cp.net.recv).

    All waits are bounded: gathers by the per-round SRML_CP_ROUND_TIMEOUT_S
    budget (raising the typed ControlPlaneTimeout naming the missing
    ranks), request/response frames by the lease.  Remote failures arrive
    as coordinator pushes and surface as RemoteRankError (abort marker or
    expired lease, naming the rank) or StaleEpochError (this process was
    fenced); a lost coordinator raises CoordinatorLost."""

    def __init__(
        self,
        address: str,
        rank: int,
        nranks: int,
        timeout: Optional[float] = None,
        resume_epoch: Optional[int] = None,
        owned_server: Optional[CoordinatorServer] = None,
        addr_file: Optional[str] = None,
    ):
        self._address = address
        self._rank = int(rank)
        self._nranks = int(nranks)
        self._timeout = (
            timeout
            if timeout is not None
            else _env_float(ROUND_TIMEOUT_ENV, _DEFAULT_ROUND_TIMEOUT_S)
        )
        self._retry = RetryPolicy.from_env()
        self._jitter = random.Random(20011 + rank)  # seeded: graftlint R4
        self._lease_s = lease_interval_s()
        self._owned_server = owned_server
        self._addr_file = addr_file
        self._round = 0
        self._seq = 0
        self._epoch: Optional[int] = resume_epoch
        self._closed = False
        self._stop = threading.Event()
        self._send_lock = sanitize.lockdep_lock("net.plane.send")
        self._lock = sanitize.lockdep_lock("net.plane.state")
        self._wake = threading.Condition(self._lock)
        self._results: Dict[int, List[bytes]] = {}
        self._abort: Optional[Dict[str, Any]] = None
        self._dead: Optional[Tuple[int, str]] = None
        self._fenced: Optional[str] = None
        self._lost: Optional[str] = None
        self._health: Dict[int, str] = {}
        self._replies: Dict[int, Dict[str, Any]] = {}
        self._last_rx = time.monotonic()

        host, port = address.rsplit(":", 1)
        # transient connect failures (coordinator still binding, SYN drops
        # under churn) retry with the shared SRML_CP_RETRIES/BACKOFF
        # policy; EXHAUSTION surfaces typed (never a bare socket error —
        # the module contract the chaos workers key their exit codes on)
        try:
            self._sock = self._retry.run(
                lambda: socket.create_connection(
                    (host, int(port)), timeout=10.0
                ),
                self._jitter,
            )
        except OSError as exc:
            raise CoordinatorLost(
                address,
                f"connect failed after {self._retry.retries} retries: {exc}",
            ) from exc
        self._sock.settimeout(_IDLE_POLL_S)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._hello()
        self._rx_thread = threading.Thread(
            target=self._recv_loop, name=f"srml-netcp-rx-r{rank}", daemon=True
        )
        self._rx_thread.start()
        self._ping_thread = threading.Thread(
            target=self._ping_loop, name=f"srml-netcp-ping-r{rank}",
            daemon=True,
        )
        self._ping_thread.start()

    # -- bootstrap ------------------------------------------------------------
    def _hello(self) -> None:
        _send_all(self._sock, _pack_frame(_HELLO, {
            "rank": self._rank, "nranks": self._nranks, "epoch": self._epoch,
        }), deadline_s=max(self._timeout, 10.0))
        deadline = time.monotonic() + max(self._timeout, 10.0)
        got = None
        while got is None:
            if time.monotonic() > deadline:
                raise CoordinatorLost(self._address, "no HELLO reply")
            try:
                got = _read_frame(self._sock, self._stop)
            except OSError as exc:
                raise CoordinatorLost(
                    self._address, f"connection lost during handshake: {exc}"
                ) from exc
        ftype, meta, _blob, _raw = got
        if ftype == _FENCED:
            with contextlib.suppress(OSError):
                self._sock.close()
            raise StaleEpochError(
                self._rank, meta.get("stale_epoch"),
                meta.get("reason", "fenced"),
            )
        if ftype != _WELCOME:
            raise ProtocolError(f"expected WELCOME, got {ftype!r}")
        self._epoch = int(meta["epoch"])
        self._lease_s = float(meta.get("lease_s", self._lease_s))

    @property
    def epoch(self) -> int:
        """This incarnation's session epoch (the fencing token)."""
        return int(self._epoch)

    # -- wire I/O (the cp.net.* fault sites) ----------------------------------
    def _send_frame(
        self, ftype: bytes, meta: Dict[str, Any], blob: bytes = b""
    ) -> None:
        if self._closed:
            # one plane = one session: close() tears the membership down
            # (LEAVE + fenced epoch semantics); silently reusing the dead
            # socket would surface as a misleading CoordinatorLost
            raise RuntimeError(
                f"TcpControlPlane rank {self._rank} is closed — build a "
                "new plane for a new session (distributed_session closes "
                "the plane it is given at teardown)"
            )
        frame = _pack_frame(ftype, meta, blob)
        frame = faults.site("cp.net.send", rank=self._rank, payload=frame)
        if frame is faults.DROPPED:
            profiling.incr_counter("cp.net.drops")
            return  # the wire ate it (injected loss / partition)
        profiling.incr_counter("cp.net.sends")
        profiling.incr_counter("cp.net.bytes_out", len(frame))
        try:
            with self._send_lock:
                _send_all(self._sock, frame, deadline_s=self._timeout)
        except OSError as exc:
            self._note_lost(f"send failed: {exc}")
            self._raise_if_failed()
            raise CoordinatorLost(self._address, f"send failed: {exc}")

    def _recv_loop(self) -> None:
        while not self._stop.is_set():
            try:
                got = _read_frame(self._sock, self._stop)
            except (OSError, ProtocolError) as exc:
                if not self._stop.is_set():
                    self._note_lost(str(exc))
                return
            now = time.monotonic()
            if got is None:
                # idle: a silent coordinator past 2 leases is lost (the
                # inbound half of a partition; PONGs refresh this)
                if now - self._last_rx > 2 * self._lease_s:
                    self._note_lost(
                        f"no frames for {now - self._last_rx:.2f}s "
                        f"(> 2x lease {self._lease_s}s) — coordinator dead "
                        "or this host partitioned"
                    )
                    return
                continue
            _ftype, _meta, _blob, raw = got
            raw = faults.site("cp.net.recv", rank=self._rank, payload=raw)
            if raw is faults.DROPPED:
                profiling.incr_counter("cp.net.drops")
                continue
            profiling.incr_counter("cp.net.recvs")
            profiling.incr_counter("cp.net.bytes_in", len(raw))
            try:
                ftype, meta, blob = _reparse_frame(raw)
            except ProtocolError as exc:
                self._note_lost(f"corrupt frame from coordinator: {exc}")
                return
            self._last_rx = now
            self._on_frame(ftype, meta, blob)

    def _on_frame(self, ftype: bytes, meta: Dict[str, Any], blob: bytes) -> None:
        with self._wake:
            if ftype == _GATHER_RESULT:
                counts = meta["counts"]
                out, off = [], 0
                for c in counts:
                    out.append(blob[off: off + int(c)])
                    off += int(c)
                self._results[int(meta["round"])] = out
            elif ftype == _ABORT_PUSH:
                info: Dict[str, Any] = {"rank": int(meta["rank"])}
                with contextlib.suppress(ValueError, UnicodeDecodeError):
                    decoded = json.loads(blob.decode("utf-8"))
                    if isinstance(decoded, dict):
                        info = decoded
                        info.setdefault("rank", int(meta["rank"]))
                self._abort = info
            elif ftype == _DEAD_PUSH:
                rank, reason = int(meta["rank"]), meta.get("reason", "dead")
                if rank == self._rank:
                    # the coordinator thinks WE are dead: we are fenced
                    self._fenced = reason
                elif self._dead is None:
                    self._dead = (rank, reason)
            elif ftype in (_HEALTH_SNAPSHOT, _PORT):
                # request/response mailbox: the whole meta is the reply
                self._replies[int(meta["seq"])] = meta
            elif ftype == _PONG:
                pass
            else:
                self._lost = f"unknown frame type {ftype!r} from coordinator"
            self._wake.notify_all()

    def _note_lost(self, reason: str) -> None:
        with self._wake:
            if self._lost is None:
                self._lost = reason
            self._wake.notify_all()

    def _ping_loop(self) -> None:
        period = max(0.01, self._lease_s / 3.0)
        while not self._stop.wait(period):
            try:
                self._send_frame(_PING, {})
            except Exception as exc:  # noqa: BLE001 - lease keep-alive only
                # typed failures (CoordinatorLost / RemoteRankError /
                # injected faults) surface from the WAITING ops; the
                # pinger's job is just to stop refreshing a dead link
                _log.debug("lease ping stopped: %s", exc)
                return

    # -- failure surfacing ----------------------------------------------------
    def _raise_if_failed(self) -> None:
        """Surface any pushed failure, most specific first.  Caller need
        not hold the lock (reads are single-assignment)."""
        if self._abort is not None:
            info = self._abort
            profiling.incr_counter("cp.remote_aborts")
            raise RemoteRankError(
                rank=int(info.get("rank", -1)),
                message=info.get("message", "aborted"),
                span=info.get("span"),
                etype=info.get("etype"),
            )
        if self._dead is not None:
            rank, reason = self._dead
            profiling.incr_counter("cp.dead_peers")
            raise RemoteRankError(rank=rank, message=reason)
        if self._fenced is not None:
            raise StaleEpochError(self._rank, self._epoch, self._fenced)
        if self._lost is not None:
            raise CoordinatorLost(self._address, self._lost)

    # -- the ControlPlane surface ---------------------------------------------
    def allGather(self, message: str) -> List[str]:
        return [
            b.decode("utf-8")
            for b in self._gather_round(message.encode("utf-8"))
        ]

    def allGatherBytes(self, message: bytes) -> List[bytes]:
        return self._gather_round(message)

    def _gather_round(self, message: bytes) -> List[bytes]:
        r = self._round
        self._round += 1
        message = faults.site("cp.gather", rank=self._rank, payload=message)
        self._send_frame(_GATHER, {"round": r, "rank": self._rank}, message)
        deadline = time.monotonic() + self._timeout
        with self._wake:
            while r not in self._results and time.monotonic() <= deadline:
                self._raise_if_failed()
                self._wake.wait(timeout=0.05)
            out = self._results.pop(r, None)
        if out is not None:
            return out
        # timed out: ask the coordinator who never posted (on demand — a
        # per-post broadcast would cost nranks^2 frames per happy round),
        # re-check for a result that raced the query, then raise typed
        self._raise_if_failed()
        missing = self._query_missing(r)
        with self._wake:
            out = self._results.pop(r, None)
        if out is not None:
            return out
        raise ControlPlaneTimeout("TcpControlPlane", r, missing, self._timeout)

    def _query_missing(self, round_no: int) -> List[int]:
        try:
            posted = set(
                self._request(_GATHER_STATE, {"round": round_no}).get(
                    "posted", []
                )
            )
        except Exception:  # noqa: BLE001 - introspection is best-effort
            posted = set()  # coordinator unreachable: report all as missing
        return sorted(set(range(self._nranks)) - {int(p) for p in posted})

    def barrier(self) -> None:
        faults.site("cp.barrier", rank=self._rank)
        self.allGather("")

    # -- request/response helpers ---------------------------------------------
    def _request(self, ftype: bytes, extra: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            self._seq += 1
            seq = self._seq
        self._send_frame(ftype, {"seq": seq, **extra})
        bound = max(2 * self._lease_s, 5.0)
        deadline = time.monotonic() + bound
        with self._wake:
            while seq not in self._replies:
                self._raise_if_failed()
                if time.monotonic() > deadline:
                    raise CoordinatorLost(
                        self._address,
                        f"no reply to {ftype!r} within {bound:.1f}s",
                    )
                self._wake.wait(timeout=0.05)
            return self._replies.pop(seq)

    # -- srml-shield abort surface --------------------------------------------
    def abort(self, payload: str) -> None:
        """Publish this rank's abort marker; the coordinator PUSHES it to
        every peer immediately — sub-RTT propagation instead of the file
        plane's 50 ms poll floor (bench_control_plane measures this)."""
        profiling.incr_counter("cp.abort_markers")
        self._send_frame(
            _ABORT, {"rank": self._rank}, payload.encode("utf-8")
        )

    def check_abort(self) -> Optional[Dict[str, Any]]:
        return self._abort

    # -- srml-watch health surface (non-collective) ---------------------------
    def publish_health(self, payload: str) -> None:
        # every frame refreshes the lease server-side, so heartbeats do
        # double duty: watch liveness AND membership lease
        self._send_frame(
            _HEALTH, {"rank": self._rank}, payload.encode("utf-8")
        )

    def read_health(self) -> Dict[int, str]:
        reply = self._request(_READ_HEALTH, {})
        return {int(r): p for r, p in reply.get("health", {}).items()}

    # -- coordinator port reservation -----------------------------------------
    def allocate_port(self) -> int:
        """A coordinator-reserved port for jax.distributed (context.py uses
        this on rank 0 when present — the rebind-race fix)."""
        return int(self._request(_ALLOC_PORT, {})["port"])

    # -- teardown -------------------------------------------------------------
    def close(self) -> None:
        """Idempotent: LEAVE best-effort, stop the pinger/receiver, close
        the socket, and (when this plane bootstrapped the coordinator) stop
        the server and reap the address file — no orphaned sockets,
        threads, or files survive a clean close."""
        if self._closed:
            return
        with contextlib.suppress(Exception):
            self._send_frame(_LEAVE, {"rank": self._rank})
        self._closed = True  # AFTER the LEAVE: _send_frame refuses once set
        self._stop.set()
        with contextlib.suppress(OSError):
            self._sock.close()
        self._ping_thread.join(timeout=5.0)
        self._rx_thread.join(timeout=5.0)
        if self._owned_server is not None:
            self._owned_server.stop()
            self._owned_server = None
        if self._addr_file is not None:
            with contextlib.suppress(OSError):
                os.remove(self._addr_file)


# -- shared-directory bootstrap ----------------------------------------------

_ADDR_FILE = "coordinator.addr"


def bootstrap_tcp_plane(
    root: str,
    rank: int,
    nranks: int,
    timeout: Optional[float] = None,
) -> TcpControlPlane:
    """Rendezvous through a shared job directory: rank 0 hosts the
    coordinator in-process and publishes host:port atomically; other ranks
    wait (bounded by the round timeout) for the address and connect.  After
    bootstrap, NOTHING rides the filesystem — every collective, heartbeat,
    and abort is wire frames (this is what the SRML_CP=tcp knob runs the
    whole multicontroller matrix on)."""
    os.makedirs(root, exist_ok=True)
    path = os.path.join(root, _ADDR_FILE)
    bound = (
        timeout
        if timeout is not None
        else _env_float(ROUND_TIMEOUT_ENV, _DEFAULT_ROUND_TIMEOUT_S)
    )
    if rank == 0:
        # a CRASHED previous session in this root never reaped its addr
        # file — unlink any leftover BEFORE starting, so no sibling can
        # rendezvous on the stale endpoint
        with contextlib.suppress(OSError):
            os.remove(path)
        server = CoordinatorServer(nranks)
        address = server.start()
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(address)
        os.replace(tmp, path)
        return TcpControlPlane(
            address, rank, nranks, timeout=timeout,
            owned_server=server, addr_file=path,
        )
    deadline = time.monotonic() + bound
    while True:
        address = ""
        with contextlib.suppress(OSError):
            with open(path) as f:
                address = f.read().strip()
        if address:
            try:
                return TcpControlPlane(address, rank, nranks, timeout=timeout)
            except CoordinatorLost:
                # a stale address from a crashed previous session (rank 0
                # unlinks it at startup, but this reader may have raced
                # that): keep polling for the fresh publication
                if time.monotonic() > deadline:
                    raise
        if time.monotonic() > deadline:
            raise ControlPlaneTimeout("TcpControlPlane bootstrap", 0, [0], bound)
        time.sleep(0.02)
