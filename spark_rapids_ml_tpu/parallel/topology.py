#
# Topology map: which devices share a host (ICI-connected) and which pairs
# can only reach each other over DCN — the physical-link dimension of the
# exchange plane (ROADMAP item 2's software half).
#
# On real multi-host TPU topology an intra-host ICI hop is an order of
# magnitude cheaper than a cross-host DCN hop, but every collective in
# parallel/exchange.py historically treated all neighbors as equal.  The
# TopologyMap derived here feeds three consumers:
#
#   * DeviceSection (parallel/exchange.py): hierarchical schedules for
#     allgather_rows / psum / psum_merge (gather within the host group,
#     ONE gateway exchange across groups, broadcast back inside the group)
#     and the gateway-aware ring_shift cycle, plus the per-link
#     `exchange.<name>.ici_bytes` / `.dcn_bytes` accounting split.
#   * ops/knn.py: the in-mesh ring/gather exchange kernels carry the map as
#     a cache-key STATIC (a topology change can never silently reuse a
#     stale executable), and distributed_kneighbors orders its host-plane
#     ring along the same two-level cycle.
#   * parallel/mesh.slice_meshes: router replica slices are carved
#     group-major so a replica never straddles a host group when the
#     device count allows.
#
# Derivation prefers real device attributes (process_index — jax's host
# grouping).  `SRML_TOPO=hosts:devs_per_host` overrides it for CI
# simulation on the virtual CPU mesh (grouping by device id), and
# `SRML_EXCHANGE_TOPO=flat` pins the topology-oblivious flat schedule —
# the parity comparator and the escape hatch, same role SRML_KNN_EXCHANGE
# plays for the route.
#
# Link accounting model (documented in docs/observability.md): the split
# counters are TRACE-TIME whole-mesh byte models per collective, not
# measured wire bytes.  A hierarchical schedule charges its intra-group
# stages to ICI and its single gateway stage to DCN; a flat schedule on a
# multi-group topology offers no locality guarantee, so ALL its traffic is
# charged to DCN (on a single-group topology everything is provably ICI).
# That conservative attribution is exactly the headline CI asserts: the
# flat ring pushes O(n_dev) unpinned frames per block per round where the
# hierarchical cycle guarantees O(n_hosts) gateway crossings.
#

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

TOPO_ENV = "SRML_TOPO"
EXCHANGE_TOPO_ENV = "SRML_EXCHANGE_TOPO"


@dataclass(frozen=True)
class TopologyMap:
    """Host-group partition of a 1-D device axis.

    `groups` holds LOGICAL axis positions (tuple per host group, groups in
    gateway order, positions ascending within a group).  Hashable with
    stable equality by value, so it can ride jit static_argnames and the
    AOT `kernel_cache_key` statics tuple directly."""

    groups: Tuple[Tuple[int, ...], ...]
    source: str = "flat"  # "process" | "env" | "flat"
    pinned: bool = False  # SRML_EXCHANGE_TOPO=flat held at derivation time

    @property
    def n_devices(self) -> int:
        return sum(len(g) for g in self.groups)

    @property
    def n_groups(self) -> int:
        return len(self.groups)

    @property
    def group_size(self) -> int:
        """Uniform group size, or 0 when groups are unequal (a shape the
        hierarchical schedules refuse — they fall back to flat)."""
        sizes = {len(g) for g in self.groups}
        return sizes.pop() if len(sizes) == 1 else 0

    @property
    def group_of(self) -> Tuple[int, ...]:
        out = [0] * self.n_devices
        for k, g in enumerate(self.groups):
            for p in g:
                out[p] = k
        return tuple(out)

    @property
    def gateways(self) -> Tuple[int, ...]:
        """One designated gateway position per group (the first member):
        the device that carries the group's cross-DCN exchange."""
        return tuple(g[0] for g in self.groups)

    @property
    def schedule(self) -> str:
        """"hier" when a two-level schedule is worthwhile and sound:
        more than one group, uniform group size > 1, and not pinned flat.
        Everything else degenerates to "flat"."""
        if self.pinned or self.n_groups <= 1 or self.group_size <= 1:
            return "flat"
        return "hier"

    @property
    def is_hierarchical(self) -> bool:
        return self.schedule == "hier"

    def describe(self) -> str:
        """Stable topology string for bench artifacts and logs,
        e.g. "2x4/hier", "1x8/flat", "2x4/flat-pinned"."""
        g = self.group_size
        shape = f"{self.n_groups}x{g}" if g else "x".join(
            str(len(g)) for g in self.groups
        )
        sched = self.schedule + ("-pinned" if self.pinned else "")
        return f"{shape}/{sched}"


def flat_topology(n_devices: int) -> TopologyMap:
    """The trivial single-group map (every device one ICI domain)."""
    return TopologyMap(groups=(tuple(range(n_devices)),), source="flat")


def _pinned_flat() -> bool:
    return os.environ.get(EXCHANGE_TOPO_ENV, "").strip().lower() == "flat"


def _parse_override() -> Optional[int]:
    """SRML_TOPO=hosts:devs_per_host → devs_per_host (the physical
    grouping stride; `hosts` documents intent and is sanity-checked only).
    Malformed specs raise: a typo'd topology silently simulating flat
    would invalidate every gate that depends on it."""
    spec = os.environ.get(TOPO_ENV, "").strip()
    if not spec:
        return None
    try:
        hosts_s, devs_s = spec.split(":")
        hosts, devs = int(hosts_s), int(devs_s)
    except ValueError:
        raise ValueError(
            f"{TOPO_ENV}={spec!r}: expected 'hosts:devs_per_host'"
        )
    if hosts < 1 or devs < 1:
        raise ValueError(f"{TOPO_ENV}={spec!r}: both fields must be >= 1")
    return devs


def _group_positions(keys: Sequence[Any]) -> Tuple[Tuple[int, ...], ...]:
    """Partition positions 0..n-1 by key; groups ordered by sorted key,
    positions ascending within each group."""
    by_key: dict = {}
    for pos, k in enumerate(keys):
        by_key.setdefault(k, []).append(pos)
    return tuple(tuple(by_key[k]) for k in sorted(by_key))


def topology_map(
    mesh: Any = None,
    devices: Optional[Sequence[Any]] = None,
    n_devices: Optional[int] = None,
) -> TopologyMap:
    """The ONE TopologyMap derivation, shared by the exchange plane, the
    kNN dispatch/warm key derivation, slice_meshes, and the host-plane
    ring.  Pass exactly one of `mesh` (1-D data mesh), `devices` (an
    explicit device list — positions are list positions), or `n_devices`
    (host ranks: no device attributes, env override only).

    Priority: `SRML_TOPO=hosts:devs_per_host` simulation override (groups
    by device id — or by position when ids are unavailable — so a shuffled
    device list is genuinely non-contiguous), then device process_index
    (jax's host grouping), then flat.  `SRML_EXCHANGE_TOPO=flat` keeps the
    derived groups (link attribution stays honest) but pins the schedule
    flat."""
    pinned = _pinned_flat()
    if mesh is not None:
        devices = list(mesh.devices.flat)
    if devices is not None:
        n = len(devices)
    elif n_devices is not None:
        n = int(n_devices)
    else:
        raise ValueError("topology_map needs a mesh, devices, or n_devices")
    if n <= 0:
        raise ValueError(f"topology_map: need at least one device, got {n}")

    devs_per_host = _parse_override()
    if devs_per_host is not None:
        if devices is not None:
            keys = [
                int(getattr(d, "id", pos)) // devs_per_host
                for pos, d in enumerate(devices)
            ]
        else:
            keys = [pos // devs_per_host for pos in range(n)]
        groups = _group_positions(keys)
        return TopologyMap(groups=groups, source="env", pinned=pinned)

    if devices is not None:
        procs = [getattr(d, "process_index", 0) for d in devices]
        if len(set(procs)) > 1:
            return TopologyMap(
                groups=_group_positions(procs), source="process",
                pinned=pinned,
            )
    return TopologyMap(
        groups=(tuple(range(n)),), source="flat", pinned=pinned
    )


def ring_cycle(topo: TopologyMap, shift: int = 1) -> List[Tuple[int, int]]:
    """Topology-aware ring permutation: a single n-cycle that tours each
    host group's devices consecutively over ICI with exactly ONE gateway
    edge per adjacent group pair crossing DCN.  Same (src, dst) pair
    format as mesh.ring_permutation — which remains the flat definition
    (and what this degenerates to when groups are contiguous).  Applied
    every hop, a block visits all n devices and is home after n hops,
    which is all the lex-merge exchange kernels require — visit ORDER is
    irrelevant under a total-order merge."""
    order = [p for g in topo.groups for p in g]
    n = len(order)
    nxt = {order[j]: order[(j + shift) % n] for j in range(n)}
    return [(p, nxt[p]) for p in range(n)]


# -- per-link byte models ------------------------------------------------------
# Whole-mesh trace-time byte split per collective, from the SCHEDULE the
# collective actually runs (see module header for the attribution rule).
# `nbytes` is the per-shard payload (the same quantity the legacy
# `exchange.<name>.bytes` counter records).


def _flat_split(topo: TopologyMap, total: int) -> Tuple[int, int]:
    if topo.n_groups <= 1:
        return total, 0
    return 0, total


def link_split_gather(topo: TopologyMap, nbytes: int) -> Tuple[int, int]:
    """(ici, dcn) for the gather-class collectives (allgather_rows,
    gather_stack, psum_merge): every shard's block must reach every
    device.  Flat: n*(n-1) block movements, unpinned to any link class.
    Hierarchical: intra-group gather, one g-block frame per ordered group
    pair over DCN, gateway rebroadcast of the foreign bytes over ICI."""
    n = topo.n_devices
    if n <= 1:
        return 0, 0
    if not topo.is_hierarchical:
        return _flat_split(topo, n * (n - 1) * nbytes)
    G, g = topo.n_groups, topo.group_size
    ici = n * (g - 1) * nbytes + G * (g - 1) * (n - g) * nbytes
    dcn = G * (G - 1) * g * nbytes
    return ici, dcn


def link_split_reduce(topo: TopologyMap, nbytes: int) -> Tuple[int, int]:
    """(ici, dcn) for psum: like the gather class, but the cross-group
    frame is the group-REDUCED partial (one block, not g)."""
    n = topo.n_devices
    if n <= 1:
        return 0, 0
    if not topo.is_hierarchical:
        return _flat_split(topo, n * (n - 1) * nbytes)
    G, g = topo.n_groups, topo.group_size
    ici = n * (g - 1) * nbytes + G * (g - 1) * nbytes
    dcn = G * (G - 1) * nbytes
    return ici, dcn


def link_split_ring_hop(topo: TopologyMap, nbytes: int) -> Tuple[int, int]:
    """(ici, dcn) for ONE ring_shift hop: n simultaneous block sends.
    The hierarchical cycle pins all but the G gateway edges to ICI; the
    flat rotation pins nothing."""
    n = topo.n_devices
    if n <= 1:
        return 0, 0
    if not topo.is_hierarchical:
        return _flat_split(topo, n * nbytes)
    G = topo.n_groups
    return (n - G) * nbytes, G * nbytes


def group_major_devices(devices: Sequence[Any]) -> List[Any]:
    """Reorder a device list group-major (each host group's devices
    consecutive), preserving in-group order — the slice_meshes carve
    order, so contiguous slices never straddle a host group when the
    count allows.  No-op on flat/unknown topologies."""
    topo = topology_map(devices=list(devices))
    if topo.n_groups <= 1:
        return list(devices)
    return [devices[p] for g in topo.groups for p in g]
