#
# Device-mesh construction and row-sharded ingest.
#
# TPU-native replacement for the reference's GPU binding + cuDF ingest
# (/root/reference/python/src/spark_rapids_ml/core.py:233-259 device binding,
# :558-632 Arrow->cupy ingest).  Instead of "1 Spark task = 1 GPU = 1 NCCL
# rank", the unit of parallelism is a jax.sharding.Mesh over all addressable
# devices: within one host the mesh rides ICI; across hosts jax.distributed +
# DCN extends the same mesh (see parallel/context.py).  Data parallelism is
# expressed by sharding the row axis with NamedSharding(P("data")) and letting
# GSPMD insert psum/all_gather collectives during compilation.
#

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
MODEL_AXIS = "model"


def default_num_workers() -> int:
    """One logical worker per addressable device (chips on this host, or the
    whole pod under jax.distributed)."""
    return jax.device_count()


def get_mesh(num_workers: Optional[int] = None) -> Mesh:
    """1-D data-parallel mesh over the first `num_workers` devices."""
    devices = jax.devices()
    n = num_workers or len(devices)
    n = min(n, len(devices))
    return Mesh(np.array(devices[:n]), (DATA_AXIS,))


def get_2d_mesh(num_data: int, num_model: int) -> Mesh:
    """(data, model) mesh for feature-axis sharding of very wide problems
    (e.g. X^T X when n_cols is huge) — the GSPMD generalization noted in
    SURVEY.md §2.4."""
    devices = np.array(jax.devices()[: num_data * num_model]).reshape(
        num_data, num_model
    )
    return Mesh(devices, (DATA_AXIS, MODEL_AXIS))


def data_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(DATA_AXIS))


def col_sharding(mesh: Mesh) -> NamedSharding:
    """Shard axis 1 (columns) over the data axis.  The UMAP layout engine
    keeps its edge arrays in transposed (P, n) component-sliced form (minor
    dimension = nodes, for full TPU lanes); sharding the NODE axis there
    means sharding columns, so each device owns a contiguous head block."""
    return NamedSharding(mesh, PartitionSpec(None, DATA_AXIS))


def axis_sharding(mesh: Mesh, axis: int, ndim: int) -> NamedSharding:
    """Shard one axis of an `ndim`-rank array over the data axis (the
    generic form of data_sharding/col_sharding).  The forest engine shards
    its (T, N) routing state and (T, N, S) per-tree stats on the ROW axis
    (axis=1) so every per-shard histogram pass sees row-aligned slices of
    bins, stats and node ids."""
    spec = [None] * ndim
    spec[axis] = DATA_AXIS
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def slice_meshes(n_slices: int, devices=None) -> list:
    """Carve `n_slices` DISJOINT 1-D data meshes over the device list — the
    unit of replica parallelism for the serving router (serving/router.py)
    and the thread-mocked multicontroller ranks (ops/knn).

    Disjointness is load-bearing, not cosmetic: XLA:CPU's cross_module
    rendezvous deadlocks when two multi-device programs launched from
    different threads interleave their per-device enqueue order on SHARED
    devices, and on TPU hardware a shared slice would serialize the
    replicas on the same chips anyway.  With fewer devices than slices the
    surplus slices each get ONE device, round-robin — single-device
    programs have no cross-program rendezvous, so oversubscription degrades
    to compute contention instead of deadlock.

    The carve order is GROUP-MAJOR over the host topology
    (parallel/topology.py): devices sharing a host group come first,
    consecutively, so a contiguous slice never straddles a host group when
    the device count allows — a replica spanning DCN would pay the slow
    link on every dispatch.  On flat/unknown topologies this is the
    identity order."""
    if n_slices < 1:
        raise ValueError(f"n_slices must be >= 1, got {n_slices}")
    from . import topology

    devs = list(devices) if devices is not None else jax.devices()
    devs = topology.group_major_devices(devs)
    per = len(devs) // n_slices
    out = []
    for i in range(n_slices):
        if per >= 1:
            local = devs[i * per : (i + 1) * per]
        else:
            local = [devs[i % len(devs)]]
        out.append(Mesh(np.array(local), (DATA_AXIS,)))
    return out


def carve_device_slices(devices, slice_devices: int) -> list:
    """Carve the device list into as many DISJOINT `slice_devices`-sized
    device groups as it holds — the fixed-granularity counterpart of
    slice_meshes, and the ONE carve rule behind the serving slice pool
    (serving/slicepool.SlicePool).

    Group-aware, not merely group-major: when the host topology is known
    and a slice fits inside a host group (slice_devices <= group size),
    the carve runs PER GROUP, so no slice ever straddles a host group —
    a replica spanning DCN would pay the slow link on every dispatch.
    Devices left over inside a group (group size not a multiple of
    slice_devices) are stranded rather than glued across the boundary;
    the pool accounts for them explicitly.  A slice BIGGER than a host
    group must span DCN by construction, so the carve falls back to
    contiguous group-major runs (the whole-mesh n_slices=1 case).  On
    flat/unknown topologies this is a plain contiguous carve."""
    if slice_devices < 1:
        raise ValueError(f"slice_devices must be >= 1, got {slice_devices}")
    from . import topology

    devs = list(devices) if devices is not None else jax.devices()
    topo = topology.topology_map(devices=devs)
    out = []
    if topo.n_groups > 1 and slice_devices <= min(len(g) for g in topo.groups):
        for g in topo.groups:
            members = [devs[p] for p in g]
            for i in range(len(members) // slice_devices):
                out.append(members[i * slice_devices : (i + 1) * slice_devices])
        return out
    ordered = topology.group_major_devices(devs)
    for i in range(len(ordered) // slice_devices):
        out.append(ordered[i * slice_devices : (i + 1) * slice_devices])
    return out


def ring_permutation(n_dev: int, shift: int = 1):
    """The (source, destination) pairs of a +shift rotation along the
    1-D data mesh — the ONE definition of the mesh's ring order, used by
    parallel/exchange.DeviceSection.ring_shift (lax.ppermute fallback) so
    the XLA and remote-DMA paths agree on who "the +1 neighbor" is.

    get_mesh builds the data axis in jax.devices() order, which on a TPU
    slice enumerates chips along the physical ICI ring — so the +1 logical
    neighbor is (one hop of) the wired neighbor and a full ring pass never
    crosses the bisection.  A custom Mesh with a shuffled device order
    still computes CORRECT results (ppermute/remote-DMA route by logical
    index); it just pays longer physical paths per hop."""
    return [(i, (i + shift) % n_dev) for i in range(n_dev)]


# Row-pad multiple shared by sharded kernels whose RNG streams index GLOBAL
# padded positions (the UMAP layout's counter-based threefry draws): padding
# to lcm(64, n_shards) keeps the padded geometry — and therefore every
# counter-derived draw — IDENTICAL across all mesh sizes that DIVIDE 64
# (every power-of-two TPU mesh up to 64 devices), which is what makes
# "fixed seed => same embedding on any such mesh" testable.  A mesh size
# that does not divide 64 (e.g. 6) raises the lcm, changing the padded
# geometry: still deterministic for that shape, just not bit-identical to
# the power-of-two shapes.
ROW_PAD_LANES = 64


def padded_row_count(n: int, mesh: Optional[Mesh] = None) -> int:
    """Rows padded up to a multiple of lcm(ROW_PAD_LANES, data-axis size)."""
    import math

    mult = ROW_PAD_LANES
    if mesh is not None:
        mult = math.lcm(mult, mesh.shape[DATA_AXIS])
    return -(-max(n, 1) // mult) * mult


def shard_rows(
    arr: np.ndarray, mesh: Mesh, dtype: Optional[np.dtype] = None
) -> Tuple[jax.Array, int]:
    """Zero-pad rows to a multiple of the data-axis size and device_put with a
    row sharding.  Returns (sharded_array, n_valid_rows).  Padded rows must be
    masked by callers via the weight vector produced in core ingest."""
    from ..utils import pad_rows

    if dtype is not None:
        arr = np.asarray(arr, dtype=dtype)
    n_valid = arr.shape[0]
    n_shards = mesh.shape[DATA_AXIS]
    padded = pad_rows(arr, n_shards)
    sharded = jax.device_put(padded, data_sharding(mesh))
    return sharded, n_valid
