#
# srml-shield: deterministic fault injection for the distributed lifecycle.
#
# PRs 7-8 built the DETECTION half of the health story (spans, flight
# recorder, stall watchdog); nothing ever exercised it: no test killed a
# rank mid-collective, so the first real process death would have been the
# production incident.  This module is the chaos-engineering half — a
# deterministic harness that makes "rank 1 dies on its 2nd gather" a
# reproducible test input instead of a 3 a.m. page (the role NCCL_BLOCKING_
# WAIT + fault-injection suites play for the reference's collective stack).
#
# Named INJECTION SITES are threaded through the layers that can hang or
# die in production:
#
#   cp.gather         FileControlPlane._gather_round / TcpControlPlane.
#                     _gather_round (every collective round, either plane)
#   cp.barrier        ControlPlane.barrier (before the empty gather)
#   cp.net.send       TcpControlPlane._send_frame — every outbound wire
#                     frame of the socket control plane (srml-wire)
#   cp.net.recv       TcpControlPlane receiver thread — every inbound wire
#                     frame, after the socket read
#   exchange.ring_pass  exchange.ring_pass_bytes (the kNN ring hop wire)
#   knn.ring_hop      ops/knn._distributed_ring (per ring rotation)
#   runner.fit        the fit task body — BOTH the barrier runner
#                     (parallel/runner.fit) and the local driver path
#                     (core._call_tpu_fit_func)
#   serving.dispatch  serving/engine.ModelServer._dispatch (tag = server name)
#   context.init      TpuContext.__enter__ (the jax.distributed bootstrap)
#
# A FaultPlan parsed from SRML_FAULTS selects WHERE (site), WHO (rank= /
# tag=), WHEN (call= — the Nth arrival at that site in this process,
# 1-based) and WHAT (action).  Grammar (docs/robustness.md):
#
#   SRML_FAULTS = spec[;spec...]
#   spec        = site[:field]...
#   field       = rank=<int> | call=<int> | tag=<str>
#               | action=(die|raise|kill|delay|corrupt|drop|partition)
#               | delay=<float s>
#
#   cp.gather:rank=1:call=2:action=die      rank 1 dies on its 2nd gather
#   serving.dispatch:tag=km:call=3:action=kill   km's worker dies, batch 3
#   exchange.ring_pass:rank=0:action=corrupt     rank 0's frames flip bytes
#   cp.barrier:rank=2:delay=5                    rank 2 stalls 5 s per barrier
#   cp.net.send:rank=1:call=5:action=partition   rank 1 partitioned from
#                                                frame 5 onward (both ways)
#
# Actions:
#   die      os._exit(17): the process vanishes mid-protocol — no abort
#            marker, no teardown, exactly what SIGKILL / an OOM kill leaves
#            behind.  Survivors must detect it through the control plane's
#            dead-peer scan (runner.FileControlPlane).
#   raise    raise FaultInjected at the site: the orderly failure — the
#            exception unwinds through TpuContext.__exit__, which broadcasts
#            the abort marker (the NCCL-abort analog).
#   kill     raise InjectedWorkerDeath (a BaseException): kills the CURRENT
#            WORKER THREAD but not the process — the serving supervisor's
#            restart path is the intended catcher.
#   delay    sleep delay seconds, then continue (wedge simulation: drives
#            the stall watchdog and the serving wedge detector).
#   corrupt  flip bytes in the site's payload (frame corruption on the
#            wire; the receiver's codec must fail loudly, never decode
#            garbage silently).
#   drop     return the DROPPED sentinel instead of the payload: the wire
#            site discards this one frame (packet loss).  Valid ONLY at
#            cp.net.* sites (strictly enforced at parse time) — callers
#            there check `is DROPPED`; a dropped collective payload
#            anywhere else would have no silent recovery.
#   partition  like drop, but STICKY: from this arrival on, EVERY cp.net.*
#            frame for the matched rank is dropped in both directions —
#            the network-partition shape.  The rank falls silent without
#            dying; survivors must detect it through lease expiry, and the
#            partitioned rank itself loses the coordinator.
#
# THE UNARMED PATH IS FREE: with SRML_FAULTS unset, _PLAN is None and
# site() is one module-global load + one `is None` branch — no env read, no
# lock, no allocation, the same discipline as watch.py's disabled recorder
# (gated structurally in tests/test_faults.py).
#
# Parsing is STRICT: a typo'd plan raises ValueError at import/reload time
# instead of silently disarming — a chaos gate that cannot fire is worse
# than one that fails loudly.
#

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

_log = logging.getLogger("spark_rapids_ml_tpu.faults")

FAULTS_ENV = "SRML_FAULTS"

# exit code of action=die: distinct from every interpreter/pytest code so a
# chaos driver can assert the victim died BY INJECTION, not by accident
DIE_EXIT_CODE = 17

# the documented site registry (docs/robustness.md table).  site() accepts
# any name — sites are strings, not an enum — but parse_plan() warns on a
# spec naming a site outside this registry, which catches the typo'd plan
# that would otherwise never fire.
SITES = (
    "cp.gather",
    "cp.barrier",
    "cp.net.send",
    "cp.net.recv",
    "exchange.ring_pass",
    "knn.ring_hop",
    "runner.fit",
    "serving.dispatch",
    "context.init",
)

_ACTIONS = ("die", "raise", "kill", "delay", "corrupt", "drop", "partition")

# wire sites share one sticky partition set: a partition armed at either
# direction silences BOTH (a real partition has no half-duplex)
_WIRE_PREFIX = "cp.net."


class _Dropped:
    """Singleton sentinel returned by action=drop/partition at wire sites:
    the caller discards the frame (send skips the write, recv skips the
    dispatch).  Identity-checked (`is DROPPED`), never equality."""

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "<faults.DROPPED>"


DROPPED = _Dropped()


class FaultInjected(RuntimeError):
    """Raised at an injection site by action=raise (and by action=corrupt
    at a site with no byte payload to corrupt)."""

    def __init__(self, site: str, rank: Optional[int], call: int):
        self.site = site
        self.rank = rank
        self.call = call
        super().__init__(
            f"injected fault at site {site!r} (rank={rank}, call #{call})"
        )


class InjectedWorkerDeath(BaseException):
    """action=kill: deliberately NOT an Exception, so per-batch error
    relays (which catch Exception) let it escape and kill the enclosing
    worker thread — the serving supervisor's restart path catches it at
    the thread's top frame."""

    def __init__(self, site: str, call: int):
        self.site = site
        self.call = call
        super().__init__(f"injected worker death at site {site!r} (call #{call})")


@dataclass(frozen=True)
class FaultSpec:
    """One armed fault: WHERE/WHO/WHEN/WHAT (module docstring grammar)."""

    site: str
    action: str
    rank: Optional[int] = None     # None = any rank
    call: Optional[int] = None     # None = every arrival; N = the Nth only
    tag: Optional[str] = None      # None = any tag (serving: server name)
    delay_s: float = 0.0

    def matches(self, rank: Optional[int], tag: Optional[str], count: int) -> bool:
        if self.rank is not None and self.rank != rank:
            return False
        if self.tag is not None and self.tag != tag:
            return False
        if self.call is not None and self.call != count:
            return False
        return True


def _parse_spec(text: str) -> FaultSpec:
    parts = [p for p in text.strip().split(":") if p]
    if not parts:
        raise ValueError(f"empty fault spec in {FAULTS_ENV}")
    site = parts[0]
    if site not in SITES:
        # not fatal — new sites may outrun the registry — but loud: a
        # typo'd site is a chaos gate that never fires
        _log.warning(
            "%s names unknown site %r (registered: %s) — this fault will "
            "only fire if code calls faults.site(%r)",
            FAULTS_ENV, site, ", ".join(SITES), site,
        )
    fields: Dict[str, str] = {}
    for f in parts[1:]:
        if "=" not in f:
            raise ValueError(
                f"{FAULTS_ENV}: malformed field {f!r} in spec {text!r} "
                "(expected key=value)"
            )
        k, v = f.split("=", 1)
        if k not in ("rank", "call", "tag", "action", "delay"):
            raise ValueError(
                f"{FAULTS_ENV}: unknown field {k!r} in spec {text!r} "
                "(rank/call/tag/action/delay)"
            )
        fields[k] = v
    action = fields.get("action")
    delay_s = float(fields["delay"]) if "delay" in fields else 0.0
    if action is None:
        if "delay" not in fields:
            raise ValueError(
                f"{FAULTS_ENV}: spec {text!r} has no action= (and no "
                f"delay= shorthand); actions: {'/'.join(_ACTIONS)}"
            )
        action = "delay"
    if action not in _ACTIONS:
        raise ValueError(
            f"{FAULTS_ENV}: unknown action {action!r} in spec {text!r} "
            f"(one of {'/'.join(_ACTIONS)})"
        )
    if action == "delay" and delay_s <= 0:
        raise ValueError(
            f"{FAULTS_ENV}: action=delay needs delay=<seconds> in {text!r}"
        )
    if action in ("drop", "partition") and not site.startswith(_WIRE_PREFIX):
        raise ValueError(
            f"{FAULTS_ENV}: action={action} only applies to wire sites "
            f"({_WIRE_PREFIX}*) — {text!r} would silently vanish a "
            "collective payload"
        )
    return FaultSpec(
        site=site,
        action=action,
        rank=int(fields["rank"]) if "rank" in fields else None,
        call=int(fields["call"]) if "call" in fields else None,
        tag=fields.get("tag"),
        delay_s=delay_s,
    )


class FaultPlan:
    """Every armed FaultSpec plus the per-(site, tag) arrival counters that
    make call= selection deterministic (counters are per-process: each rank
    of a multi-process job counts its own arrivals)."""

    def __init__(self, specs: List[FaultSpec]):
        self.specs = list(specs)
        self._lock = threading.Lock()
        self._counts: Dict[Tuple[str, Optional[str]], int] = {}
        # ranks whose cp.net.* traffic is sticky-dropped (action=partition)
        self._partitioned: set = set()

    def counts(self) -> Dict[Tuple[str, Optional[str]], int]:
        with self._lock:
            return dict(self._counts)

    def partitioned(self) -> set:
        with self._lock:
            return set(self._partitioned)

    def fire(self, name: str, rank: Optional[int], tag: Optional[str], payload):
        key = (name, tag)
        with self._lock:
            self._counts[key] = count = self._counts.get(key, 0) + 1
            if name.startswith(_WIRE_PREFIX) and rank in self._partitioned:
                return DROPPED  # the partition swallows both directions
        for spec in self.specs:
            if spec.site != name or not spec.matches(rank, tag, count):
                continue
            return self._apply(spec, name, rank, count, payload)
        return payload

    def _apply(self, spec: FaultSpec, name: str, rank, count: int, payload):
        _log.error(
            "FAULT INJECTED: site=%s rank=%s call=%d action=%s",
            name, rank, count, spec.action,
        )
        if spec.action == "die":
            # simulate SIGKILL/OOM: no marker, no teardown, no flush —
            # survivors must detect the absence, not a message
            os._exit(DIE_EXIT_CODE)
        if spec.action == "raise":
            raise FaultInjected(name, rank, count)
        if spec.action == "kill":
            raise InjectedWorkerDeath(name, count)
        if spec.action == "delay":
            time.sleep(spec.delay_s)
            return payload
        if spec.action == "drop":
            return DROPPED
        if spec.action == "partition":
            with self._lock:
                self._partitioned.add(rank)
            return DROPPED
        # corrupt: flip bytes in the payload; a site with nothing to
        # corrupt degrades to the orderly failure
        if not isinstance(payload, (bytes, bytearray)) or len(payload) == 0:
            raise FaultInjected(name, rank, count)
        buf = bytearray(payload)
        buf[0] ^= 0xFF                  # kill any magic header
        buf[len(buf) // 2] ^= 0xFF      # and a body byte
        return bytes(buf)


def parse_plan(text: Optional[str]) -> Optional[FaultPlan]:
    if not text or not text.strip():
        return None
    specs = [_parse_spec(s) for s in text.split(";") if s.strip()]
    if not specs:
        return None
    return FaultPlan(specs)


def _load() -> Optional[FaultPlan]:
    return parse_plan(os.environ.get(FAULTS_ENV))


_PLAN: Optional[FaultPlan] = _load()


def site(name: str, rank: Optional[int] = None, tag: Optional[str] = None,
         payload=None):
    """The ONE injection chokepoint.  Unarmed (SRML_FAULTS unset): a single
    module-global `is None` branch, nothing else — zero overhead at every
    call site (gated structurally).  Armed: counts the arrival and applies
    the first matching spec's action; returns `payload` (possibly
    corrupted) so byte-frame sites can thread their wire payload through."""
    if _PLAN is None:
        return payload
    return _PLAN.fire(name, rank, tag, payload)


def plan() -> Optional[FaultPlan]:
    """The installed FaultPlan (None = unarmed)."""
    return _PLAN


def armed() -> bool:
    return _PLAN is not None


def reload() -> Optional[FaultPlan]:
    """Re-parse SRML_FAULTS (tests arm/disarm per-case; arrival counters
    reset with the new plan)."""
    global _PLAN
    _PLAN = _load()
    return _PLAN
