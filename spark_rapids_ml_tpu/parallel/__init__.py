from .mesh import default_num_workers, get_mesh, shard_rows
from .partition import PartitionDescriptor
from .context import ControlPlaneTimeout, RemoteRankError, TpuContext
from . import faults

__all__ = [
    "default_num_workers",
    "get_mesh",
    "shard_rows",
    "PartitionDescriptor",
    "ControlPlaneTimeout",
    "RemoteRankError",
    "TpuContext",
    "faults",
]
