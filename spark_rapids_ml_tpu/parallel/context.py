#
# Distributed-runtime lifecycle management.
#
# TPU-native replacement for the reference's CumlContext
# (/root/reference/python/src/spark_rapids_ml/common/cuml_context.py:35-192),
# which creates a raft Handle, has rank 0 mint an NCCL uid, spreads it via
# BarrierTaskContext.allGather, and injects NCCL/UCX comms.  Here the same
# three-phase shape holds, but the data plane is jax.distributed + XLA
# collectives over ICI/DCN:
#
#   1. rank 0 picks a coordinator address (host:port) — analog of the NCCL uid
#   2. the address is allGathered over the *control plane* (Spark barrier RPC
#      in the Spark adapter; trivial in single-controller local mode)
#   3. every rank calls jax.distributed.initialize(coordinator, nranks, rank);
#      afterwards jax.devices() spans the pod and a global Mesh is built, so
#      psum/all_gather/ppermute ride ICI within a host and DCN across hosts.
#
# __exit__ tears down jax.distributed the way CumlContext.__exit__ destroys or
# aborts the NCCL comm (cuml_context.py:149-166).
#

from __future__ import annotations

import json
import socket
from typing import Any, List, Optional, Protocol

import jax

from ..utils import get_logger
from . import faults


class RemoteRankError(RuntimeError):
    """Another rank of the cooperating job failed (orderly abort) or died
    (no marker — killed/OOMed) while this rank waited on a collective.
    Raised by the control plane's gather waits within one poll interval of
    the abort marker / dead pid appearing, instead of the full round
    timeout — and it NAMES the culprit: origin rank, its exception type,
    and the innermost span it was in (from the srml-watch health surface),
    so the survivor's traceback reads "rank 1 died in exchange.ring", not
    "TimeoutError after 300 s"."""

    def __init__(
        self,
        rank: int,
        message: str,
        span: Optional[str] = None,
        etype: Optional[str] = None,
    ):
        self.rank = int(rank)
        self.span = span
        self.etype = etype
        where = f" in span {span!r}" if span else ""
        what = f"{etype}: {message}" if etype else message
        super().__init__(f"remote rank {self.rank}{where}: {what}")


class ControlPlane(Protocol):
    """Minimal control-plane contract: Spark's BarrierTaskContext satisfies it
    (allGather of strings + barrier), as does the local trivial impl.

    ORDERING REQUIREMENT: allGather must return messages indexed by rank
    (result[r] = rank r's message) — Spark's BarrierTaskContext orders by
    partition id, FileControlPlane by rank-numbered files.  The binary
    collectives (parallel/exchange.py) and the kneighbors exchange index
    results positionally and would silently mis-attribute payloads on an
    arrival-ordered plane.

    Planes MAY additionally provide ``allGatherBytes(bytes) -> List[bytes]``
    (same semantics, binary frames); exchange.py uses it to skip base64
    where the transport allows raw bytes."""

    def allGather(self, message: str) -> List[str]: ...

    def barrier(self) -> None: ...


class LocalControlPlane:
    """Single-controller control plane: one process drives the whole mesh, so
    gather/barrier are identities."""

    def __init__(self) -> None:
        self._health: dict = {}

    def allGather(self, message: str) -> List[str]:
        return [message]

    def allGatherBytes(self, message: bytes) -> List[bytes]:
        return [message]

    def barrier(self) -> None:
        return None

    # srml-watch health surface (non-collective): trivial in-process store
    # so thread-mocked rank harnesses can exercise the heartbeat/watchdog
    # contract without a shared filesystem
    def publish_health(self, payload: str) -> None:
        import json as _json

        try:
            rank = int(_json.loads(payload).get("rank", 0))
        except (ValueError, TypeError):
            rank = 0
        self._health[rank] = payload

    def read_health(self) -> dict:
        return dict(self._health)


def _local_ip() -> str:
    """Routable local IP: a UDP connect() selects the egress interface without
    sending packets, avoiding /etc/hosts entries that pin the hostname to
    127.0.x.1 (common on Debian TPU-VMs)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


def _free_port() -> int:
    # NOTE: inherently racy (jax.distributed.initialize rebinds the port after
    # we release it) — the coordinator retries are jax's own; picking from the
    # kernel ephemeral range keeps collisions rare.
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class TpuContext:
    """Context manager bootstrapping the distributed jax runtime for one fit.

    In single-controller mode (nranks == 1 processes) this is a cheap no-op
    that exposes the local device mesh.  In multi-controller mode (one process
    per Spark barrier task / TPU-VM worker) it initializes jax.distributed
    with a coordinator address exchanged over the control plane, mirroring the
    NCCL-uid handshake of the reference (cuml_context.py:75-103).
    """

    def __init__(
        self,
        rank: int,
        nranks: int,
        control_plane: Optional[ControlPlane] = None,
        require_dcn: bool = False,
    ):
        self._rank = rank
        self._nranks = nranks
        self._cp = control_plane or LocalControlPlane()
        self._require_dcn = require_dcn
        self._initialized_distributed = False
        self._logger = get_logger(type(self))

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def nranks(self) -> int:
        return self._nranks

    def __enter__(self) -> "TpuContext":
        faults.site("context.init", rank=self._rank)
        if self._nranks > 1:
            # CPU pods (virtual-device CI, mc tests, CPU-only clusters)
            # need gloo collectives armed BEFORE the backend initializes,
            # or every cross-process GSPMD computation fails to compile.
            # Unconditional: probing the backend kind here would itself
            # initialize it, and the flag is inert off-CPU
            # (compat.ensure_cpu_collectives docstring has the story)
            from ..compat import ensure_cpu_collectives

            ensure_cpu_collectives()
            # rank 0 advertises coordinator host:port; everyone gathers it.
            if self._rank == 0:
                addr = f"{_local_ip()}:{_free_port()}"
            else:
                addr = ""
            gathered = self._cp.allGather(json.dumps({"rank": self._rank, "addr": addr}))
            coordinator = ""
            for msg in gathered:
                info = json.loads(msg)
                if info["rank"] == 0:
                    coordinator = info["addr"]
            assert coordinator, "rank 0 coordinator address missing from allGather"
            self._logger.info(
                "rank %d/%d connecting to coordinator %s",
                self._rank, self._nranks, coordinator,
            )
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=self._nranks,
                process_id=self._rank,
            )
            self._initialized_distributed = True
        return self

    def __exit__(self, exc_type: Any, exc_val: Any, exc_tb: Any) -> None:
        # Abort-vs-clean semantics — the reference deliberately
        # distinguishes NCCL abort()-on-error from destroy()-on-clean
        # (cuml_context.py:149-166); here the exception path BROADCASTS an
        # abort marker through the control plane FIRST, so peers blocked
        # in a collective wait raise RemoteRankError within one poll
        # interval instead of riding out the round timeout.  A
        # RemoteRankError is itself a relayed abort: re-broadcasting it
        # would cascade markers around the ring, so only ORIGINAL failures
        # publish.
        if (
            exc_type is not None
            and self._nranks > 1
            and not isinstance(exc_val, RemoteRankError)
            and hasattr(self._cp, "abort")
        ):
            try:
                from .. import watch

                self._cp.abort(json.dumps({
                    "rank": self._rank,
                    "etype": exc_type.__name__,
                    "message": str(exc_val)[:512],
                    "span": watch.failing_span(),
                }))
            except Exception as abort_exc:  # noqa: BLE001 - best effort
                # the abort broadcast must never mask the real error, but
                # its failure is LOGGED, not swallowed (graftlint R9)
                self._logger.warning("abort broadcast failed: %s", abort_exc)
        if self._initialized_distributed:
            try:
                jax.distributed.shutdown()
            except Exception as exc:  # noqa: BLE001 - nccl abort-path mirror
                if exc_type is None:
                    raise
                # abort path: a shutdown failure while unwinding a real
                # error is expected (the coordinator may already be gone);
                # log it, never mask the original exception
                self._logger.warning(
                    "jax.distributed.shutdown failed during abort "
                    "teardown: %s", exc,
                )
        return None
