#
# Distributed-runtime lifecycle management.
#
# TPU-native replacement for the reference's CumlContext
# (/root/reference/python/src/spark_rapids_ml/common/cuml_context.py:35-192),
# which creates a raft Handle, has rank 0 mint an NCCL uid, spreads it via
# BarrierTaskContext.allGather, and injects NCCL/UCX comms.  Here the same
# three-phase shape holds, but the data plane is jax.distributed + XLA
# collectives over ICI/DCN:
#
#   1. rank 0 picks a coordinator address (host:port) — analog of the NCCL uid
#   2. the address is allGathered over the *control plane* (Spark barrier RPC
#      in the Spark adapter; trivial in single-controller local mode)
#   3. every rank calls jax.distributed.initialize(coordinator, nranks, rank);
#      afterwards jax.devices() spans the pod and a global Mesh is built, so
#      psum/all_gather/ppermute ride ICI within a host and DCN across hosts.
#
# __exit__ tears down jax.distributed the way CumlContext.__exit__ destroys or
# aborts the NCCL comm (cuml_context.py:149-166).
#

from __future__ import annotations

import json
import random
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Protocol, Sequence, Tuple

import jax

from ..utils import env_float as _env_float
from ..utils import get_logger
from . import faults

# -- srml-shield / srml-wire control-plane knobs (docs/robustness.md) ---------
# Shared by EVERY plane implementation (FileControlPlane, TcpControlPlane):
# per-ROUND bounded timeout instead of one session-wide cliff, and retrying
# I/O with exponential backoff + deterministic per-rank jitter for transient
# transport errors (NFS burps on the file plane, connection resets on the
# socket plane).
ROUND_TIMEOUT_ENV = "SRML_CP_ROUND_TIMEOUT_S"
RETRIES_ENV = "SRML_CP_RETRIES"
BACKOFF_ENV = "SRML_CP_BACKOFF_S"
# jax.distributed coordination-service heartbeat cadence (seconds x count):
# bounds how long any jax-layer teardown can dangle on a dead peer
JAX_HEARTBEAT_ENV = "SRML_JAX_HEARTBEAT_S"
JAX_MAX_MISSING_ENV = "SRML_JAX_MAX_MISSING_HEARTBEATS"
_DEFAULT_ROUND_TIMEOUT_S = 300.0
_DEFAULT_RETRIES = 3
_DEFAULT_BACKOFF_S = 0.05


@dataclass(frozen=True)
class RetryPolicy:
    """The SRML_CP_RETRIES / SRML_CP_BACKOFF_S contract, parsed ONCE at
    plane construction (a per-I/O env re-parse was the old file-plane shape)
    and shared verbatim by the file and TCP planes.  `run` retries `fn` on
    the given transient exception types with exponential backoff and
    deterministic per-rank jitter (explicitly seeded: graftlint R4)."""

    retries: int
    backoff_s: float

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        return cls(
            retries=int(_env_float(RETRIES_ENV, _DEFAULT_RETRIES)),
            backoff_s=_env_float(BACKOFF_ENV, _DEFAULT_BACKOFF_S),
        )

    def run(
        self,
        fn,
        jitter: random.Random,
        retry_on: Tuple[type, ...] = (OSError,),
        counter: str = "cp.io_retries",
    ):
        from .. import profiling

        attempt = 0
        while True:
            try:
                return fn()
            except retry_on:
                if attempt >= self.retries:
                    raise
                delay = self.backoff_s * (2 ** attempt) * (
                    1.0 + 0.25 * jitter.random()
                )
                profiling.incr_counter(counter)
                attempt += 1
                time.sleep(delay)


class ControlPlaneTimeout(TimeoutError):
    """A gather round ran out its per-round budget with ranks still missing.
    Typed (vs the old builtin TimeoutError) so callers can distinguish "the
    collective never completed" from arbitrary stdlib timeouts, and
    self-describing: it carries the round number, the ranks that never
    posted, and the knob that bounds the budget.  Still a TimeoutError
    subclass so existing `except TimeoutError` handlers keep working."""

    def __init__(
        self,
        plane: str,
        round_no: int,
        missing_ranks: Sequence[int],
        timeout_s: float,
        knob: str = ROUND_TIMEOUT_ENV,
    ):
        self.plane = plane
        self.round_no = int(round_no)
        self.missing_ranks = sorted(int(r) for r in missing_ranks)
        self.timeout_s = float(timeout_s)
        self.knob = knob
        super().__init__(
            f"{plane} round {self.round_no}: ranks {self.missing_ranks} "
            f"never posted within {self.timeout_s}s ({knob} bounds each "
            "round)"
        )


class RemoteRankError(RuntimeError):
    """Another rank of the cooperating job failed (orderly abort) or died
    (no marker — killed/OOMed) while this rank waited on a collective.
    Raised by the control plane's gather waits within one poll interval of
    the abort marker / dead pid appearing, instead of the full round
    timeout — and it NAMES the culprit: origin rank, its exception type,
    and the innermost span it was in (from the srml-watch health surface),
    so the survivor's traceback reads "rank 1 died in exchange.ring", not
    "TimeoutError after 300 s"."""

    def __init__(
        self,
        rank: int,
        message: str,
        span: Optional[str] = None,
        etype: Optional[str] = None,
    ):
        self.rank = int(rank)
        self.span = span
        self.etype = etype
        where = f" in span {span!r}" if span else ""
        what = f"{etype}: {message}" if etype else message
        super().__init__(f"remote rank {self.rank}{where}: {what}")


class ControlPlane(Protocol):
    """Minimal control-plane contract: Spark's BarrierTaskContext satisfies it
    (allGather of strings + barrier), as does the local trivial impl.

    ORDERING REQUIREMENT: allGather must return messages indexed by rank
    (result[r] = rank r's message) — Spark's BarrierTaskContext orders by
    partition id, FileControlPlane by rank-numbered files.  The binary
    collectives (parallel/exchange.py) and the kneighbors exchange index
    results positionally and would silently mis-attribute payloads on an
    arrival-ordered plane.

    Planes MAY additionally provide ``allGatherBytes(bytes) -> List[bytes]``
    (same semantics, binary frames); exchange.py uses it to skip base64
    where the transport allows raw bytes."""

    def allGather(self, message: str) -> List[str]: ...

    def barrier(self) -> None: ...


class LocalControlPlane:
    """Single-controller control plane: one process drives the whole mesh, so
    gather/barrier are identities."""

    def __init__(self) -> None:
        self._health: dict = {}

    def allGather(self, message: str) -> List[str]:
        return [message]

    def allGatherBytes(self, message: bytes) -> List[bytes]:
        return [message]

    def barrier(self) -> None:
        return None

    # srml-watch health surface (non-collective): trivial in-process store
    # so thread-mocked rank harnesses can exercise the heartbeat/watchdog
    # contract without a shared filesystem
    def publish_health(self, payload: str) -> None:
        import json as _json

        try:
            rank = int(_json.loads(payload).get("rank", 0))
        except (ValueError, TypeError):
            rank = 0
        self._health[rank] = payload

    def read_health(self) -> dict:
        return dict(self._health)

    # srml-shield abort surface (single-controller: no peers to warn, but
    # the conformance suite holds every plane to the same method shape)
    def abort(self, payload: str) -> None:
        return None

    def check_abort(self) -> Optional[Dict[str, Any]]:
        return None

    def close(self) -> None:
        return None


class TpuContext:
    """Context manager bootstrapping the distributed jax runtime for one fit.

    In single-controller mode (nranks == 1 processes) this is a cheap no-op
    that exposes the local device mesh.  In multi-controller mode (one process
    per Spark barrier task / TPU-VM worker) it initializes jax.distributed
    with a coordinator address exchanged over the control plane, mirroring the
    NCCL-uid handshake of the reference (cuml_context.py:75-103).
    """

    def __init__(
        self,
        rank: int,
        nranks: int,
        control_plane: Optional[ControlPlane] = None,
        require_dcn: bool = False,
    ):
        self._rank = rank
        self._nranks = nranks
        self._cp = control_plane or LocalControlPlane()
        self._require_dcn = require_dcn
        self._initialized_distributed = False
        self._logger = get_logger(type(self))

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def nranks(self) -> int:
        return self._nranks

    def __enter__(self) -> "TpuContext":
        faults.site("context.init", rank=self._rank)
        if self._nranks > 1:
            # CPU pods (virtual-device CI, mc tests, CPU-only clusters)
            # need gloo collectives armed BEFORE the backend initializes,
            # or every cross-process GSPMD computation fails to compile.
            # Unconditional: probing the backend kind here would itself
            # initialize it, and the flag is inert off-CPU
            # (compat.ensure_cpu_collectives docstring has the story)
            from ..compat import ensure_cpu_collectives

            ensure_cpu_collectives()
            # rank 0 advertises coordinator host:port; everyone gathers it.
            # A port-allocating control plane (TcpControlPlane) hands out a
            # coordinator-reserved port — no two sessions through the same
            # coordinator can collide, killing the _free_port rebind race
            # between sibling jobs on one host.  Planes without the surface
            # (file / Spark barrier) keep the best-effort ephemeral pick.
            from .netplane import _free_port, _local_ip

            if self._rank == 0:
                if hasattr(self._cp, "allocate_port"):
                    port = self._cp.allocate_port()
                else:
                    port = _free_port()
                addr = f"{_local_ip()}:{port}"
            else:
                addr = ""
            gathered = self._cp.allGather(json.dumps({"rank": self._rank, "addr": addr}))
            coordinator = ""
            for msg in gathered:
                info = json.loads(msg)
                if info["rank"] == 0:
                    coordinator = info["addr"]
            assert coordinator, "rank 0 coordinator address missing from allGather"
            self._logger.info(
                "rank %d/%d connecting to coordinator %s",
                self._rank, self._nranks, coordinator,
            )
            # Coordination-service heartbeats tightened from the 10 s x 10
            # default: 100 s was how long a survivor's teardown dangled on
            # a dead peer before the client's missed-heartbeat handler
            # fired (srml-wire chaos drive).  The control plane still owns
            # FAST detection (ms-scale markers/leases); these bound the
            # jax-layer tail so no teardown outlives ~interval x missing.
            from ..compat import distributed_initialize

            distributed_initialize(
                coordinator_address=coordinator,
                num_processes=self._nranks,
                process_id=self._rank,
                heartbeat_interval_s=max(
                    1, int(_env_float(JAX_HEARTBEAT_ENV, 1.0))
                ),
                max_missing_heartbeats=max(
                    2, int(_env_float(JAX_MAX_MISSING_ENV, 10.0))
                ),
            )
            self._initialized_distributed = True
        return self

    def __exit__(self, exc_type: Any, exc_val: Any, exc_tb: Any) -> None:
        # Abort-vs-clean semantics — the reference deliberately
        # distinguishes NCCL abort()-on-error from destroy()-on-clean
        # (cuml_context.py:149-166); here the exception path BROADCASTS an
        # abort marker through the control plane FIRST, so peers blocked
        # in a collective wait raise RemoteRankError within one poll
        # interval instead of riding out the round timeout.  A
        # RemoteRankError is itself a relayed abort: re-broadcasting it
        # would cascade markers around the ring, so only ORIGINAL failures
        # publish.
        if (
            exc_type is not None
            and self._nranks > 1
            and not isinstance(exc_val, RemoteRankError)
            and hasattr(self._cp, "abort")
        ):
            try:
                from .. import watch

                self._cp.abort(json.dumps({
                    "rank": self._rank,
                    "etype": exc_type.__name__,
                    "message": str(exc_val)[:512],
                    "span": watch.failing_span(),
                }))
            except Exception as abort_exc:  # noqa: BLE001 - best effort
                # the abort broadcast must never mask the real error, but
                # its failure is LOGGED, not swallowed (graftlint R9)
                self._logger.warning("abort broadcast failed: %s", abort_exc)
        if self._initialized_distributed:
            if exc_type is not None:
                # The abort-vs-destroy contract, for real:
                # jax.distributed.shutdown() runs a COLLECTIVE shutdown
                # barrier.  On any abort path a peer is dead or about to
                # be (it is unwinding this same path), so the barrier can
                # never complete — and the 0.4.37 client LOG(FATAL)s the
                # whole process after the ~100 s coordination heartbeat
                # timeout, killing the typed RemoteRankError before it
                # reaches the user (found by the srml-wire chaos drive).
                # Abort therefore means detach WITHOUT the barrier: skip
                # the call, let process teardown reclaim the sockets —
                # exactly NCCL abort() vs destroy().
                self._logger.warning(
                    "abort path (%s unwinding): skipping the collective "
                    "jax.distributed.shutdown barrier — it cannot "
                    "complete once a peer is gone",
                    exc_type.__name__,
                )
            else:
                jax.distributed.shutdown()
        return None
