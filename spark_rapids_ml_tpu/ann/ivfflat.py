#
# IVF-Flat approximate nearest neighbors, pure jax, mesh-aware.
#
# TPU-native counterpart of cuML's ApproximateNearestNeighbors
# (algorithm='ivfflat', algoParams={nlist, nprobe}) and the FAISS IVF-Flat
# tier (Johnson et al., "Billion-scale similarity search with GPUs"):
#
#   build:  the EXISTING kmeans engine (ops/kmeans.lloyd_iterations +
#           scalable k-means|| init) trains the coarse quantizer on a
#           deterministic sample; list assignment is the fused
#           distance+argmin kernel (ops/pallas_tpu.min_dist_argmin — Pallas
#           on TPU, identical-math XLA elsewhere); the inverted lists are
#           laid out host-side as ONE dense (nlist_pad, L_pad, D) buffer —
#           L_pad is the pow2 bucket of the longest list, nlist_pad a
#           multiple of lcm(8, n_dev) — and row-sharded over DATA_AXIS on
#           the LIST axis, so each device owns a contiguous block of whole
#           lists.
#   search: queries are replicated; every shard picks the query's nprobe
#           nearest centroids (replicated math), gathers the probed lists
#           it OWNS from its resident shard, computes distances on the
#           gathered tile, and keeps a local top-k; ONE psum'd candidate
#           merge (parallel/exchange.psum_merge_parts) combines the
#           per-shard (Q, k) lists and a final selection yields the global
#           top-k.  Host orchestration reuses the kNN engine's block
#           pipeline (ops/knn._run_block_pipeline) over pow2-bucketed query
#           blocks, and every kernel dispatches through
#           ops/precompile.cached_kernel — repeat same-shape probed
#           searches perform ZERO new compilations.
#
# Mesh parity (the CI gate): every selection point orders candidates by the
# LEXICOGRAPHIC key (d2, global position) — jax.lax.sort with num_keys=2 —
# and positions are unique, so the selected set AND its order form a total
# order independent of how lists shard.  A candidate's d2 (the expanded
# ||q||^2 - 2 q.x + ||x||^2 form, same as the exact engine) reduces over
# the fixed-width feature axis of an identically shaped tile on every mesh
# size, so its bits are mesh-independent too: fixed seed =>
# bitwise-identical probed results on 1-device and 8-device
# meshes.  (Plain value-only top-k would break this: the pool
# concatenation order differs between the single-shard pool and the
# shard-merged pool, so value ties would resolve differently.)
#
# Exactness knob: probing all lists (nprobe >= nlist) visits every item
# exactly once, so the probed result EQUALS the exact kneighbors result up
# to f32 distance formulation differences — the recall harness
# (recall_at_k) gates probed results against ops/knn's exact path in tests
# and in benchmark/bench_approximate_nn.py.
#

from __future__ import annotations

import math
import os
from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .. import profiling
from ..compat import shard_map
from ..parallel.mesh import (
    DATA_AXIS,
    axis_sharding,
    data_sharding,
    get_mesh,
    replicated_sharding,
)
from ..ops.precompile import cached_kernel, kernel_cache_key, shape_bucket

# The ONE lexicographic (d2, pos) tie contract: ops/knn.lex_topk (this
# engine's mesh-parity gate established it; PR 9 moved the implementation
# into the exact engine's exchange kernels).  Re-exported here — `_lex_topk`
# used to be a private wrapper around it, and the PQ tier (ann/pq.py)
# imports the same names — so every selection point in the ANN subsystem
# shares one total order and one sentinel.
from ..ops.knn import LEX_POS_SENTINEL, lex_topk as _lex_topk  # noqa: E402

# nlist padding unit: the packed layout pads the list count to a multiple of
# 8, and staging re-pads to lcm(8, n_dev) — every power-of-two mesh up to 8
# devices therefore sees the IDENTICAL padded geometry (the parity basis;
# larger meshes stay deterministic per shape, like mesh.padded_row_count)
_LIST_ALIGN = 8
# smallest per-list slot bucket (pow2 ladder floor, like the serving
# min-bucket rule)
_MIN_LIST_SLOTS = 8
# positions are int32 (list * L_pad + slot); the sentinel marks
# invalid/padded candidate slots and must exceed every real position —
# the SAME sentinel lex_topk pads unfillable slots with (one contract)
_POS_SENTINEL = LEX_POS_SENTINEL
# byte budget for the gathered (chunk, nprobe, L_pad, D) candidate tile —
# the probe kernel's only big intermediate; sized per query chunk so HBM
# use stays flat no matter the query block.  SRML_ANN_TILE_BUDGET overrides
# (tests shrink it to exercise the multi-chunk scan).
_PROBE_TILE_BUDGET = 64 << 20
# assignment row-block cap (pow2-bucketed, so repeat builds reuse kernels)
_ASSIGN_BLOCK = 65536
# quantizer training sample cap: IVF quantizers train on a sample (the
# FAISS convention); the cap bounds build time independent of index size
_TRAIN_CAP = 65536


def default_nlist(n_items: int) -> int:
    """sqrt(n) lists clamped to [8, 1024] — the standard IVF sizing rule
    (documented in docs/ann_engine.md with the measured recall table)."""
    return int(max(_LIST_ALIGN, min(1024, round(math.sqrt(max(n_items, 1))))))


def default_nprobe(n_lists: int) -> int:
    """A quarter of the lists, floor 8: recall ~0.95+ on clustered data at
    the docs/ann_engine.md operating points."""
    return int(max(8, n_lists // 4))


def _probe_tile_budget() -> int:
    try:
        return int(os.environ.get("SRML_ANN_TILE_BUDGET", _PROBE_TILE_BUDGET))
    except ValueError:
        return _PROBE_TILE_BUDGET


def _probe_chunk(block: int, nprobe: int, l_pad: int, dim: int) -> int:
    """Power-of-two query-chunk size whose gathered candidate tile fits the
    byte budget.  `block` is itself a pow2 bucket, so the chunk always
    divides it exactly — the kernel's scan needs no ragged tail."""
    per_row = max(nprobe * l_pad * dim * 4, 1)
    c = max(1, _probe_tile_budget() // per_row)
    c = 1 << (c.bit_length() - 1)
    return min(c, block)


def select_probes(
    q: jax.Array,       # (Q, D) replicated queries
    c: jax.Array,       # (nlist_pad, D) replicated centroids
    cn: jax.Array,      # (nlist_pad,) replicated ||c||^2, +inf pad rows
    nprobe: int,
    lps: int,           # lists per shard
    mesh: Mesh,
):
    """Replicated probe selection shared by the IVF-Flat and IVF-PQ probe
    kernels: expanded-form query->centroid distances, top-nprobe lists, and
    each shard's local-list mapping.  Identical on every shard and every
    mesh size (pad-list rows carry +inf norms so they lose to every genuine
    list; lax.top_k tie-break is lowest-index-first, also replicated).

    Returns (qn (Q,), d2c_probe (Q, nprobe) probed-centroid distances —
    the ADC base term the PQ kernel consumes, discarded by IVF-Flat —
    probes (Q, nprobe) int32, lp (Q, nprobe) clamped local list ids,
    is_local (Q, nprobe) ownership mask)."""
    qn = (q * q).sum(axis=1)
    cross = jnp.matmul(
        q, c.T,
        precision=jax.lax.Precision.HIGH,
        preferred_element_type=jnp.float32,
    )
    d2c = qn[:, None] - 2.0 * cross + cn[None, :]
    neg_d2, probes = jax.lax.top_k(-d2c, nprobe)  # (Q, nprobe)
    if mesh.shape[DATA_AXIS] > 1:
        off = jax.lax.axis_index(DATA_AXIS) * lps
    else:
        off = jnp.int32(0)
    local = probes - off
    is_local = (local >= 0) & (local < lps)
    lp = jnp.clip(local, 0, lps - 1)
    return qn, -neg_d2, probes, lp, is_local


def merge_shard_topk(
    best_d: jax.Array, best_p: jax.Array, mesh: Mesh, k: int
):
    """The ONE cross-shard candidate merge, shared VERBATIM by the IVF-Flat
    and IVF-PQ probe kernels (the 1-dev-vs-8-dev bitwise parity contract
    has a single implementation): per-shard (Q, k) candidates scattered
    into a (n_dev, Q, k) slab and psum'd (exact — each element is one
    shard's value plus zeros), then one final lexicographic (d2, pos)
    selection.  Typed exchange section: uniform exchange.ann.probe_merge.*
    counters."""
    if mesh.shape[DATA_AXIS] <= 1:
        return best_d, best_p
    from ..parallel.exchange import device_collective

    Q = best_d.shape[0]
    sec = device_collective("ann.probe_merge")
    all_d = sec.psum_merge(best_d, DATA_AXIS)
    all_p = sec.psum_merge(best_p, DATA_AXIS)
    cand_d = jnp.moveaxis(all_d, 0, 1).reshape(Q, -1)
    cand_p = jnp.moveaxis(all_p, 0, 1).reshape(Q, -1)
    return _lex_topk(cand_d, cand_p, k)


@partial(jax.jit, static_argnames=("mesh", "k", "nprobe", "chunk"))
def ivf_probe_kernel(
    list_data: jax.Array,  # (nlist_pad, L_pad, D) list-sharded over DATA_AXIS
    list_norm: jax.Array,  # (nlist_pad, L_pad) list-sharded ||x||^2
    counts: jax.Array,     # (nlist_pad,) int32 list-sharded valid-slot counts
    centroids: jax.Array,  # (nlist_pad, D) replicated (pad rows zero)
    c_norm: jax.Array,     # (nlist_pad,) replicated ||c||^2, +inf in pad rows
    queries: jax.Array,    # (Q, D) replicated
    mesh: Mesh,
    k: int,
    nprobe: int,
    chunk: int,
) -> Tuple[jax.Array, jax.Array]:
    """Probed IVF-Flat search: (euclidean distances (Q, k) ascending,
    positions (Q, k) into the padded list layout; unfillable slots carry
    inf distance and the _POS_SENTINEL position — the host maps them to the
    -1 id sentinel, same contract as the exact kNN kernels)."""
    nlist_pad, l_pad, _d = list_data.shape

    def per_shard(ld_loc, ln_loc, cnt_loc, c, cn, q):
        lps = ld_loc.shape[0]
        Q = q.shape[0]
        # probe selection on REPLICATED data (shared with the PQ kernel;
        # the probed-centroid distances it also returns are the ADC base
        # term — unused here, DCE'd by XLA)
        qn, _d2p, probes, lp, is_local = select_probes(
            q, c, cn, nprobe, lps, mesh
        )
        slot = jnp.arange(l_pad, dtype=jnp.int32)

        def chunk_body(carry, i):
            qs = jax.lax.dynamic_slice_in_dim(q, i * chunk, chunk)
            qn_c = jax.lax.dynamic_slice_in_dim(qn, i * chunk, chunk)
            lp_c = jax.lax.dynamic_slice_in_dim(lp, i * chunk, chunk)
            loc_c = jax.lax.dynamic_slice_in_dim(is_local, i * chunk, chunk)
            pr_c = jax.lax.dynamic_slice_in_dim(probes, i * chunk, chunk)
            # gather the chunk's probed lists from the RESIDENT shard:
            # (chunk, nprobe, L_pad, D) — the budget-bounded tile
            tile = jnp.take(ld_loc, lp_c, axis=0)
            xn = jnp.take(ln_loc, lp_c, axis=0)
            # expanded-form distances (||q||^2 - 2 q.x + ||x||^2) — the
            # SAME formulation as the exact engine and the kmeans/UMAP
            # kernels, so probed distances agree with exact kneighbors to
            # shared-rounding precision (the UMAP graph calibration
            # consumes distances, not just ids).  Parity basis: the
            # contraction reduces over the fixed feature axis of an
            # identically shaped tile on every mesh size, so a candidate's
            # d2 bits are mesh-independent.
            cross = jnp.einsum(
                "qd,qpld->qpl", qs, tile,
                precision=jax.lax.Precision.HIGH,
                preferred_element_type=jnp.float32,
            )
            d2 = qn_c[:, None, None] - 2.0 * cross + xn  # (chunk, nprobe, L_pad)
            valid = loc_c[:, :, None] & (
                slot[None, None, :] < jnp.take(cnt_loc, lp_c, axis=0)[:, :, None]
            )
            d2 = jnp.where(valid, d2, jnp.inf)
            pos = pr_c[:, :, None] * l_pad + slot[None, None, :]
            pos = jnp.where(valid, pos, _POS_SENTINEL)
            bd, bp = _lex_topk(
                d2.reshape(chunk, -1), pos.reshape(chunk, -1), k
            )
            return carry, (bd, bp)

        n_chunks = Q // chunk
        _, (ds, ps) = jax.lax.scan(
            chunk_body, 0, jnp.arange(n_chunks, dtype=jnp.int32)
        )
        best_d, best_p = merge_shard_topk(
            ds.reshape(Q, k), ps.reshape(Q, k), mesh, k
        )
        return jnp.sqrt(jnp.maximum(best_d, 0.0)), best_p

    return shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(), P(), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )(list_data, list_norm, counts, centroids, c_norm, queries)


@partial(jax.jit, static_argnames=("mesh", "nprobe"))
def ivf_select_kernel(
    centroids: jax.Array,  # (nlist_pad, D) replicated
    c_norm: jax.Array,     # (nlist_pad,) replicated, +inf pad rows
    queries: jax.Array,    # (Q, D) replicated
    mesh: Mesh,            # unused in the math — cache-key rider only, so
    #                        executables never cross mesh placements
    nprobe: int,
) -> jax.Array:
    """Probe selection ALONE, for the tiered pager (flat and PQ): the host
    needs each block's probed list ids BEFORE dispatch so cold lists can
    page in.  Op-for-op the select_probes math (expanded-form distances at
    HIGH matmul precision, lax.top_k over the same +inf-padded norms) on
    the same replicated arrays — the probe kernels re-select identically
    inside their shard_map, so the pager and the scan always agree on
    which lists a query touches."""
    qn = (queries * queries).sum(axis=1)
    cross = jnp.matmul(
        queries, centroids.T,
        precision=jax.lax.Precision.HIGH,
        preferred_element_type=jnp.float32,
    )
    d2c = qn[:, None] - 2.0 * cross + c_norm[None, :]
    _neg_d2, probes = jax.lax.top_k(-d2c, nprobe)
    return probes.astype(jnp.int32)


@partial(jax.jit, static_argnames=("mesh", "k", "nprobe", "chunk"))
def ivf_probe_tiered_kernel(
    list_data: jax.Array,  # (n_dev * slots_per_shard, L_pad, D) slot pool
    list_norm: jax.Array,  # (n_dev * slots_per_shard, L_pad) slot pool
    list_slot: jax.Array,  # (nlist_pad,) int32 list->local-slot, 0 sentinel
    counts: jax.Array,     # (nlist_pad,) int32 list-sharded
    centroids: jax.Array,  # (nlist_pad, D) replicated (pad rows zero)
    c_norm: jax.Array,     # (nlist_pad,) replicated ||c||^2, +inf pad rows
    queries: jax.Array,    # (Q, D) replicated
    mesh: Mesh,
    k: int,
    nprobe: int,
    chunk: int,
) -> Tuple[jax.Array, jax.Array]:
    """ivf_probe_kernel's body plus ONE indirection: probed local list ids
    map through list_slot into the shard's HBM slot pool before the
    data/norm gathers (ann/tier.py pages the pool).  Positions stay GLOBAL
    (probe * L_pad + slot) so ids/refine are untouched, and the gathered
    tiles hold byte-for-byte the values the resident kernel gathers —
    the tiered-vs-resident bitwise parity argument.  A probed list whose
    slot is 0 reads the sentinel (+inf norms) and drops out: residency
    bugs degrade recall, never corrupt."""
    _rows, l_pad, _d = list_data.shape

    def per_shard(ld_loc, ln_loc, slot_loc, cnt_loc, c, cn, q):
        lps = cnt_loc.shape[0]
        Q = q.shape[0]
        qn, _d2p, probes, lp, is_local = select_probes(
            q, c, cn, nprobe, lps, mesh
        )
        slot = jnp.arange(l_pad, dtype=jnp.int32)

        def chunk_body(carry, i):
            qs = jax.lax.dynamic_slice_in_dim(q, i * chunk, chunk)
            qn_c = jax.lax.dynamic_slice_in_dim(qn, i * chunk, chunk)
            lp_c = jax.lax.dynamic_slice_in_dim(lp, i * chunk, chunk)
            loc_c = jax.lax.dynamic_slice_in_dim(is_local, i * chunk, chunk)
            pr_c = jax.lax.dynamic_slice_in_dim(probes, i * chunk, chunk)
            # THE tiered indirection: local list -> pool slot, then gather
            # from the slot pool instead of the full list plane
            ls_c = jnp.take(slot_loc, lp_c, axis=0)
            tile = jnp.take(ld_loc, ls_c, axis=0)
            xn = jnp.take(ln_loc, ls_c, axis=0)
            cross = jnp.einsum(
                "qd,qpld->qpl", qs, tile,
                precision=jax.lax.Precision.HIGH,
                preferred_element_type=jnp.float32,
            )
            d2 = qn_c[:, None, None] - 2.0 * cross + xn
            valid = loc_c[:, :, None] & (
                slot[None, None, :] < jnp.take(cnt_loc, lp_c, axis=0)[:, :, None]
            )
            d2 = jnp.where(valid, d2, jnp.inf)
            pos = pr_c[:, :, None] * l_pad + slot[None, None, :]
            pos = jnp.where(valid, pos, _POS_SENTINEL)
            bd, bp = _lex_topk(
                d2.reshape(chunk, -1), pos.reshape(chunk, -1), k
            )
            return carry, (bd, bp)

        n_chunks = Q // chunk
        _, (ds, ps) = jax.lax.scan(
            chunk_body, 0, jnp.arange(n_chunks, dtype=jnp.int32)
        )
        best_d, best_p = merge_shard_topk(
            ds.reshape(Q, k), ps.reshape(Q, k), mesh, k
        )
        return jnp.sqrt(jnp.maximum(best_d, 0.0)), best_p

    return shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(
            P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
            P(), P(), P(),
        ),
        out_specs=(P(), P()),
        check_vma=False,
    )(list_data, list_norm, list_slot, counts, centroids, c_norm, queries)


@jax.jit
def _assign_block_kernel(X: jax.Array, centroids: jax.Array) -> jax.Array:
    """Fused distance+argmin list assignment for one pow2 row block
    (ops/pallas_tpu.min_dist_argmin: the Pallas kernel on TPU in its
    profitable regime, identical-math XLA elsewhere).  Per-row math with no
    cross-row reduction — assignments are bitwise mesh-independent."""
    from ..ops.pallas_tpu import min_dist_argmin

    _, assign = min_dist_argmin(X, centroids)
    return assign


class PackedIVF:
    """Host-side, mesh-INDEPENDENT index payload: items sorted by list
    (stable), their ids, per-list counts, and the genuine (unpadded)
    centroids.  This is what the model persists (plain np arrays through
    the core npz path); index_from_packed expands it into the device
    layout for whatever mesh serves it."""

    __slots__ = ("items", "ids", "counts", "centroids", "n_lists", "n_items")

    def __init__(self, items, ids, counts, centroids, n_lists, n_items):
        self.items = items          # (N, D) f32, list-sorted
        self.ids = ids              # (N,) int64 user ids, list-sorted
        self.counts = counts        # (nlist_base,) int64 per-list counts
        self.centroids = centroids  # (n_lists, D) f32
        self.n_lists = int(n_lists)
        self.n_items = int(n_items)


class IVFFlatIndex:
    """Device-staged IVF-Flat index (one mesh's layout of a PackedIVF)."""

    __slots__ = (
        "list_data", "list_norm", "counts", "centroids", "c_norm",
        "ids", "n_items", "n_lists", "nlist_pad", "l_pad", "dim",
    )

    def __init__(
        self, list_data, list_norm, counts, centroids, c_norm, ids,
        n_items, n_lists, nlist_pad, l_pad, dim,
    ):
        self.list_data = list_data  # (nlist_pad, L_pad, D) sharded
        self.list_norm = list_norm  # (nlist_pad, L_pad) sharded ||x||^2
        self.counts = counts        # (nlist_pad,) int32 sharded
        self.centroids = centroids  # (nlist_pad, D) replicated
        self.c_norm = c_norm        # (nlist_pad,) replicated, inf pad rows
        self.ids = ids              # (nlist_pad * L_pad,) int64 HOST, -1 pads
        self.n_items = n_items
        self.n_lists = n_lists
        self.nlist_pad = nlist_pad
        self.l_pad = l_pad
        self.dim = dim

    def device_bytes(self) -> int:
        """Global device-resident footprint of the staged index (logical
        bytes across all shards; ids stay host-side) — the numerator of the
        benchmark's index_bytes_per_item column, where the flat-vs-PQ
        compression headline is measured."""
        return int(
            self.list_data.nbytes + self.list_norm.nbytes
            + self.counts.nbytes + self.centroids.nbytes + self.c_norm.nbytes
        )


def train_coarse_quantizer(
    items: np.ndarray,
    n_clusters: int,
    seed: int,
    max_train_rows: int = _TRAIN_CAP,
    max_iter: int = 25,
    tol: float = 1e-4,
    phase: str = "ann.train",
) -> np.ndarray:
    """Train an (n_clusters, D) quantizer with the EXISTING kmeans engine on
    a SINGLE-device submesh over a deterministic seed-keyed sample (the
    FAISS convention — IVF quantizers train on a sample anyway, and a
    multi-shard psum would tie the centroid bits to the mesh size).  The
    result is therefore mesh-independent data.  Shared by the IVF coarse
    quantizer and the PQ per-subspace codebooks (ann/pq.py)."""
    from ..ops.kmeans import lloyd_iterations, scalable_kmeans_pp_init

    items = np.ascontiguousarray(np.asarray(items), dtype=np.float32)
    n = items.shape[0]
    n_clusters = int(max(1, min(n_clusters, n)))
    seed = int(seed) & 0x7FFFFFFF
    with profiling.phase(phase):
        mesh1 = get_mesh(1)
        rng = np.random.default_rng(seed)
        if n > max_train_rows:
            sel = np.sort(rng.choice(n, size=max_train_rows, replace=False))
            train = items[sel]
        else:
            train = items
        Xd = jax.device_put(train, data_sharding(mesh1))
        wd = jax.device_put(
            np.ones(train.shape[0], np.float32), data_sharding(mesh1)
        )
        round_size = max(1, min(2 * n_clusters, train.shape[0]))
        centers0 = scalable_kmeans_pp_init(
            Xd, wd, n_clusters, seed, 2.0, rounds=4, round_size=round_size
        )
        centers, _, _ = lloyd_iterations(
            Xd, wd, centers0, mesh1, max_iter, float(tol),
            min(32768, train.shape[0]),
        )
        return np.asarray(jax.device_get(centers), np.float32)


def assign_nearest(
    items: np.ndarray,
    centroids: np.ndarray,
    phase: str = "ann.assign",
    counter: str = "ann.assign_blocks",
) -> np.ndarray:
    """Nearest-centroid id per row via the fused distance+argmin kernel in
    pow2 row blocks through the AOT executable cache, ONE batched fetch.
    Per-row math with no cross-row reduction — assignments are bitwise
    mesh-independent.  Shared by IVF list assignment and PQ subspace
    encoding (same executable when shapes agree)."""
    items = np.ascontiguousarray(np.asarray(items), dtype=np.float32)
    n, d = items.shape
    with profiling.phase(phase):
        cdev = jnp.asarray(centroids)
        block = shape_bucket(min(n, _ASSIGN_BLOCK), lo=256)
        handles = []
        for start in range(0, n, block):
            stop = min(start + block, n)
            xb = items[start:stop]
            if xb.shape[0] != block:
                xb = np.concatenate(
                    [xb, np.zeros((block - xb.shape[0], d), np.float32)]
                )
            handles.append(
                cached_kernel(
                    "ann_assign", _assign_block_kernel, jnp.asarray(xb), cdev
                )
            )
        # ONE batched fetch for every dispatched block (per-block asarray
        # would pay a host round-trip apiece)
        fetched = jax.device_get(handles)
        assign = np.concatenate([np.asarray(a) for a in fetched])[:n]
        profiling.incr_counter(counter, len(handles))
        return assign.astype(np.int64)


def build_ivfflat_packed(
    items,
    item_ids: np.ndarray,
    n_lists: int,
    seed: int = 0,
    max_train_rows: int = _TRAIN_CAP,
    max_iter: int = 25,
    tol: float = 1e-4,
) -> PackedIVF:
    """Train the coarse quantizer and pack the inverted lists.

    Every step is mesh-independent by construction: the kmeans engine runs
    on a single-device submesh over a deterministic sample
    (train_coarse_quantizer), assignment is per-row argmin with no
    cross-row reduction (assign_nearest), and the layout is a stable host
    sort.  The same PackedIVF therefore stages bitwise-identically on any
    mesh."""
    items = np.ascontiguousarray(np.asarray(items), dtype=np.float32)
    n, _d = items.shape
    if n == 0:
        raise ValueError("cannot build an IVF-Flat index over 0 items")
    n_lists = int(max(1, min(n_lists, n)))
    centroids = train_coarse_quantizer(
        items, n_lists, seed, max_train_rows, max_iter, tol
    )
    assign = assign_nearest(items, centroids)

    with profiling.phase("ann.layout"):
        nlist_base = -(-n_lists // _LIST_ALIGN) * _LIST_ALIGN
        counts = np.bincount(assign, minlength=nlist_base).astype(np.int64)
        order = np.argsort(assign, kind="stable")
    return PackedIVF(
        items[order],
        np.asarray(item_ids, np.int64)[order],
        counts,
        centroids,
        n_lists,
        n,
    )


def item_norms(data: np.ndarray) -> np.ndarray:
    """||x||^2 per padded row, host-computed in f64 and stored f32: the
    norms are index DATA (the same bits on every mesh — and across the
    live-mutation restages of ann/mutable.py), not per-search math."""
    return np.einsum(
        "nd,nd->n", data.astype(np.float64), data.astype(np.float64)
    ).astype(np.float32)


def padded_host_layout(packed: PackedIVF, mesh: Mesh, l_pad: int = None):
    """Expand a PackedIVF into the padded HOST layout this mesh stages:
    lists padded to the pow2 slot bucket of the LONGEST list (one static
    geometry for the whole index — rebuilds at nearby sizes reuse compiled
    kernels), the list axis padded to a multiple of lcm(8, n_dev) with
    empty lists.  Returns (data (nlist_pad*l_pad, D), x_norm, ids_pad,
    counts int64, cpad, c_norm, nlist_pad, l_pad).  `l_pad` may be forced
    UP (the mutable index's repack-with-headroom path); forcing it below
    the longest list raises.  Shared by index_from_packed and the live
    mutation tier (ann/mutable.py), so the two can never disagree on the
    geometry a probe kernel sees."""
    n_dev = mesh.shape[DATA_AXIS]
    mult = math.lcm(_LIST_ALIGN, n_dev)
    nlist_pad = -(-max(packed.n_lists, 1) // mult) * mult
    counts = np.zeros(nlist_pad, np.int64)
    counts[: packed.counts.shape[0]] = packed.counts
    l_need = shape_bucket(int(max(counts.max(), 1)), lo=_MIN_LIST_SLOTS)
    if l_pad is None:
        l_pad = l_need
    elif l_pad < l_need:
        raise ValueError(
            f"l_pad={l_pad} cannot hold the longest list ({counts.max()} "
            f"items needs {l_need} slots)"
        )
    if nlist_pad * l_pad > int(_POS_SENTINEL):
        raise ValueError(
            f"IVF layout overflows int32 positions: {nlist_pad} lists x "
            f"{l_pad} slots; raise nlist so lists shrink"
        )
    d = packed.items.shape[1]
    offs = np.zeros(nlist_pad + 1, np.int64)
    np.cumsum(counts, out=offs[1:])
    row_list = np.repeat(np.arange(nlist_pad, dtype=np.int64), counts)
    slot = np.arange(packed.items.shape[0], dtype=np.int64) - offs[row_list]
    flat = row_list * l_pad + slot
    data = np.zeros((nlist_pad * l_pad, d), np.float32)
    data[flat] = packed.items
    ids_pad = np.full(nlist_pad * l_pad, -1, np.int64)
    ids_pad[flat] = packed.ids
    cpad = np.zeros((nlist_pad, d), np.float32)
    cpad[: packed.n_lists] = packed.centroids
    c_norm = np.einsum(
        "nd,nd->n", cpad.astype(np.float64), cpad.astype(np.float64)
    ).astype(np.float32)
    c_norm[packed.n_lists :] = np.inf  # pad lists never win a probe slot
    x_norm = item_norms(data)
    return data, x_norm, ids_pad, counts, cpad, c_norm, nlist_pad, l_pad


def stage_padded_layout(
    data: np.ndarray,
    x_norm: np.ndarray,
    ids_pad: np.ndarray,
    counts: np.ndarray,
    cpad: np.ndarray,
    c_norm: np.ndarray,
    nlist_pad: int,
    l_pad: int,
    n_items: int,
    n_lists: int,
    mesh: Mesh,
) -> IVFFlatIndex:
    """device_put a padded host layout as this mesh's IVFFlatIndex (the
    staging half of index_from_packed, reused verbatim by every live
    mutation restage — a plain upload, never a compile)."""
    d = data.shape[1]
    with profiling.phase("ann.stage", bytes=int(data.nbytes)):
        index = IVFFlatIndex(
            list_data=jax.device_put(
                data.reshape(nlist_pad, l_pad, d), axis_sharding(mesh, 0, 3)
            ),
            list_norm=jax.device_put(
                x_norm.reshape(nlist_pad, l_pad), axis_sharding(mesh, 0, 2)
            ),
            counts=jax.device_put(counts.astype(np.int32), data_sharding(mesh)),
            centroids=jax.device_put(cpad, replicated_sharding(mesh)),
            c_norm=jax.device_put(c_norm, replicated_sharding(mesh)),
            ids=ids_pad,
            n_items=n_items,
            n_lists=n_lists,
            nlist_pad=nlist_pad,
            l_pad=l_pad,
            dim=d,
        )
    profiling.incr_counter("ann.stage_bytes", int(data.nbytes))
    return index


def index_from_packed(packed: PackedIVF, mesh: Mesh) -> IVFFlatIndex:
    """Expand a PackedIVF into this mesh's device layout (padded host
    layout + staging; user ids stay on the host in int64)."""
    data, x_norm, ids_pad, counts, cpad, c_norm, nlist_pad, l_pad = (
        padded_host_layout(packed, mesh)
    )
    return stage_padded_layout(
        data, x_norm, ids_pad, counts, cpad, c_norm, nlist_pad, l_pad,
        packed.n_items, packed.n_lists, mesh,
    )


class TieredIVFFlatIndex:
    """IVF-Flat index whose data/norm list planes live in a
    TieredListPlanes HBM pool (hot lists pinned, cold lists LRU-paged from
    the padded host layout).  Same search frame contract as IVFFlatIndex;
    paging is a residency change, never a math change.  The tier's host
    planes are VIEWS of the padded layout arrays, so a mutable holder that
    edits its mirrors in place only has to tier.refresh() the touched
    lists for resident copies to match (non-resident lists pick the edit
    up at their next page-in — the tombstone-interaction contract)."""

    __slots__ = (
        "tier", "counts", "centroids", "c_norm", "ids", "n_items",
        "n_lists", "nlist_pad", "l_pad", "dim", "hot_fraction",
    )

    def __init__(self, tier, counts, centroids, c_norm, ids, n_items,
                 n_lists, nlist_pad, l_pad, dim, hot_fraction):
        self.tier = tier            # TieredListPlanes over [data, norms]
        self.counts = counts
        self.centroids = centroids
        self.c_norm = c_norm
        self.ids = ids
        self.n_items = n_items
        self.n_lists = n_lists
        self.nlist_pad = nlist_pad
        self.l_pad = l_pad
        self.dim = dim
        self.hot_fraction = float(hot_fraction)

    def device_bytes(self) -> int:
        return int(
            self.tier.device_bytes() + self.counts.nbytes
            + self.centroids.nbytes + self.c_norm.nbytes
        )

    def host_bytes(self) -> int:
        return self.tier.host_bytes()


def tiered_stage_padded_layout(
    data: np.ndarray,
    x_norm: np.ndarray,
    ids_pad: np.ndarray,
    counts: np.ndarray,
    cpad: np.ndarray,
    c_norm: np.ndarray,
    nlist_pad: int,
    l_pad: int,
    n_items: int,
    n_lists: int,
    mesh: Mesh,
    hot_fraction: float,
    pool_slots: int = None,
) -> TieredIVFFlatIndex:
    """Stage a padded host layout with only `hot_fraction` of each shard's
    lists HBM-resident (stage_padded_layout's tiered twin).  The tier
    planes are reshaped VIEWS of `data`/`x_norm` — zero host copies, and
    in-place mutation of those arrays is visible to every later page-in."""
    from .tier import TieredListPlanes

    d = data.shape[1]
    tier = TieredListPlanes(
        planes=[
            data.reshape(nlist_pad, l_pad, d),
            x_norm.reshape(nlist_pad, l_pad),
        ],
        sentinels=[None, np.inf],
        counts=counts,
        mesh=mesh,
        hot_fraction=hot_fraction,
        pool_slots=pool_slots,
        name="ann.tier",
    )
    with profiling.phase("ann.stage", bytes=tier.device_bytes()):
        index = TieredIVFFlatIndex(
            tier=tier,
            counts=jax.device_put(counts.astype(np.int32), data_sharding(mesh)),
            centroids=jax.device_put(cpad, replicated_sharding(mesh)),
            c_norm=jax.device_put(c_norm, replicated_sharding(mesh)),
            ids=ids_pad,
            n_items=n_items,
            n_lists=n_lists,
            nlist_pad=nlist_pad,
            l_pad=l_pad,
            dim=d,
            hot_fraction=hot_fraction,
        )
    return index


def tiered_index_from_packed(
    packed: PackedIVF,
    mesh: Mesh,
    hot_fraction: float,
    pool_slots: int = None,
) -> TieredIVFFlatIndex:
    """index_from_packed's tiered twin: padded host layout + slot-pool
    staging at the given hot fraction."""
    data, x_norm, ids_pad, counts, cpad, c_norm, nlist_pad, l_pad = (
        padded_host_layout(packed, mesh)
    )
    return tiered_stage_padded_layout(
        data, x_norm, ids_pad, counts, cpad, c_norm, nlist_pad, l_pad,
        packed.n_items, packed.n_lists, mesh, hot_fraction, pool_slots,
    )


def _effective_nprobe(index: IVFFlatIndex, nprobe: int) -> int:
    return int(max(1, min(nprobe, index.nlist_pad)))


def _tiered_flat_probe_all(
    index: TieredIVFFlatIndex,
    q: np.ndarray,
    k: int,
    np_eff: int,
    mesh: Mesh,
    block: int,
    chunk: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Tiered flat probe sweep — the PQ pager's exact shape: selection
    kernel replays probe selection for the host, the planner splits each
    block into groups whose cold lists fit the pool, each group dispatches
    at the SAME block bucket with its queries at their ORIGINAL offsets
    (zeros elsewhere; every op is row-independent, so group rows carry
    bitwise the all-resident sweep's values).  One cached executable per
    shape — zero new compiles at steady state."""
    n = q.shape[0]
    out_d = np.empty((n, k), np.float32)
    out_p = np.empty((n, k), np.int32)
    # Pass 1: dispatch every block's selection kernel, then ONE batched
    # device_get — the planner needs host probes, but not one sync per block.
    blocks = []
    sel = []
    for start in range(0, n, block):
        n_q = min(block, n - start)
        qb = np.zeros((block, index.dim), np.float32)
        qb[:n_q] = q[start : start + n_q]
        blocks.append((start, n_q, qb))
        sel.append(
            cached_kernel(
                "ann_select", ivf_select_kernel,
                index.centroids, index.c_norm, jnp.asarray(qb),
                mesh=mesh, nprobe=np_eff,
            )
        )
    # Pass 2: plan/page/dispatch per group, deferring the result fetch to
    # ONE device_get — tier buffers are immutably replaced on slot writes,
    # so earlier results stay valid on their old buffers.
    spans = []
    parts = []
    for (start, n_q, qb), probes in zip(blocks, jax.device_get(sel)):
        for s, e in index.tier.plan_groups(probes[:n_q]):
            planes, slot_map = index.tier.acquire(probes[s:e].ravel())
            gq = np.zeros((block, index.dim), np.float32)
            gq[s:e] = qb[s:e]
            spans.append((start, s, e))
            parts.append(
                cached_kernel(
                    "ann_probe_tiered", ivf_probe_tiered_kernel,
                    planes[0], planes[1], slot_map, index.counts,
                    index.centroids, index.c_norm, jnp.asarray(gq),
                    mesh=mesh, k=k, nprobe=np_eff, chunk=chunk,
                )
            )
    for (start, s, e), (d_host, p_host) in zip(spans, jax.device_get(parts)):
        out_d[start + s : start + e] = d_host[s:e]
        out_p[start + s : start + e] = p_host[s:e]
    return out_d, out_p


def ivfflat_search_prepared(
    index: IVFFlatIndex,
    queries,
    k: int,
    nprobe: int,
    mesh: Mesh,
    query_block: int = 8192,
    dtype=np.float32,
) -> Tuple[np.ndarray, np.ndarray]:
    """Probed search of `queries` against a staged index: returns
    (distances (Q, k_eff) ascending euclidean, ids (Q, k_eff) int64, -1 in
    unfillable slots), k_eff = min(k, n_items).  Query blocks are pow2
    buckets driven through the kNN engine's dispatch/collect pipeline;
    every kernel dispatch rides the AOT executable cache — a repeat search
    at a seen geometry performs zero new compilations."""
    from ..ops.knn import _pipeline_window, _query_block_bucket, _run_block_pipeline

    if isinstance(queries, jax.Array):
        q = queries if queries.dtype == dtype else queries.astype(dtype)
    else:
        q = np.asarray(queries, dtype=dtype)
    if q.ndim != 2 or q.shape[1] != index.dim:
        raise ValueError(
            f"queries must be (n, {index.dim}); got {q.shape}"
        )
    k_eff = min(k, index.n_items)
    if q.shape[0] == 0:
        return (
            np.zeros((0, k_eff), dtype=dtype),
            np.zeros((0, k_eff), dtype=np.int64),
        )
    np_eff = _effective_nprobe(index, nprobe)
    block = _query_block_bucket(q.shape[0], query_block)
    chunk = _probe_chunk(block, np_eff, index.l_pad, index.dim)
    if isinstance(index, TieredIVFFlatIndex):
        d_all, p_all = _tiered_flat_probe_all(
            index, np.asarray(q, dtype=dtype), k, np_eff, mesh, block, chunk
        )
        profiling.incr_counter("ann.searches")
        with profiling.phase("ann.merge"):
            ids_all = index.ids[np.minimum(p_all, index.ids.size - 1)]
            ids_all[np.isinf(d_all)] = -1
            return d_all[:, :k_eff], ids_all[:, :k_eff]
    starts = list(range(0, q.shape[0], block))
    pending: list = []
    out_d, out_i = [], []

    def _dispatch(bi):
        start = starts[bi]
        qb = q[start : start + block]
        n_q = qb.shape[0]
        if n_q != block:
            if isinstance(qb, jax.Array):
                qb = jnp.pad(qb, ((0, block - n_q), (0, 0)))
            else:
                qb = np.concatenate(
                    [qb, np.zeros((block - n_q, q.shape[1]), dtype=dtype)]
                )
        d, pos = cached_kernel(
            "ann_probe", ivf_probe_kernel,
            index.list_data, index.list_norm, index.counts,
            index.centroids, index.c_norm, jnp.asarray(qb),
            mesh=mesh, k=k, nprobe=np_eff, chunk=chunk,
        )
        for h in (d, pos):
            try:
                h.copy_to_host_async()
            except (AttributeError, RuntimeError):
                break
        pending.append((d, pos, n_q))

    def _collect(bi):
        d, pos, n_q = pending.pop(0)
        d_host, pos_host = jax.device_get((d, pos))
        d_host = d_host[:n_q]
        # sentinel positions index past the id table; clamp then overwrite
        # via the inf-distance mask (same -1 contract as the exact engine)
        ids_host = index.ids[
            np.minimum(pos_host[:n_q], index.ids.size - 1)
        ]
        ids_host[np.isinf(d_host)] = -1
        out_d.append(d_host)
        out_i.append(ids_host)

    _run_block_pipeline(
        len(starts), _dispatch, _collect, _pipeline_window(2),
        phase_prefix="ann",
    )
    profiling.incr_counter("ann.searches")
    with profiling.phase("ann.merge"):
        return (
            np.concatenate(out_d)[:, :k_eff],
            np.concatenate(out_i)[:, :k_eff],
        )


def warm_probe_kernels(
    index: IVFFlatIndex,
    k: int,
    nprobe: int,
    mesh: Mesh,
    n_queries: int = None,
    query_block: int = 8192,
    dtype=np.float32,
) -> list:
    """Submit the AOT compilation the next same-shape probed search will
    dispatch (key derived by the SAME kernel_cache_key the dispatch path
    uses, so the first dispatch lands on the warmed executable).  Returns
    the submitted keys — the serving entry's warm hook."""
    from ..ops.knn import _query_block_bucket
    from ..ops.precompile import aval, global_precompiler

    np_eff = _effective_nprobe(index, nprobe)
    block = _query_block_bucket(n_queries or query_block, query_block)
    chunk = _probe_chunk(block, np_eff, index.l_pad, index.dim)
    q_aval = aval((block, index.dim), dtype)
    statics = dict(k=k, nprobe=np_eff, chunk=chunk)
    if isinstance(index, TieredIVFFlatIndex):
        planes, slot_map = index.tier.snapshot()
        args = (
            planes[0], planes[1], slot_map, index.counts,
            index.centroids, index.c_norm, q_aval,
        )
        key = kernel_cache_key("ann_probe_tiered", args, mesh, statics)
        global_precompiler().submit(
            key, ivf_probe_tiered_kernel, *args, mesh=mesh, **statics
        )
        sel_args = (index.centroids, index.c_norm, q_aval)
        sel_statics = dict(nprobe=np_eff)
        sel_key = kernel_cache_key("ann_select", sel_args, mesh, sel_statics)
        global_precompiler().submit(
            sel_key, ivf_select_kernel, *sel_args, mesh=mesh, **sel_statics
        )
        return [key, sel_key]
    args = (
        index.list_data, index.list_norm, index.counts,
        index.centroids, index.c_norm, q_aval,
    )
    key = kernel_cache_key("ann_probe", args, mesh, statics)
    global_precompiler().submit(
        key, ivf_probe_kernel, *args, mesh=mesh, **statics
    )
    return [key]


def recall_at_k(approx_ids, exact_ids) -> float:
    """Mean fraction of each row's exact k-nearest ids recovered by the
    probed result — the gate every probed result set is scored with
    (tests/test_ann_engine.py, benchmark/bench_approximate_nn.py).  The -1
    unfillable sentinel never counts as a hit."""
    a = np.asarray(approx_ids)
    e = np.asarray(exact_ids)
    if a.shape[0] != e.shape[0]:
        raise ValueError(
            f"row mismatch: {a.shape[0]} approx vs {e.shape[0]} exact"
        )
    if e.size == 0:
        return 1.0
    hits = 0
    for ar, er in zip(a, e):
        hits += np.intersect1d(ar[ar >= 0], er).size
    return hits / float(e.shape[0] * e.shape[1])
