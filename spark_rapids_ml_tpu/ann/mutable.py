#
# Live IVF-Flat index mutation (srml-stream, the ann/ half).
#
# The reference's FAISS/cuML ANN tier rebuilds an index to change it; this
# module mutates a SERVING IVF-Flat index in place:
#
#   add_items:    new rows are assigned to their nearest coarse list by the
#                 SAME fused distance+argmin kernel that built the index
#                 (assign_nearest — cached executable, zero new compiles at
#                 a seen row bucket) and appended into the free slots of
#                 the existing (nlist_pad, L_pad, D) pow2 geometry.
#   delete_items: per-list TOMBSTONE bitmap; a tombstoned slot's stored
#                 ||x||^2 flips to +inf, so its expanded-form distance is
#                 +inf and it can never win a probe slot — the probe
#                 kernel is UNCHANGED (no new compile, no mask argument),
#                 and the host id map already turns inf-distance rows into
#                 the -1 sentinel.  Slots are reclaimed at repack.
#   repack:       when a list outgrows L_pad (or tombstones accumulate),
#                 the live rows re-lay into the NEXT pow2 slot bucket; the
#                 new geometry's probe kernels are warmed ON THE
#                 PRECOMPILE POOL before the atomic index swap, so probes
#                 never block on the repack (searches keep hitting the old
#                 staged index until the swap instant) and the next search
#                 dispatches a ready executable.
#
# Concurrency model: mutators serialize on one lock; readers take an
# ATOMIC SNAPSHOT of the staged index reference and search it lock-free —
# a search overlapping a mutation sees either the whole old index or the
# whole new one, never a half-written state.  The coarse quantizer is
# FIXED for the index lifetime (the FAISS semantics): adds assign to the
# existing centroids, so heavy drift degrades list balance, not
# correctness — rebuild when the distribution moves (docs/ann_engine.md
# §incremental-mutation).
#

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import profiling, sanitize
from ..ops.precompile import shape_bucket
from .ivfflat import (
    IVFFlatIndex,
    PackedIVF,
    TieredIVFFlatIndex,
    _MIN_LIST_SLOTS,
    assign_nearest,
    item_norms,
    ivfflat_search_prepared,
    padded_host_layout,
    stage_padded_layout,
    tiered_stage_padded_layout,
    warm_probe_kernels,
)


class MutableIVFIndex:
    """A PackedIVF staged for one mesh with live add/delete/repack.

    Host mirrors (padded data/norms/ids/counts + the tombstone bitmap +
    an id->position map) are the source of truth; every mutation updates
    the mirrors and restages the touched device buffers (a device_put,
    never a compile), then swaps the staged IVFFlatIndex reference
    atomically.  `index` is the snapshot readers search."""

    def __init__(
        self,
        packed: PackedIVF,
        mesh: Any,
        hot_fraction: float = 1.0,
        pool_slots: Optional[int] = None,
    ):
        self._mesh = mesh
        # hot_fraction < 1 opts into TIERED staging (ann/tier.py): the tier's
        # host planes are views of this holder's mirrors, so in-place
        # mutations are visible to every later page-in; deletes additionally
        # refresh() the touched lists' RESIDENT copies so tombstones are
        # honored device-side immediately (the tombstone-interaction gate)
        self._hot_fraction = float(hot_fraction)
        self._pool_slots = pool_slots
        self._lock = sanitize.lockdep_lock(
            "ann.mutable.mutator", factory=threading.RLock
        )
        (
            self._data, self._norms, self._ids, self._counts,
            self._cpad, self._c_norm, self._nlist_pad, self._l_pad,
        ) = padded_host_layout(packed, mesh)
        self._n_lists = packed.n_lists
        self._live = int(packed.n_items)
        # per-list tombstone bitmap: bit set => slot holds a deleted item
        # awaiting reclamation (np.packbits over the slot axis)
        self._tombstones = np.zeros(
            (self._nlist_pad, self._l_pad), dtype=bool
        )
        self._dead = 0
        live = self._ids >= 0
        self._pos_of_id: Dict[int, int] = {
            int(i): int(p) for p, i in zip(np.flatnonzero(live), self._ids[live])
        }
        # probe geometries to re-warm before a repack swap: {(k, nprobe,
        # query_block)} noted by search()/the serving warm hook.  Guarded
        # by its OWN lock: noting a spec is on the READ path, and taking
        # the mutator lock there would stall searches behind a repack's
        # staging + compile wait — the blocking the snapshot design avoids
        self._spec_lock = sanitize.lockdep_lock("ann.mutable.warmspec")
        self._warm_specs: set = set()
        self._repacks = 0
        self._index = self._stage()

    # -- read side ---------------------------------------------------------
    @property
    def index(self) -> IVFFlatIndex:
        """Atomic snapshot of the staged index (searches hold the returned
        object; a concurrent mutation swaps the reference, never the
        buffers a running search reads).  Deliberately LOCK-FREE: the
        reference read is atomic, and taking the mutator lock here would
        stall every probe behind a repack's layout+warm work — exactly the
        blocking the snapshot design exists to avoid."""
        return self._index

    @property
    def n_items(self) -> int:
        with self._lock:
            return self._live

    def tombstone_bitmap(self) -> np.ndarray:
        """(nlist_pad, ceil(L_pad/8)) uint8 — the packed per-list tombstone
        bitmap (introspection/persistence surface; the mutation hot path
        keeps the unpacked bool mirror)."""
        with self._lock:
            return np.packbits(self._tombstones, axis=1)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "n_items": self._live,
                "tombstoned": self._dead,
                "n_lists": self._n_lists,
                "l_pad": self._l_pad,
                "repacks": self._repacks,
                "device_bytes": self._index.device_bytes(),
            }

    def search(
        self, queries: np.ndarray, k: int, nprobe: int, **kw: Any
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Probed search against the current snapshot (lock-free after the
        snapshot read).  Notes the (k, nprobe, block) geometry so a later
        repack can warm the successor's kernels before the swap."""
        idx = self.index
        self._note_spec(k, nprobe, queries.shape[0] if hasattr(queries, "shape") else None)
        return ivfflat_search_prepared(idx, queries, k, nprobe, self._mesh, **kw)

    def register_warm(self, k: int, nprobe: int, n_queries: int) -> None:
        """Record a probe geometry the serving plane dispatches (the
        serve.ann warm hook calls this) so repack re-warms it."""
        self._note_spec(k, nprobe, n_queries)

    def _note_spec(self, k: int, nprobe: int, n_queries: Optional[int]) -> None:
        from ..ops.knn import _query_block_bucket

        block = _query_block_bucket(n_queries or 8192, 8192)
        with self._spec_lock:
            self._warm_specs.add((int(k), int(nprobe), int(block)))

    # -- mutation ----------------------------------------------------------
    def add_items(self, items: np.ndarray, ids: np.ndarray) -> None:
        """Append rows into their nearest lists' free slots.  Lists that
        would overflow L_pad trigger a repack to the pow2 bucket that fits
        (reclaiming tombstones first — the common case needs no growth).
        Duplicate ids fail loudly before any state changes."""
        items = np.ascontiguousarray(np.asarray(items), dtype=np.float32)
        ids = np.asarray(ids, dtype=np.int64)
        if items.ndim != 2 or items.shape[1] != self._data.shape[1]:
            raise ValueError(
                f"items must be (n, {self._data.shape[1]}); got {items.shape}"
            )
        if items.shape[0] != ids.shape[0]:
            raise ValueError(
                f"{items.shape[0]} items vs {ids.shape[0]} ids"
            )
        if items.shape[0] == 0:
            return
        if len(np.unique(ids)) != len(ids):
            raise ValueError("duplicate ids within the added batch")
        # nearest-list assignment OUTSIDE the lock (device work; the fixed
        # centroids it reads never mutate)
        assign = assign_nearest(
            items, self._cpad[: self._n_lists],
            phase="ann.mutate.assign", counter="ann.mutate.assign_blocks",
        )
        with self._lock:
            dup = [int(i) for i in ids if int(i) in self._pos_of_id]
            if dup:
                raise ValueError(
                    f"ids already present in the index: {dup[:8]}"
                    f"{'...' if len(dup) > 8 else ''}"
                )
            demand = np.bincount(assign, minlength=self._nlist_pad)
            need = self._counts + demand
            if int(need.max()) > self._l_pad:
                # reclaim tombstones and grow to the pow2 bucket that fits
                live_need = (
                    self._counts
                    - self._tombstones.sum(axis=1).astype(np.int64)
                    + demand
                )
                self._repack_locked(
                    shape_bucket(int(live_need.max()), lo=_MIN_LIST_SLOTS)
                )
            norms = item_norms(items)
            order = np.argsort(assign, kind="stable")
            sorted_assign = assign[order]
            # slot offset of each row within its list for THIS batch:
            # arange minus the first index of the row's group
            starts = np.searchsorted(sorted_assign, sorted_assign, side="left")
            within = np.arange(len(order), dtype=np.int64) - starts
            pos = (
                sorted_assign * self._l_pad
                + self._counts[sorted_assign]
                + within
            )
            grew = self._l_pad != self._index.l_pad
            self._data[pos] = items[order]
            self._norms[pos] = norms[order]
            self._ids[pos] = ids[order]
            self._counts += demand
            for i, p in zip(ids[order], pos):
                self._pos_of_id[int(i)] = int(p)
            self._live += items.shape[0]
            staged = self._stage()
            if grew:
                # a repack changed the probe geometry: warm its kernels
                # from the FINAL staged buffers before the swap, so the
                # first post-swap search dispatches a ready executable
                # (probes keep serving the old snapshot meanwhile)
                # graftlint: disable=R11 (compile wait holds only the mutator lock, by design: probes are lock-free on the snapshot, and releasing mid-mutation would tear the staged swap — NOTES.md)
                self._warm_for(staged)
            self._index = staged
            profiling.incr_counter("ann.mutate.adds", items.shape[0])

    def delete_items(self, ids: np.ndarray) -> int:
        """Tombstone rows by user id: the slot's stored norm flips to +inf
        (its probe distance becomes +inf — the unchanged kernel can never
        select it ahead of a live candidate) and its id leaves the map.
        Returns the number of rows actually deleted; unknown ids are
        ignored (idempotent deletes).  Only the small (nlist_pad, L_pad)
        norm plane restages — the data buffer is untouched."""
        removed = 0
        touched: List[int] = []
        with self._lock:
            for i in np.asarray(ids, dtype=np.int64):
                pos = self._pos_of_id.pop(int(i), None)
                if pos is None:
                    continue
                lst, slot = divmod(pos, self._l_pad)
                self._tombstones[lst, slot] = True
                self._norms[pos] = np.inf
                self._ids[pos] = -1
                touched.append(int(lst))
                removed += 1
            if removed:
                self._live -= removed
                self._dead += removed
                self._index = self._swap_norms(np.unique(touched))
                profiling.incr_counter("ann.mutate.deletes", removed)
        return removed

    def repack(self, l_pad: Optional[int] = None) -> None:
        """Reclaim tombstoned slots (and optionally re-bucket): live rows
        re-lay contiguously, L_pad re-derives from the longest LIVE list
        (or is forced), the successor geometry's probe kernels warm on the
        precompile pool, and the staged index swaps atomically — probes in
        flight finish on the old geometry, the next search dispatches the
        warmed successor executable."""
        with self._lock:
            self._repack_locked(l_pad)
            staged = self._stage()
            if staged.l_pad != self._index.l_pad:
                # graftlint: disable=R11 (compile wait holds only the mutator lock, by design: probes are lock-free on the snapshot, and releasing mid-repack would tear the staged swap — NOTES.md)
                self._warm_for(staged)
            self._index = staged

    def to_packed(self) -> PackedIVF:
        """Compacted mesh-independent payload of the LIVE rows — what a
        model persists after a mutation session (ApproximateNearestNeighborsModel
        .freeze_mutations)."""
        with self._lock:
            return self._to_packed_locked()

    # -- internals (lock held) ---------------------------------------------
    def _repack_locked(self, l_pad: Optional[int]) -> None:
        packed = self._to_packed_locked()
        new_l = l_pad or shape_bucket(
            int(max(packed.counts.max(), 1)), lo=_MIN_LIST_SLOTS
        )
        (
            self._data, self._norms, self._ids, self._counts,
            self._cpad, self._c_norm, self._nlist_pad, self._l_pad,
        ) = padded_host_layout(packed, self._mesh, l_pad=new_l)
        self._tombstones = np.zeros((self._nlist_pad, self._l_pad), bool)
        self._dead = 0
        live = self._ids >= 0
        self._pos_of_id = {
            int(i): int(p) for p, i in zip(np.flatnonzero(live), self._ids[live])
        }
        self._repacks += 1
        profiling.incr_counter("ann.mutate.repacks")

    def _warm_for(self, staged: IVFFlatIndex) -> None:
        """Warm every noted probe geometry against a freshly staged index
        and WAIT for the compiles, so the first search after the caller's
        swap dispatches a ready executable (the zero-steady-compile gate
        across repacks).  Probes keep serving the old snapshot meanwhile —
        the swap happens only after this returns."""
        with self._spec_lock:
            specs = sorted(self._warm_specs)
        keys: List = []
        for k, nprobe, block in specs:
            keys.extend(
                warm_probe_kernels(
                    staged, k, nprobe, self._mesh, n_queries=block
                )
            )
        if keys:
            from ..ops.precompile import global_precompiler

            global_precompiler().wait(keys)

    def _to_packed_locked(self) -> PackedIVF:
        live_counts = (
            self._counts - self._tombstones.sum(axis=1).astype(np.int64)
        )
        items, ids = [], []
        for lst in range(self._nlist_pad):
            base = lst * self._l_pad
            sl = slice(base, base + int(self._counts[lst]))
            keep = self._ids[sl] >= 0
            items.append(self._data[sl][keep])
            ids.append(self._ids[sl][keep])
        return PackedIVF(
            np.concatenate(items) if items else self._data[:0],
            np.concatenate(ids) if ids else self._ids[:0],
            live_counts,
            self._cpad[: self._n_lists].copy(),
            self._n_lists,
            self._live,
        )

    def _stage(self) -> IVFFlatIndex:
        # ids are COPIED into the snapshot: the staged index host-maps
        # positions through index.ids, and handing it the live mirror
        # would let a later in-place add/delete mutate an older snapshot
        # a concurrent search still holds (device buffers are immutable
        # uploads, so they need no copy)
        if self._hot_fraction < 1.0:
            # tiered restage: a NEW slot pool over the (possibly regrown)
            # mirrors — device_puts plus cached slot writes, never a compile
            idx = tiered_stage_padded_layout(
                self._data, self._norms, self._ids.copy(), self._counts,
                self._cpad, self._c_norm, self._nlist_pad, self._l_pad,
                self._live, self._n_lists, self._mesh,
                self._hot_fraction, self._pool_slots,
            )
            profiling.incr_counter(
                "ann.mutate.bytes", int(idx.tier.device_bytes())
            )
            return idx
        idx = stage_padded_layout(
            self._data, self._norms, self._ids.copy(), self._counts,
            self._cpad, self._c_norm, self._nlist_pad, self._l_pad,
            self._live, self._n_lists, self._mesh,
        )
        profiling.incr_counter(
            "ann.mutate.bytes", int(self._data.nbytes + self._norms.nbytes)
        )
        return idx

    def _swap_norms(self, touched_lists: np.ndarray) -> IVFFlatIndex:
        """Delete-path restage: only the (nlist_pad, L_pad) norm plane
        re-uploads; the data/counts/centroid device buffers carry over.
        Tiered: the mirror edit is already visible to future page-ins
        (views), so only the touched lists' RESIDENT slot copies re-page —
        paged-in cold lists honor the tombstone bitmap either way."""
        import jax

        from ..parallel.mesh import axis_sharding

        old = self._index
        if isinstance(old, TieredIVFFlatIndex):
            old.tier.refresh(touched_lists)
            return TieredIVFFlatIndex(
                tier=old.tier,
                counts=old.counts,
                centroids=old.centroids,
                c_norm=old.c_norm,
                ids=self._ids.copy(),  # snapshot isolation (see _stage)
                n_items=self._live,
                n_lists=self._n_lists,
                nlist_pad=self._nlist_pad,
                l_pad=self._l_pad,
                dim=old.dim,
                hot_fraction=self._hot_fraction,
            )
        norms_dev = jax.device_put(
            self._norms.reshape(self._nlist_pad, self._l_pad),
            axis_sharding(self._mesh, 0, 2),
        )
        profiling.incr_counter("ann.mutate.bytes", int(self._norms.nbytes))
        return IVFFlatIndex(
            list_data=old.list_data,
            list_norm=norms_dev,
            counts=old.counts,
            centroids=old.centroids,
            c_norm=old.c_norm,
            ids=self._ids.copy(),  # snapshot isolation (see _stage)
            n_items=self._live,
            n_lists=self._n_lists,
            nlist_pad=self._nlist_pad,
            l_pad=self._l_pad,
            dim=old.dim,
        )
