#
# srml-ann: approximate nearest-neighbor engines (layer peer of serving/).
#
# First tier: IVF-Flat (ivfflat.py) — a coarse k-means quantizer partitions
# the item set into inverted lists; queries probe only the nprobe nearest
# lists, turning the exact engine's O(items x queries) scan into
# O(nprobe * list_len * queries) with a recall knob.  The engine is built
# FROM the primitives PRs 2-5 hardened: the kmeans engine trains the
# quantizer, the fused distance+argmin kernel assigns lists, probed search
# rides the kNN block pipeline, and every kernel dispatches through the
# process-wide AOT executable cache.
#
# Second tier: IVF-PQ (pq.py) — residual product quantization on top of the
# same coarse machinery: items stored as m_sub one-byte codes + one ADC
# scalar (~32x device-memory compression at embedding dims), probed search
# becomes a per-query lookup-table accumulation over int8 codes
# (ops/pallas_pq), and recall is recovered by re-scoring top candidates
# against the host-side f32 payload.
#
# Live mutation (mutable.py, srml-stream): add/delete/repack on a SERVING
# IVF-Flat index — append slots inside the pow2 geometry, per-list
# tombstone bitmaps, warm-before-swap repack to the next slot bucket.
#

from .ivfflat import (
    IVFFlatIndex,
    PackedIVF,
    build_ivfflat_packed,
    default_nlist,
    default_nprobe,
    index_from_packed,
    ivfflat_search_prepared,
    recall_at_k,
    warm_probe_kernels,
)
from .mutable import MutableIVFIndex
from .pq import (
    IVFPQIndex,
    PackedPQ,
    build_ivfpq_packed,
    default_m_sub,
    index_from_packed_pq,
    ivfpq_search_prepared,
    warm_pq_probe_kernels,
)

__all__ = [
    "MutableIVFIndex",
    "IVFPQIndex",
    "PackedPQ",
    "build_ivfpq_packed",
    "default_m_sub",
    "index_from_packed_pq",
    "ivfpq_search_prepared",
    "warm_pq_probe_kernels",
    "IVFFlatIndex",
    "PackedIVF",
    "build_ivfflat_packed",
    "default_nlist",
    "default_nprobe",
    "index_from_packed",
    "ivfflat_search_prepared",
    "recall_at_k",
    "warm_probe_kernels",
]
