#
# srml-ann: approximate nearest-neighbor engines (layer peer of serving/).
#
# First tier: IVF-Flat (ivfflat.py) — a coarse k-means quantizer partitions
# the item set into inverted lists; queries probe only the nprobe nearest
# lists, turning the exact engine's O(items x queries) scan into
# O(nprobe * list_len * queries) with a recall knob.  The engine is built
# FROM the primitives PRs 2-5 hardened: the kmeans engine trains the
# quantizer, the fused distance+argmin kernel assigns lists, probed search
# rides the kNN block pipeline, and every kernel dispatches through the
# process-wide AOT executable cache.
#

from .ivfflat import (
    IVFFlatIndex,
    PackedIVF,
    build_ivfflat_packed,
    default_nlist,
    default_nprobe,
    index_from_packed,
    ivfflat_search_prepared,
    recall_at_k,
    warm_probe_kernels,
)

__all__ = [
    "IVFFlatIndex",
    "PackedIVF",
    "build_ivfflat_packed",
    "default_nlist",
    "default_nprobe",
    "index_from_packed",
    "ivfflat_search_prepared",
    "recall_at_k",
    "warm_probe_kernels",
]
