#
# srml-tier: HBM/host-RAM tiered residency for IVF list planes.
#
# The flat and PQ indexes stage every padded list into device HBM, so HBM
# caps the item count long before host RAM does.  This module keeps only a
# fixed per-shard POOL of list slots device-resident and pages the rest in
# on demand from the host-RAM padded layout (the same packed layout the
# refine payload already rides):
#
#   hot lists:   the top hot_fraction of each shard's lists by a
#                probe-frequency score (list population — denser regions
#                win more probes) are PINNED into the pool at stage time
#                and never evicted.
#   cold lists:  stay in host RAM; when a query block probes one, it pages
#                into an LRU slot with ONE H2D slice write per plane at a
#                TRACED slot index (ops/lanes.lane_write_kernel's insight,
#                hoisted from serving/multiplex.py's variant paging): every
#                page-in after the first reuses ONE cached executable per
#                plane shape — zero new compiles at steady state.
#   sentinel:    slot 0 of every shard is reserved and carries +inf in the
#                scoring plane (scalars / norms), so a list that is somehow
#                probed while non-resident contributes nothing (its
#                candidates score +inf and lose to every real candidate) —
#                residency bugs degrade recall, they can NEVER corrupt
#                results.
#
# The probe kernels consume the pool through a (nlist_pad,) int32
# list->slot indirection (local slot ids per shard, 0 = non-resident):
# gathering via the indirection returns byte-identical list data, so a
# tiered search's probed candidates — and therefore its refined results —
# are BITWISE the all-resident search's (paging is a residency change,
# never a math change; the CI gate asserts it).
#
# Buffers are replaced IMMUTABLY on page-in (the multiplex snapshot rule):
# a dispatch that already snapshotted the previous buffers keeps reading
# consistent values; acquire() pages and snapshots under one lock.
#
# Counters: {name}.hits / {name}.misses / {name}.page_bytes (+ evictions,
# refreshes) — docs/observability.md lists the family.
#

from __future__ import annotations

import math
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import profiling, sanitize
from ..ops.precompile import cached_kernel
from ..parallel.mesh import (
    DATA_AXIS,
    axis_sharding,
    data_sharding,
    replicated_sharding,
)

# smallest cold-list pool per shard: even a tiny index keeps a few slots so
# the LRU has room to avoid thrashing a single slot
_MIN_POOL_SLOTS = 8


@jax.jit
def _slot_write_kernel(buf: jax.Array, val: jax.Array, idx: jax.Array) -> jax.Array:
    """One slot page-in: buf with buf[idx] <- val, the slot index TRACED
    (int32 scalar) so every slot of a given plane shape shares ONE
    executable — paging a list in is an H2D slice write, never a
    recompile (the ops/lanes.lane_write_kernel contract on a sharded
    buffer)."""
    return jax.lax.dynamic_update_index_in_dim(buf, val, idx, 0)


class TieredListPlanes:
    """Per-shard slot pools for K parallel (nlist_pad, l_pad, ...) list
    planes plus the list->slot indirection the tiered probe kernels
    gather through.

    `planes` are the HOST padded layouts, kept BY REFERENCE — a mutable
    holder (ann/mutable.py) that edits a plane in place then calls
    refresh() re-pages the resident copies, which is how tombstones stay
    honored by paged-in lists.  `sentinels` gives the scalar fill value of
    each plane's reserved sentinel slot (+inf for the scoring plane).
    `counts` ranks lists for the hot split and lets empty lists skip the
    pool entirely."""

    def __init__(
        self,
        planes: Sequence[np.ndarray],
        sentinels: Sequence[float],
        counts: np.ndarray,
        mesh,
        hot_fraction: float,
        pool_slots: Optional[int] = None,
        name: str = "ann.tier",
    ):
        if not planes:
            raise ValueError("at least one list plane is required")
        nlist_pad = int(planes[0].shape[0])
        for p in planes:
            if int(p.shape[0]) != nlist_pad:
                raise ValueError("every plane must share the list axis")
        if len(sentinels) != len(planes):
            raise ValueError("one sentinel fill value per plane")
        if not 0.0 <= float(hot_fraction) <= 1.0:
            raise ValueError(
                f"hot_fraction ({hot_fraction}) must be in [0, 1]"
            )
        n_dev = mesh.shape[DATA_AXIS]
        if nlist_pad % n_dev:
            raise ValueError(
                f"{nlist_pad} padded lists do not shard over {n_dev} devices"
            )
        self._mesh = mesh
        self._name = str(name)
        self._planes_host = list(planes)
        self._sent = list(sentinels)
        self._counts = np.asarray(counts, np.int64)
        self._n_dev = int(n_dev)
        self._lps = nlist_pad // n_dev
        self.nlist_pad = nlist_pad
        self.hot_fraction = float(hot_fraction)
        self._hot_per_shard = int(
            min(self._lps, math.ceil(self.hot_fraction * self._lps))
        )
        self.pool_slots = int(
            pool_slots if pool_slots is not None
            else max(_MIN_POOL_SLOTS, self._lps - self._hot_per_shard)
        )
        if self.pool_slots < 1:
            raise ValueError(f"pool_slots ({pool_slots}) must be >= 1")
        # per-shard slot layout: [0]=sentinel, [1..h]=pinned hot,
        # [1+h .. 1+h+pool)=LRU'd cold pool
        self.slots_per_shard = 1 + self._hot_per_shard + self.pool_slots
        self._lock = sanitize.lockdep_lock(f"{self._name}.pager")
        # residency bookkeeping: global list id -> local slot (hot ids are
        # pinned and never leave); per-shard LRU over pool slots only
        self._slot_of: Dict[int, int] = {}
        self._hot_ids: set = set()
        self._lru: List[OrderedDict] = [OrderedDict() for _ in range(n_dev)]
        self._free: List[List[int]] = [
            list(range(1 + self._hot_per_shard, self.slots_per_shard))[::-1]
            for _ in range(n_dev)
        ]
        self._stage_initial()

    # -- staging -----------------------------------------------------------
    def _hot_lists_of_shard(self, s: int) -> np.ndarray:
        lo, hi = s * self._lps, (s + 1) * self._lps
        ids = np.arange(lo, hi, dtype=np.int64)
        cnt = self._counts[lo:hi]
        # probe-frequency proxy: list population, ties by id (deterministic)
        order = np.lexsort((ids, -cnt))
        hot = ids[order][: self._hot_per_shard]
        return hot[self._counts[hot] > 0]

    def _stage_initial(self) -> None:
        sps = self.slots_per_shard
        rows = self._n_dev * sps
        slot_map = np.zeros(self.nlist_pad, np.int32)
        bufs = []
        for plane, sent in zip(self._planes_host, self._sent):
            buf = np.zeros((rows,) + plane.shape[1:], plane.dtype)
            if sent is not None:
                buf[0 :: sps] = sent
            bufs.append(buf)
        for s in range(self._n_dev):
            for j, g in enumerate(self._hot_lists_of_shard(s)):
                local = 1 + j
                slot_map[g] = local
                self._slot_of[int(g)] = local
                self._hot_ids.add(int(g))
                for buf, plane in zip(bufs, self._planes_host):
                    buf[s * sps + local] = plane[g]
        stage_bytes = int(sum(b.nbytes for b in bufs))
        with profiling.phase(f"{self._name}.stage", bytes=stage_bytes):
            self._planes_dev = [
                jax.device_put(b, axis_sharding(self._mesh, 0, b.ndim))
                for b in bufs
            ]
            self._map_dev = jax.device_put(slot_map, data_sharding(self._mesh))
        profiling.incr_counter(f"{self._name}.stage_bytes", stage_bytes)

    # -- sizing ------------------------------------------------------------
    def device_bytes(self) -> int:
        return int(
            sum(b.nbytes for b in self._planes_dev) + self._map_dev.nbytes
        )

    def host_bytes(self) -> int:
        return int(sum(p.nbytes for p in self._planes_host))

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hot_per_shard": self._hot_per_shard,
                "pool_slots": self.pool_slots,
                "slots_per_shard": self.slots_per_shard,
                "resident_lists": len(self._slot_of),
                "device_bytes": self.device_bytes(),
                "host_bytes": self.host_bytes(),
            }

    # -- paging ------------------------------------------------------------
    def plan_groups(
        self, probes: np.ndarray
    ) -> List[Tuple[int, int]]:
        """Split a (Q, nprobe) probe table into contiguous query ranges
        whose distinct COLD probed lists fit the per-shard pool, so every
        range can be fully paged before its dispatch.  A single query
        needing more cold lists than the pool holds is a typed error
        (nprobe outgrew the staged pool — restage with a larger pool)."""
        Q = int(probes.shape[0])
        groups: List[Tuple[int, int]] = []
        need: List[set] = [set() for _ in range(self._n_dev)]
        start = 0
        for i in range(Q):
            row = [
                int(g) for g in probes[i]
                if 0 <= g < self.nlist_pad
                and self._counts[g] > 0
                and int(g) not in self._hot_ids
            ]
            row_need: Dict[int, set] = {}
            for g in row:
                row_need.setdefault(g // self._lps, set()).add(g)
            if any(len(v) > self.pool_slots for v in row_need.values()):
                raise ValueError(
                    f"one query probes more cold lists than the tier pool "
                    f"holds ({self.pool_slots} slots/shard); restage with "
                    f"a larger pool (nprobe grew past the staging hint)"
                )
            if any(
                len(need[s] | v) > self.pool_slots
                for s, v in row_need.items()
            ):
                groups.append((start, i))
                start = i
                need = [set() for _ in range(self._n_dev)]
            for s, v in row_need.items():
                need[s] |= v
        groups.append((start, Q))
        return groups

    def acquire(self, lists: Sequence[int]):
        """Page every list in `lists` into the pool (LRU eviction, pinned
        hot lists untouched) and return the snapshot
        (plane buffers tuple, list->slot map) the probe kernel should
        gather through.  Page-in and snapshot share one critical section,
        so the returned buffers always hold every requested list; later
        page-ins replace buffers immutably and never disturb a dispatch
        holding this snapshot."""
        with self._lock:
            req = [
                g for g in sorted({int(g) for g in lists})
                if 0 <= g < self.nlist_pad and self._counts[g] > 0
            ]
            # pass 1: touch already-resident requests FIRST so pass-2
            # evictions can never victimize a list this same acquire needs
            # (the planner bounds distinct cold requests by the pool size,
            # so after the touch pass the LRU front is always a non-request)
            misses = []
            for g in req:
                slot = self._slot_of.get(g)
                if slot is None:
                    misses.append(g)
                    continue
                profiling.incr_counter(f"{self._name}.hits")
                if g not in self._hot_ids:
                    self._lru[g // self._lps].move_to_end(slot)
            for g in misses:
                self._page_in_locked(g)
            return tuple(self._planes_dev), self._map_dev

    def snapshot(self):
        with self._lock:
            return tuple(self._planes_dev), self._map_dev

    def _page_in_locked(self, g: int) -> None:
        s = g // self._lps
        profiling.incr_counter(f"{self._name}.misses")
        if self._free[s]:
            slot = self._free[s].pop()
        else:
            slot, evicted = self._lru[s].popitem(last=False)
            del self._slot_of[evicted]
            self._write_map(evicted, 0)
            profiling.incr_counter(f"{self._name}.evictions")
        self._write_planes(s, slot, g)
        self._write_map(g, slot)
        self._slot_of[g] = slot
        self._lru[s][slot] = g

    def refresh(self, lists: Sequence[int]) -> None:
        """Re-page RESIDENT lists from the (possibly just-mutated) host
        planes — the tombstone-interaction hook: a delete flips the host
        norm plane, refresh() makes every resident copy honor it, and
        non-resident lists pick the mutation up at their next page-in."""
        with self._lock:
            for g in sorted({int(g) for g in lists}):
                slot = self._slot_of.get(g)
                if slot is None:
                    continue
                self._write_planes(g // self._lps, slot, g)
                profiling.incr_counter(f"{self._name}.refreshes")

    def _write_planes(self, s: int, local_slot: int, g: int) -> None:
        row = jnp.asarray(np.int32(s * self.slots_per_shard + local_slot))
        nbytes = 0
        for i, plane in enumerate(self._planes_host):
            val = jax.device_put(
                np.ascontiguousarray(plane[g]),
                replicated_sharding(self._mesh),
            )
            self._planes_dev[i] = cached_kernel(
                f"{self._name}.w{i}", _slot_write_kernel,
                self._planes_dev[i], val, row,
            )
            nbytes += int(plane[g].nbytes)
        profiling.incr_counter(f"{self._name}.page_bytes", nbytes)

    def _write_map(self, g: int, local_slot: int) -> None:
        self._map_dev = cached_kernel(
            f"{self._name}.map", _slot_write_kernel,
            self._map_dev,
            jax.device_put(
                np.int32(local_slot), replicated_sharding(self._mesh)
            ),
            jnp.asarray(np.int32(g)),
        )
