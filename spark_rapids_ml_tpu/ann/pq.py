#
# IVF-PQ: residual product quantization on top of the IVF machinery —
# the ~32x-compressed 100M+-item tier of the ANN subsystem.
#
# IVF-Flat (ivfflat.py) stores raw f32 vectors, so device memory caps the
# index around ~10M items at embedding dims.  This tier stores each item as
# m_sub one-byte codes plus one f32 correction scalar (FAISS IVFPQ, Jegou
# et al. "Product quantization for nearest neighbor search"; cuML
# algorithm='ivfpq'):
#
#   build:  the coarse quantizer and list assignment are the SHARED IVF
#           helpers (train_coarse_quantizer / assign_nearest — the kmeans
#           engine + the fused distance+argmin kernel).  Residuals
#           r = x - centroid[assign] are split into m_sub subspaces
#           (feature dim zero-padded to m_sub * dsub, dsub a pow2), each
#           subspace gets its own ksub=2^n_bits-centroid codebook trained
#           with the SAME kmeans engine (single-device submesh, FAISS
#           training-sample cap), and encoding is the SAME fused
#           distance+argmin kernel per subspace.  The packed payload
#           (codes + per-item ADC scalars + list layout) is
#           mesh-independent, exactly like PackedIVF.
#   search: asymmetric distance computation (ADC).  With r^ the item's
#           reconstructed residual (disjoint subspace codewords),
#
#             d2(q, item) = ||q - centroid_l - r^||^2
#                         = ||q - centroid_l||^2            (probe term)
#                         + sum_j  -2 q_j . cb[j, code_j]   (query table)
#                         + (||r^||^2 + 2 centroid_l . r^)  (item scalar)
#
#           The probe term falls out of probe selection (select_probes
#           already computes every query->centroid distance), the item
#           scalar is packed per item at build time, and the query table
#           T (m_sub, ksub) is computed ONCE per query block and stays
#           VMEM-resident while the int8 codes of the probed lists stream
#           through the LUT-accumulation kernel (ops/pallas_pq — MXU-free,
#           and the per-item HBM traffic is m_sub bytes instead of
#           IVF-Flat's 4*D: the scan is bandwidth-optimal by layout).
#           Selection and the cross-shard merge are REUSED VERBATIM from
#           the flat kernel (lexicographic (d2, pos) total order +
#           merge_shard_topk), so probed PQ results are bitwise identical
#           on 1-device and 8-device meshes, same contract, same gate.
#   refine: ADC distances are quantized approximations; recall is
#           recovered by probing top (k * refine_ratio) candidates and
#           re-scoring them against the f32 vectors the exactSearch
#           fallback already keeps HOST-side (the expanded-form f32
#           formulation the exact engine uses).  The device index stays
#           codes-only — compression is a device-memory claim; the f32
#           payload lives in host RAM with the model.
#

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .. import profiling
from ..compat import shard_map
from ..parallel.mesh import (
    DATA_AXIS,
    axis_sharding,
    data_sharding,
    replicated_sharding,
)
from ..ops.pallas_pq import lut_accumulate
from ..ops.precompile import cached_kernel, kernel_cache_key, shape_bucket
from .ivfflat import (
    _LIST_ALIGN,
    _MIN_LIST_SLOTS,
    _POS_SENTINEL,
    _TRAIN_CAP,
    _lex_topk,
    _probe_tile_budget,
    assign_nearest,
    merge_shard_topk,
    select_probes,
    train_coarse_quantizer,
)

# ADC re-score chunk budget: bytes of gathered (q_chunk, R, D) f32
# candidates the host refine materializes at once
_REFINE_BUDGET = 256 << 20
# subspace-seed stride: each codebook trains with its own deterministic
# seed so subspaces do not share init draws
_SUBSPACE_SEED_STRIDE = 0x51F1_5EED

DEFAULT_N_BITS = 8
DEFAULT_REFINE_RATIO = 4


def default_m_sub(dim: int) -> int:
    """Subspace count: the largest power of two <= dim/8 clamped to
    [4, 64] (and never above dim) — ~8 feature dims per one-byte code,
    the 32x-compression operating point at embedding dims (documented
    with the measured recall table in docs/ann_engine.md)."""
    target = max(4, dim // 8)
    m = 1 << (target.bit_length() - 1)
    return int(max(1, min(64, m, dim)))


def _pow2_ceil(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def pq_geometry(dim: int, m_sub: int) -> Tuple[int, int, int]:
    """(m_sub, dsub, d_pad): subspace width is the pow2 bucket of
    ceil(dim / m_sub) and the feature axis zero-pads to m_sub * dsub —
    pow2-padded subspaces keep every per-subspace kernel at one static
    lane-aligned geometry."""
    m_sub = int(max(1, min(m_sub, dim)))
    dsub = _pow2_ceil(-(-dim // m_sub))
    return m_sub, dsub, m_sub * dsub


def _pad_features(x: np.ndarray, d_pad: int) -> np.ndarray:
    if x.shape[1] == d_pad:
        return x
    out = np.zeros((x.shape[0], d_pad), np.float32)
    out[:, : x.shape[1]] = x
    return out


class PackedPQ:
    """Host-side, mesh-INDEPENDENT IVF-PQ payload: per-item codes + ADC
    scalars sorted by list (stable, the SAME layout rule as PackedIVF),
    per-list counts, the coarse centroids, and the subspace codebooks.
    This is what the model persists through the core npz path;
    index_from_packed_pq expands it per mesh."""

    __slots__ = (
        "codes", "scalars", "ids", "items", "counts", "centroids",
        "codebooks", "n_lists", "n_items", "dim", "m_sub", "n_bits",
    )

    def __init__(
        self, codes, scalars, ids, items, counts, centroids, codebooks,
        n_lists, n_items, dim, m_sub, n_bits,
    ):
        self.codes = codes          # (N, m_sub) uint8, list-sorted
        self.scalars = scalars      # (N,) f32 ADC item scalars, list-sorted
        self.ids = ids              # (N,) int64 user ids, list-sorted
        self.items = items          # (N, dim) f32 list-sorted — HOST-side
        #                             refine/exactSearch payload, never staged
        self.counts = counts        # (nlist_base,) int64 per-list counts
        self.centroids = centroids  # (n_lists, dim) f32 coarse quantizer
        self.codebooks = codebooks  # (m_sub, ksub, dsub) f32
        self.n_lists = int(n_lists)
        self.n_items = int(n_items)
        self.dim = int(dim)
        self.m_sub = int(m_sub)
        self.n_bits = int(n_bits)


def reconstruct(packed: PackedPQ, rows: Optional[np.ndarray] = None) -> np.ndarray:
    """Decode rows back to (approximate) vectors: coarse centroid + the
    subspace codewords, truncated to the true feature dim.  The encode/
    decode round-trip oracle in tests/test_pq_engine.py rides this."""
    m_sub, dsub, d_pad = pq_geometry(packed.dim, packed.m_sub)
    if rows is None:
        rows = np.arange(packed.codes.shape[0])
    codes = packed.codes[rows].astype(np.int64)
    rec = np.zeros((codes.shape[0], d_pad), np.float32)
    for j in range(m_sub):
        rec[:, j * dsub : (j + 1) * dsub] = packed.codebooks[j][codes[:, j]]
    row_list = np.repeat(
        np.arange(packed.counts.shape[0]), packed.counts
    )[rows]
    cpad = _pad_features(packed.centroids, d_pad)
    return (rec + cpad[row_list])[:, : packed.dim]


def build_ivfpq_packed(
    items,
    item_ids: np.ndarray,
    n_lists: int,
    m_sub: int,
    n_bits: int = DEFAULT_N_BITS,
    seed: int = 0,
    max_train_rows: int = _TRAIN_CAP,
    max_iter: int = 25,
    tol: float = 1e-4,
) -> PackedPQ:
    """Train the coarse quantizer + per-subspace codebooks and pack the
    code lists.  Mesh-independent by the same construction as the flat
    build: every kmeans runs on a single-device submesh over a
    deterministic sample, encoding is per-row argmin, the ADC scalars are
    host float64 math rounded once to f32 (index DATA, like c_norm), and
    the layout is a stable host sort."""
    items = np.ascontiguousarray(np.asarray(items), dtype=np.float32)
    n, d = items.shape
    if n == 0:
        raise ValueError("cannot build an IVF-PQ index over 0 items")
    if not 1 <= int(n_bits) <= 8:
        raise ValueError(f"n_bits must be in [1, 8]; got {n_bits}")
    n_lists = int(max(1, min(n_lists, n)))
    m_sub, dsub, d_pad = pq_geometry(d, m_sub)
    ksub = 1 << int(n_bits)
    seed = int(seed) & 0x7FFFFFFF

    centroids = train_coarse_quantizer(
        items, n_lists, seed, max_train_rows, max_iter, tol
    )
    assign = assign_nearest(items, centroids)

    with profiling.phase("ann.pq_train"):
        # residuals on the padded feature axis; pad dims are exactly zero,
        # so codebook centroids stay exactly zero there (means of zeros)
        cpad = _pad_features(centroids, d_pad)
        res = _pad_features(items, d_pad) - cpad[assign]
        codebooks = np.stack(
            [
                train_coarse_quantizer(
                    res[:, j * dsub : (j + 1) * dsub],
                    ksub,
                    (seed + _SUBSPACE_SEED_STRIDE * (j + 1)) & 0x7FFFFFFF,
                    max_train_rows,
                    max_iter,
                    tol,
                    phase="ann.pq_codebook",
                )
                for j in range(m_sub)
            ]
        )  # (m_sub, ksub_eff, dsub); ksub_eff = min(ksub, n)

    with profiling.phase("ann.pq_encode"):
        codes = np.empty((n, m_sub), np.uint8)
        for j in range(m_sub):
            cj = assign_nearest(
                res[:, j * dsub : (j + 1) * dsub],
                codebooks[j],
                phase="ann.pq_encode_block",
                counter="ann.pq_encode_blocks",
            )
            codes[:, j] = cj.astype(np.uint8)

    with profiling.phase("ann.pq_scalars"):
        # s_item = ||r^||^2 + 2 centroid . r^  in float64, stored f32:
        # mesh-independent index DATA (the same once-rounded contract as
        # the staged c_norm/x_norm)
        rec = np.zeros((n, d_pad), np.float64)
        idx = codes.astype(np.int64)
        for j in range(m_sub):
            rec[:, j * dsub : (j + 1) * dsub] = codebooks[j][idx[:, j]]
        scalars = (
            np.einsum("nd,nd->n", rec, rec)
            + 2.0 * np.einsum("nd,nd->n", cpad[assign].astype(np.float64), rec)
        ).astype(np.float32)

    with profiling.phase("ann.layout"):
        nlist_base = -(-n_lists // _LIST_ALIGN) * _LIST_ALIGN
        counts = np.bincount(assign, minlength=nlist_base).astype(np.int64)
        order = np.argsort(assign, kind="stable")
    return PackedPQ(
        codes[order],
        scalars[order],
        np.asarray(item_ids, np.int64)[order],
        items[order],
        counts,
        centroids,
        codebooks.astype(np.float32),
        n_lists,
        n,
        d,
        m_sub,
        n_bits,
    )


class IVFPQIndex:
    """Device-staged IVF-PQ index (one mesh's layout of a PackedPQ).  The
    device-resident per-item cost is m_sub bytes of codes + 4 bytes of ADC
    scalar — the compression headline device_bytes() measures."""

    __slots__ = (
        "codes", "scalars", "counts", "centroids", "c_norm", "codebooks",
        "ids", "rows", "n_items", "n_lists", "nlist_pad", "l_pad",
        "dim", "d_pad", "m_sub", "dsub", "ksub", "n_bits",
    )

    def __init__(
        self, codes, scalars, counts, centroids, c_norm, codebooks, ids,
        rows, n_items, n_lists, nlist_pad, l_pad, dim, d_pad, m_sub, dsub,
        ksub, n_bits,
    ):
        self.codes = codes          # (nlist_pad, L_pad, m_sub) u8 sharded
        self.scalars = scalars      # (nlist_pad, L_pad) f32 sharded
        self.counts = counts        # (nlist_pad,) int32 sharded
        self.centroids = centroids  # (nlist_pad, d_pad) f32 replicated
        self.c_norm = c_norm        # (nlist_pad,) f32 replicated, inf pads
        self.codebooks = codebooks  # (m_sub, ksub, dsub) f32 replicated
        self.ids = ids              # (nlist_pad * L_pad,) int64 HOST, -1 pads
        self.rows = rows            # (nlist_pad * L_pad,) int64 HOST packed
        #                             row per slot, -1 pads (the refine map)
        self.n_items = n_items
        self.n_lists = n_lists
        self.nlist_pad = nlist_pad
        self.l_pad = l_pad
        self.dim = dim
        self.d_pad = d_pad
        self.m_sub = m_sub
        self.dsub = dsub
        self.ksub = ksub
        self.n_bits = n_bits

    def device_bytes(self) -> int:
        """Global device-resident footprint (logical bytes across shards;
        ids/rows and the refine f32 payload stay host-side)."""
        return int(
            self.codes.nbytes + self.scalars.nbytes + self.counts.nbytes
            + self.centroids.nbytes + self.c_norm.nbytes
            + self.codebooks.nbytes
        )


def index_from_packed_pq(packed: PackedPQ, mesh: Mesh) -> IVFPQIndex:
    """Expand a PackedPQ into this mesh's device layout — the SAME pow2
    bucket geometry as the flat index (L_pad = pow2 of the longest list,
    nlist_pad a multiple of lcm(8, n_dev), int32 position overflow guard),
    with (nlist_pad, L_pad, m_sub) uint8 codes + (nlist_pad, L_pad) f32 ADC
    scalars row-sharded on the LIST axis instead of f32 vectors."""
    m_sub, dsub, d_pad = pq_geometry(packed.dim, packed.m_sub)
    ksub = packed.codebooks.shape[1]
    n_dev = mesh.shape[DATA_AXIS]
    mult = math.lcm(_LIST_ALIGN, n_dev)
    nlist_pad = -(-max(packed.n_lists, 1) // mult) * mult
    counts = np.zeros(nlist_pad, np.int64)
    counts[: packed.counts.shape[0]] = packed.counts
    l_pad = shape_bucket(int(max(counts.max(), 1)), lo=_MIN_LIST_SLOTS)
    if nlist_pad * l_pad > int(_POS_SENTINEL):
        raise ValueError(
            f"IVF-PQ layout overflows int32 positions: {nlist_pad} lists x "
            f"{l_pad} slots; raise nlist so lists shrink"
        )
    n = packed.codes.shape[0]
    offs = np.zeros(nlist_pad + 1, np.int64)
    np.cumsum(counts, out=offs[1:])
    row_list = np.repeat(np.arange(nlist_pad, dtype=np.int64), counts)
    slot = np.arange(n, dtype=np.int64) - offs[row_list]
    flat = row_list * l_pad + slot
    codes = np.zeros((nlist_pad * l_pad, m_sub), np.uint8)
    codes[flat] = packed.codes
    scal = np.zeros(nlist_pad * l_pad, np.float32)
    scal[flat] = packed.scalars
    ids_pad = np.full(nlist_pad * l_pad, -1, np.int64)
    ids_pad[flat] = packed.ids
    rows_pad = np.full(nlist_pad * l_pad, -1, np.int64)
    rows_pad[flat] = np.arange(n, dtype=np.int64)
    cpad = np.zeros((nlist_pad, d_pad), np.float32)
    cpad[: packed.n_lists] = _pad_features(packed.centroids, d_pad)
    c_norm = np.einsum(
        "nd,nd->n", cpad.astype(np.float64), cpad.astype(np.float64)
    ).astype(np.float32)
    c_norm[packed.n_lists :] = np.inf  # pad lists never win a probe slot
    stage_bytes = int(codes.nbytes + scal.nbytes)
    with profiling.phase("ann.stage", bytes=stage_bytes):
        index = IVFPQIndex(
            codes=jax.device_put(
                codes.reshape(nlist_pad, l_pad, m_sub),
                axis_sharding(mesh, 0, 3),
            ),
            scalars=jax.device_put(
                scal.reshape(nlist_pad, l_pad), axis_sharding(mesh, 0, 2)
            ),
            counts=jax.device_put(counts.astype(np.int32), data_sharding(mesh)),
            centroids=jax.device_put(cpad, replicated_sharding(mesh)),
            c_norm=jax.device_put(c_norm, replicated_sharding(mesh)),
            codebooks=jax.device_put(
                np.ascontiguousarray(packed.codebooks, np.float32),
                replicated_sharding(mesh),
            ),
            ids=ids_pad,
            rows=rows_pad,
            n_items=packed.n_items,
            n_lists=packed.n_lists,
            nlist_pad=nlist_pad,
            l_pad=l_pad,
            dim=packed.dim,
            d_pad=d_pad,
            m_sub=m_sub,
            dsub=dsub,
            ksub=ksub,
            n_bits=packed.n_bits,
        )
    profiling.incr_counter("ann.stage_bytes", stage_bytes)
    return index


def _pq_probe_chunk(block: int, nprobe: int, l_pad: int, m_sub: int) -> int:
    """Power-of-two query-chunk size whose gathered code tile + the LUT
    gather intermediate fit the shared probe tile budget
    (SRML_ANN_TILE_BUDGET).  `block` is a pow2 bucket, so the chunk always
    divides it — the scan needs no ragged tail."""
    per_row = max(nprobe * l_pad * (4 * m_sub + 8), 1)
    c = max(1, _probe_tile_budget() // per_row)
    c = 1 << (c.bit_length() - 1)
    return min(c, block)


@partial(jax.jit, static_argnames=("mesh", "k", "nprobe", "chunk"))
def ivfpq_probe_kernel(
    codes: jax.Array,      # (nlist_pad, L_pad, m_sub) u8 list-sharded
    scalars: jax.Array,    # (nlist_pad, L_pad) f32 list-sharded ADC scalars
    counts: jax.Array,     # (nlist_pad,) int32 list-sharded
    centroids: jax.Array,  # (nlist_pad, d_pad) replicated
    c_norm: jax.Array,     # (nlist_pad,) replicated, +inf pad rows
    codebooks: jax.Array,  # (m_sub, ksub, dsub) replicated
    queries: jax.Array,    # (Q, d_pad) replicated
    mesh: Mesh,
    k: int,
    nprobe: int,
    chunk: int,
) -> Tuple[jax.Array, jax.Array]:
    """Probed IVF-PQ ADC search: (euclidean ADC distances (Q, k) ascending,
    positions (Q, k) into the padded list layout — the flat kernel's exact
    output contract, -1/inf sentinel mapping included).  Selection and the
    cross-shard merge are the flat kernel's own helpers, so the bitwise
    1-dev-vs-8-dev parity argument carries over verbatim: ADC terms reduce
    over fixed-shape tiles (m_sub-wide LUT rows, dsub-wide table einsum)
    identical on every mesh size, and every selection orders by the total
    (d2, pos) key."""
    _nlist_pad, l_pad, m_sub = codes.shape
    ksub = codebooks.shape[1]
    dsub = codebooks.shape[2]

    def per_shard(cd_loc, sc_loc, cnt_loc, c, cn, cb, q):
        lps = cd_loc.shape[0]
        Q = q.shape[0]
        _qn, d2p, probes, lp, is_local = select_probes(
            q, c, cn, nprobe, lps, mesh
        )
        # the per-query ADC table T[q, j, c] = -2 q_j . cb[j, c] — computed
        # once per block on REPLICATED data, resident across the list scan
        tables = -2.0 * jnp.einsum(
            "qjd,jcd->qjc",
            q.reshape(Q, m_sub, dsub),
            cb,
            precision=jax.lax.Precision.HIGH,
            preferred_element_type=jnp.float32,
        )  # (Q, m_sub, ksub)
        slot = jnp.arange(l_pad, dtype=jnp.int32)

        def chunk_body(carry, i):
            d2p_c = jax.lax.dynamic_slice_in_dim(d2p, i * chunk, chunk)
            lp_c = jax.lax.dynamic_slice_in_dim(lp, i * chunk, chunk)
            loc_c = jax.lax.dynamic_slice_in_dim(is_local, i * chunk, chunk)
            pr_c = jax.lax.dynamic_slice_in_dim(probes, i * chunk, chunk)
            t_c = jax.lax.dynamic_slice_in_dim(tables, i * chunk, chunk)
            # gather the chunk's probed CODE lists from the resident shard:
            # (chunk, nprobe, L_pad, m_sub) uint8 — m_sub bytes/item, the
            # whole bandwidth story
            ctile = jnp.take(cd_loc, lp_c, axis=0)
            stile = jnp.take(sc_loc, lp_c, axis=0)  # (chunk, nprobe, L_pad)
            acc = lut_accumulate(
                t_c, ctile.reshape(chunk, nprobe * l_pad, m_sub)
            ).reshape(chunk, nprobe, l_pad)
            # ADC distance: probe term + query-table term + item scalar,
            # fixed association order (parity: same shapes on every mesh)
            d2 = d2p_c[:, :, None] + (acc + stile)
            valid = loc_c[:, :, None] & (
                slot[None, None, :] < jnp.take(cnt_loc, lp_c, axis=0)[:, :, None]
            )
            d2 = jnp.where(valid, d2, jnp.inf)
            pos = pr_c[:, :, None] * l_pad + slot[None, None, :]
            pos = jnp.where(valid, pos, _POS_SENTINEL)
            bd, bp = _lex_topk(
                d2.reshape(chunk, -1), pos.reshape(chunk, -1), k
            )
            return carry, (bd, bp)

        n_chunks = Q // chunk
        _, (ds, ps) = jax.lax.scan(
            chunk_body, 0, jnp.arange(n_chunks, dtype=jnp.int32)
        )
        best_d, best_p = merge_shard_topk(
            ds.reshape(Q, k), ps.reshape(Q, k), mesh, k
        )
        return jnp.sqrt(jnp.maximum(best_d, 0.0)), best_p

    return shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(
            P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(), P(), P(), P(),
        ),
        out_specs=(P(), P()),
        check_vma=False,
    )(codes, scalars, counts, centroids, c_norm, codebooks, queries)


def _effective_nprobe(index: IVFPQIndex, nprobe: int) -> int:
    return int(max(1, min(nprobe, index.nlist_pad)))


def _probe_k(k_eff: int, refine_ratio: int, n_items: int) -> int:
    """Candidate count the probe kernel selects: k itself without refine,
    k * refine_ratio (clamped to the item count) with it.  Static — part
    of the kernel cache key, derived identically by warm and dispatch."""
    if refine_ratio <= 1:
        return k_eff
    return int(max(k_eff, min(k_eff * int(refine_ratio), n_items)))


def ivfpq_search_prepared(
    index: IVFPQIndex,
    queries,
    k: int,
    nprobe: int,
    mesh: Mesh,
    query_block: int = 8192,
    refine_items: Optional[np.ndarray] = None,
    refine_ratio: int = DEFAULT_REFINE_RATIO,
) -> Tuple[np.ndarray, np.ndarray]:
    """Probed ADC search + optional f32 refine: returns (distances
    (Q, k_eff) ascending euclidean, ids (Q, k_eff) int64, -1 unfillable),
    k_eff = min(k, n_items) — the flat engine's exact frame contract.

    With `refine_items` (the model's list-sorted f32 payload, the same
    array the exactSearch route scores), the kernel selects the top
    k * refine_ratio ADC candidates and the host re-scores them against
    the true vectors (expanded-form f32, lexicographic (d2, pos) ties) —
    deterministic given the probed candidates, which are themselves
    bitwise mesh-independent, so refined results inherit mesh parity.

    Query blocks ride the kNN engine's dispatch/collect pipeline and every
    kernel dispatch rides the AOT executable cache: repeat same-shape
    searches perform zero new compilations (refine adds none — it is host
    numpy)."""
    from ..ops.knn import _pipeline_window, _query_block_bucket, _run_block_pipeline

    q = np.asarray(queries, dtype=np.float32)
    if q.ndim != 2 or q.shape[1] != index.dim:
        raise ValueError(f"queries must be (n, {index.dim}); got {q.shape}")
    k_eff = min(k, index.n_items)
    if q.shape[0] == 0:
        return (
            np.zeros((0, k_eff), dtype=np.float32),
            np.zeros((0, k_eff), dtype=np.int64),
        )
    refine = refine_items is not None and int(refine_ratio) > 1
    kp = _probe_k(k_eff, int(refine_ratio) if refine else 1, index.n_items)
    np_eff = _effective_nprobe(index, nprobe)
    qp = _pad_features(q, index.d_pad)
    block = _query_block_bucket(q.shape[0], query_block)
    chunk = _pq_probe_chunk(block, np_eff, index.l_pad, index.m_sub)
    starts = list(range(0, q.shape[0], block))
    pending: list = []
    out_d, out_p = [], []

    def _dispatch(bi):
        start = starts[bi]
        qb = qp[start : start + block]
        n_q = qb.shape[0]
        if n_q != block:
            qb = np.concatenate(
                [qb, np.zeros((block - n_q, index.d_pad), np.float32)]
            )
        d, pos = cached_kernel(
            "ann_pq_probe", ivfpq_probe_kernel,
            index.codes, index.scalars, index.counts,
            index.centroids, index.c_norm, index.codebooks, jnp.asarray(qb),
            mesh=mesh, k=kp, nprobe=np_eff, chunk=chunk,
        )
        for h in (d, pos):
            try:
                h.copy_to_host_async()
            except (AttributeError, RuntimeError):
                break
        pending.append((d, pos, n_q))

    def _collect(bi):
        d, pos, n_q = pending.pop(0)
        d_host, pos_host = jax.device_get((d, pos))
        out_d.append(d_host[:n_q])
        out_p.append(pos_host[:n_q])
    _run_block_pipeline(
        len(starts), _dispatch, _collect, _pipeline_window(2),
        phase_prefix="ann",
    )
    profiling.incr_counter("ann.searches")
    d_all = np.concatenate(out_d)
    p_all = np.concatenate(out_p)
    if refine:
        with profiling.phase("ann.refine"):
            return _refine_host(
                index, refine_items, q, d_all, p_all, k_eff
            )
    with profiling.phase("ann.merge"):
        ids = index.ids[np.minimum(p_all, index.ids.size - 1)]
        ids[np.isinf(d_all)] = -1
        return d_all[:, :k_eff], ids[:, :k_eff]


def _refine_host(
    index: IVFPQIndex,
    items: np.ndarray,      # (N, dim) f32 list-sorted (the packed payload)
    q: np.ndarray,          # (Q, dim) f32 queries, true feature width
    d_probe: np.ndarray,    # (Q, R) ADC distances (inf = invalid)
    pos: np.ndarray,        # (Q, R) padded-layout positions
    k_eff: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Re-score the probed ADC candidates against the f32 vectors: the
    expanded-form distance the exact engine uses (||q||^2 - 2 q.x +
    ||x||^2, f32), lexicographic (d2, pos) selection — the ONE tie
    contract.  Chunked over queries so the gathered (chunk, R, D)
    candidate tile stays inside a fixed byte budget."""
    Q, R = d_probe.shape
    rows = index.rows[np.minimum(pos, index.rows.size - 1)]
    invalid = np.isinf(d_probe) | (rows < 0)
    rows = np.where(invalid, 0, rows)
    qn = np.einsum("qd,qd->q", q, q, dtype=np.float32)
    q_chunk = max(1, _REFINE_BUDGET // max(R * index.dim * 4, 1))
    out_d = np.empty((Q, k_eff), np.float32)
    out_i = np.empty((Q, k_eff), np.int64)
    for s in range(0, Q, q_chunk):
        e = min(s + q_chunk, Q)
        cand = items[rows[s:e]]                      # (c, R, D) f32
        xn = np.einsum("crd,crd->cr", cand, cand, dtype=np.float32)
        cross = np.einsum("cd,crd->cr", q[s:e], cand, dtype=np.float32)
        d2 = qn[s:e, None] - 2.0 * cross + xn
        d2 = np.where(invalid[s:e], np.inf, d2)
        order = np.lexsort((pos[s:e], d2), axis=-1)[:, :k_eff]
        rsel = np.take_along_axis(d2, order, axis=1)
        psel = np.take_along_axis(pos[s:e], order, axis=1)
        ids = index.ids[np.minimum(psel, index.ids.size - 1)]
        ids[np.isinf(rsel)] = -1
        out_d[s:e] = np.sqrt(np.maximum(rsel, 0.0))
        out_i[s:e] = ids
    profiling.incr_counter("ann.refined_queries", int(Q))
    return out_d, out_i


def warm_pq_probe_kernels(
    index: IVFPQIndex,
    k: int,
    nprobe: int,
    mesh: Mesh,
    n_queries: int = None,
    query_block: int = 8192,
    refine: bool = True,
    refine_ratio: int = DEFAULT_REFINE_RATIO,
) -> list:
    """Submit the AOT compilation the next same-shape probed PQ search will
    dispatch — key derived by the SAME kernel_cache_key/_probe_k/_pq_probe_chunk
    the dispatch path uses, so the first dispatch lands on the warmed
    executable (the serving entry's warm hook, flat-warm contract)."""
    from ..ops.knn import _query_block_bucket
    from ..ops.precompile import aval, global_precompiler

    k_eff = min(k, index.n_items)
    kp = _probe_k(k_eff, int(refine_ratio) if refine else 1, index.n_items)
    np_eff = _effective_nprobe(index, nprobe)
    block = _query_block_bucket(n_queries or query_block, query_block)
    chunk = _pq_probe_chunk(block, np_eff, index.l_pad, index.m_sub)
    q_aval = aval((block, index.d_pad), np.float32)
    args = (
        index.codes, index.scalars, index.counts,
        index.centroids, index.c_norm, index.codebooks, q_aval,
    )
    statics = dict(k=kp, nprobe=np_eff, chunk=chunk)
    key = kernel_cache_key("ann_pq_probe", args, mesh, statics)
    global_precompiler().submit(
        key, ivfpq_probe_kernel, *args, mesh=mesh, **statics
    )
    return [key]
