#
# IVF-PQ: residual product quantization on top of the IVF machinery —
# the ~32x-compressed 100M+-item tier of the ANN subsystem.
#
# IVF-Flat (ivfflat.py) stores raw f32 vectors, so device memory caps the
# index around ~10M items at embedding dims.  This tier stores each item as
# m_sub one-byte codes plus one f32 correction scalar (FAISS IVFPQ, Jegou
# et al. "Product quantization for nearest neighbor search"; cuML
# algorithm='ivfpq'):
#
#   build:  the coarse quantizer and list assignment are the SHARED IVF
#           helpers (train_coarse_quantizer / assign_nearest — the kmeans
#           engine + the fused distance+argmin kernel).  Residuals
#           r = x - centroid[assign] are split into m_sub subspaces
#           (feature dim zero-padded to m_sub * dsub, dsub a pow2), each
#           subspace gets its own ksub=2^n_bits-centroid codebook trained
#           with the SAME kmeans engine (single-device submesh, FAISS
#           training-sample cap), and encoding is the SAME fused
#           distance+argmin kernel per subspace.  The packed payload
#           (codes + per-item ADC scalars + list layout) is
#           mesh-independent, exactly like PackedIVF.
#   search: asymmetric distance computation (ADC).  With r^ the item's
#           reconstructed residual (disjoint subspace codewords),
#
#             d2(q, item) = ||q - centroid_l - r^||^2
#                         = ||q - centroid_l||^2            (probe term)
#                         + sum_j  -2 q_j . cb[j, code_j]   (query table)
#                         + (||r^||^2 + 2 centroid_l . r^)  (item scalar)
#
#           The probe term falls out of probe selection (select_probes
#           already computes every query->centroid distance), the item
#           scalar is packed per item at build time, and the query table
#           T (m_sub, ksub) is computed ONCE per query block and stays
#           VMEM-resident while the int8 codes of the probed lists stream
#           through the LUT-accumulation kernel (ops/pallas_pq — MXU-free,
#           and the per-item HBM traffic is m_sub bytes instead of
#           IVF-Flat's 4*D: the scan is bandwidth-optimal by layout).
#           Selection and the cross-shard merge are REUSED VERBATIM from
#           the flat kernel (lexicographic (d2, pos) total order +
#           merge_shard_topk), so probed PQ results are bitwise identical
#           on 1-device and 8-device meshes, same contract, same gate.
#   refine: ADC distances are quantized approximations; recall is
#           recovered by probing top (k * refine_ratio) candidates and
#           re-scoring them against the f32 vectors the exactSearch
#           fallback already keeps HOST-side (the expanded-form f32
#           formulation the exact engine uses).  The device index stays
#           codes-only — compression is a device-memory claim; the f32
#           payload lives in host RAM with the model.
#

from __future__ import annotations

import math
import os
from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .. import profiling
from ..compat import shard_map
from ..parallel.mesh import (
    DATA_AXIS,
    axis_sharding,
    data_sharding,
    replicated_sharding,
)
from ..ops.pallas_pq import fastscan_lut_accumulate, lut_accumulate, pack_codes4
from ..ops.precompile import cached_kernel, kernel_cache_key, shape_bucket
from .tier import TieredListPlanes
from .ivfflat import (
    _LIST_ALIGN,
    _MIN_LIST_SLOTS,
    _POS_SENTINEL,
    _TRAIN_CAP,
    _lex_topk,
    _probe_tile_budget,
    assign_nearest,
    ivf_select_kernel,
    merge_shard_topk,
    select_probes,
    train_coarse_quantizer,
)

# ADC re-score chunk budget: bytes of gathered (q_chunk, R, D) f32
# candidates the host refine materializes at once
_REFINE_BUDGET = 256 << 20
# subspace-seed stride: each codebook trains with its own deterministic
# seed so subspaces do not share init draws
_SUBSPACE_SEED_STRIDE = 0x51F1_5EED
# OPQ training sample cap (FAISS-style) and alternation count: rotation
# quality saturates after a handful of assign/encode/Procrustes rounds
_OPQ_TRAIN_CAP = 65536
_OPQ_ITERS = 4
_OPQ_KMEANS_ITERS = 8

DEFAULT_N_BITS = 8
DEFAULT_REFINE_RATIO = 4


def pq_fastscan(n_bits: int, m_sub: int) -> bool:
    """ONE fast-scan route derivation shared by the build validator, the
    stager, dispatch, and warm (the knn _fused_epilogue_route discipline:
    the flag picks the staged code layout AND is a cache-key static, so
    every consumer must derive it identically — here they all read the
    staged index's `fastscan` attribute, which this function set).  n_bits=4
    packs two codes per byte and scans through the 16-lane LUT kernel;
    every other width stays on the one-byte-per-code scan.  An ODD m_sub
    cannot pack two codes per byte, so it stays on the unpacked route too
    (the ops-layer packer pack_codes4 raises the typed error on odd
    widths — this derivation keeps such payloads from ever reaching it).
    Escape hatch: SRML_PQ_FASTSCAN=0 keeps n_bits=4 on the unpacked route
    (read at STAGING, like the fused-epilogue escape)."""
    if int(n_bits) != 4 or int(m_sub) % 2:
        return False
    return os.environ.get("SRML_PQ_FASTSCAN", "1") != "0"


def default_m_sub(dim: int) -> int:
    """Subspace count: the largest power of two <= dim/8 clamped to
    [4, 64] (and never above dim) — ~8 feature dims per one-byte code,
    the 32x-compression operating point at embedding dims (documented
    with the measured recall table in docs/ann_engine.md)."""
    target = max(4, dim // 8)
    m = 1 << (target.bit_length() - 1)
    return int(max(1, min(64, m, dim)))


def _pow2_ceil(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def pq_geometry(dim: int, m_sub: int) -> Tuple[int, int, int]:
    """(m_sub, dsub, d_pad): subspace width is the pow2 bucket of
    ceil(dim / m_sub) and the feature axis zero-pads to m_sub * dsub —
    pow2-padded subspaces keep every per-subspace kernel at one static
    lane-aligned geometry."""
    m_sub = int(max(1, min(m_sub, dim)))
    dsub = _pow2_ceil(-(-dim // m_sub))
    return m_sub, dsub, m_sub * dsub


def _pad_features(x: np.ndarray, d_pad: int) -> np.ndarray:
    if x.shape[1] == d_pad:
        return x
    out = np.zeros((x.shape[0], d_pad), np.float32)
    out[:, : x.shape[1]] = x
    return out


class PackedPQ:
    """Host-side, mesh-INDEPENDENT IVF-PQ payload: per-item codes + ADC
    scalars sorted by list (stable, the SAME layout rule as PackedIVF),
    per-list counts, the coarse centroids, and the subspace codebooks.
    This is what the model persists through the core npz path;
    index_from_packed_pq expands it per mesh."""

    __slots__ = (
        "codes", "scalars", "ids", "items", "counts", "centroids",
        "codebooks", "n_lists", "n_items", "dim", "m_sub", "n_bits",
        "rotation",
    )

    def __init__(
        self, codes, scalars, ids, items, counts, centroids, codebooks,
        n_lists, n_items, dim, m_sub, n_bits, rotation=None,
    ):
        self.codes = codes          # (N, m_sub) uint8, list-sorted
        self.scalars = scalars      # (N,) f32 ADC item scalars, list-sorted
        self.ids = ids              # (N,) int64 user ids, list-sorted
        self.items = items          # (N, dim) f32 list-sorted — HOST-side
        #                             refine/exactSearch payload, never staged
        self.counts = counts        # (nlist_base,) int64 per-list counts
        self.centroids = centroids  # (n_lists, dim) f32 coarse quantizer
        self.codebooks = codebooks  # (m_sub, ksub, dsub) f32
        self.n_lists = int(n_lists)
        self.n_items = int(n_items)
        self.dim = int(dim)
        self.m_sub = int(m_sub)
        self.n_bits = int(n_bits)
        # optional OPQ rotation (d_pad, d_pad) f32 orthogonal, applied to
        # RESIDUALS (r^ = r @ R.T); None = identity (wire back-compat: the
        # srml-pq payload simply omits the R entry)
        self.rotation = rotation


def reconstruct(packed: PackedPQ, rows: Optional[np.ndarray] = None) -> np.ndarray:
    """Decode rows back to (approximate) vectors: coarse centroid + the
    subspace codewords, truncated to the true feature dim.  The encode/
    decode round-trip oracle in tests/test_pq_engine.py rides this."""
    m_sub, dsub, d_pad = pq_geometry(packed.dim, packed.m_sub)
    if rows is None:
        rows = np.arange(packed.codes.shape[0])
    codes = packed.codes[rows].astype(np.int64)
    rec = np.zeros((codes.shape[0], d_pad), np.float32)
    for j in range(m_sub):
        rec[:, j * dsub : (j + 1) * dsub] = packed.codebooks[j][codes[:, j]]
    if packed.rotation is not None:
        # codewords live in ROTATED residual space: un-rotate (R orthogonal,
        # so the inverse of r @ R.T is r^ @ R), host f64 once-rounded
        rec = (
            rec.astype(np.float64) @ packed.rotation.astype(np.float64)
        ).astype(np.float32)
    row_list = np.repeat(
        np.arange(packed.counts.shape[0]), packed.counts
    )[rows]
    cpad = _pad_features(packed.centroids, d_pad)
    return (rec + cpad[row_list])[:, : packed.dim]


def _train_opq_rotation(
    res: np.ndarray,
    dsub: int,
    ksub: int,
    seed: int,
    max_train_rows: int = _OPQ_TRAIN_CAP,
    opq_iters: int = _OPQ_ITERS,
) -> np.ndarray:
    """Learn the OPQ rotation R (d_pad x d_pad, orthogonal) over the coarse
    residuals: alternate (train per-subspace codebooks on the rotated
    sample with the SAME kmeans engine) / (encode with the SAME fused
    assign kernel) / (orthogonal Procrustes update), Ge et al. 2014.

    Procrustes step: minimizing ||X R^T - X^||_F over orthogonal R is
    maximizing tr(R M) with M = X^T X^, so with the SVD M = U S V^T the
    optimum is R = V U^T — host float64, deterministic (fixed sample, fixed
    subspace seeds), mesh-independent like every other trained bit.  The
    returned R is the ONE f32 rounding every consumer shares."""
    n, d_pad = res.shape
    m_sub = d_pad // dsub
    seed = int(seed) & 0x7FFFFFFF
    if n > max_train_rows:
        # deterministic sorted sample — the coarse trainer's sampling rule
        rng = np.random.default_rng(seed)
        sel = np.sort(rng.choice(n, size=max_train_rows, replace=False))
        res = res[sel]
    X = res.astype(np.float64)
    R = np.eye(d_pad)
    for it in range(int(opq_iters)):
        Xr = (X @ R.T).astype(np.float32)
        rec = np.zeros_like(X)
        for j in range(m_sub):
            sl = slice(j * dsub, (j + 1) * dsub)
            cb = train_coarse_quantizer(
                Xr[:, sl],
                ksub,
                (seed + _SUBSPACE_SEED_STRIDE * (m_sub * it + j + 1))
                & 0x7FFFFFFF,
                max_train_rows,
                _OPQ_KMEANS_ITERS,
                1e-3,
                phase="ann.opq_codebook",
            )
            cj = assign_nearest(
                Xr[:, sl], cb,
                phase="ann.opq_encode_block",
                counter="ann.opq_encode_blocks",
            )
            rec[:, sl] = cb[cj]
        M = X.T @ rec
        U, _s, Vh = np.linalg.svd(M)
        R = Vh.T @ U.T
    return R.astype(np.float32)


def build_ivfpq_packed(
    items,
    item_ids: np.ndarray,
    n_lists: int,
    m_sub: int,
    n_bits: int = DEFAULT_N_BITS,
    seed: int = 0,
    max_train_rows: int = _TRAIN_CAP,
    max_iter: int = 25,
    tol: float = 1e-4,
    opq: bool = False,
) -> PackedPQ:
    """Train the coarse quantizer + per-subspace codebooks and pack the
    code lists.  Mesh-independent by the same construction as the flat
    build: every kmeans runs on a single-device submesh over a
    deterministic sample, encoding is per-row argmin, the ADC scalars are
    host float64 math rounded once to f32 (index DATA, like c_norm), and
    the layout is a stable host sort."""
    items = np.ascontiguousarray(np.asarray(items), dtype=np.float32)
    n, d = items.shape
    if n == 0:
        raise ValueError("cannot build an IVF-PQ index over 0 items")
    if not 1 <= int(n_bits) <= 8:
        raise ValueError(f"n_bits must be in [1, 8]; got {n_bits}")
    n_lists = int(max(1, min(n_lists, n)))
    m_sub, dsub, d_pad = pq_geometry(d, m_sub)
    ksub = 1 << int(n_bits)
    seed = int(seed) & 0x7FFFFFFF

    centroids = train_coarse_quantizer(
        items, n_lists, seed, max_train_rows, max_iter, tol
    )
    assign = assign_nearest(items, centroids)

    with profiling.phase("ann.pq_train"):
        # residuals on the padded feature axis; pad dims are exactly zero,
        # so codebook centroids stay exactly zero there (means of zeros)
        cpad = _pad_features(centroids, d_pad)
        res = _pad_features(items, d_pad) - cpad[assign]
        rotation = None
        if opq:
            with profiling.phase("ann.opq_train"):
                rotation = _train_opq_rotation(res, dsub, ksub, seed)
            # codebooks/codes/scalars all live in ROTATED residual space
            # from here on; the stager rotates centroids and the search
            # path rotates queries to match
            res = (
                res.astype(np.float64)
                @ rotation.astype(np.float64).T
            ).astype(np.float32)
        codebooks = np.stack(
            [
                train_coarse_quantizer(
                    res[:, j * dsub : (j + 1) * dsub],
                    ksub,
                    (seed + _SUBSPACE_SEED_STRIDE * (j + 1)) & 0x7FFFFFFF,
                    max_train_rows,
                    max_iter,
                    tol,
                    phase="ann.pq_codebook",
                )
                for j in range(m_sub)
            ]
        )  # (m_sub, ksub_eff, dsub); ksub_eff = min(ksub, n)

    with profiling.phase("ann.pq_encode"):
        codes = np.empty((n, m_sub), np.uint8)
        for j in range(m_sub):
            cj = assign_nearest(
                res[:, j * dsub : (j + 1) * dsub],
                codebooks[j],
                phase="ann.pq_encode_block",
                counter="ann.pq_encode_blocks",
            )
            codes[:, j] = cj.astype(np.uint8)

    with profiling.phase("ann.pq_scalars"):
        # s_item = ||r^||^2 + 2 centroid . r^  in float64, stored f32:
        # mesh-independent index DATA (the same once-rounded contract as
        # the staged c_norm/x_norm).  Under OPQ both factors live in
        # rotated space: r^ is the rotated-residual reconstruction and the
        # centroid term uses c~ = c @ R.T — exactly the centroids the
        # stager puts on device, so the kernel's three ADC terms stay one
        # consistent decomposition of ||q~ - c~ - r^||^2.
        rec = np.zeros((n, d_pad), np.float64)
        idx = codes.astype(np.int64)
        for j in range(m_sub):
            rec[:, j * dsub : (j + 1) * dsub] = codebooks[j][idx[:, j]]
        cass = cpad[assign].astype(np.float64)
        if rotation is not None:
            cass = cass @ rotation.astype(np.float64).T
        scalars = (
            np.einsum("nd,nd->n", rec, rec)
            + 2.0 * np.einsum("nd,nd->n", cass, rec)
        ).astype(np.float32)

    with profiling.phase("ann.layout"):
        nlist_base = -(-n_lists // _LIST_ALIGN) * _LIST_ALIGN
        counts = np.bincount(assign, minlength=nlist_base).astype(np.int64)
        order = np.argsort(assign, kind="stable")
    return PackedPQ(
        codes[order],
        scalars[order],
        np.asarray(item_ids, np.int64)[order],
        items[order],
        counts,
        centroids,
        codebooks.astype(np.float32),
        n_lists,
        n,
        d,
        m_sub,
        n_bits,
        rotation=rotation,
    )


class IVFPQIndex:
    """Device-staged IVF-PQ index (one mesh's layout of a PackedPQ).  The
    device-resident per-item cost is m_sub bytes of codes + 4 bytes of ADC
    scalar — the compression headline device_bytes() measures."""

    __slots__ = (
        "codes", "scalars", "counts", "centroids", "c_norm", "codebooks",
        "ids", "rows", "n_items", "n_lists", "nlist_pad", "l_pad",
        "dim", "d_pad", "m_sub", "dsub", "ksub", "n_bits", "fastscan",
        "rotation",
    )

    def __init__(
        self, codes, scalars, counts, centroids, c_norm, codebooks, ids,
        rows, n_items, n_lists, nlist_pad, l_pad, dim, d_pad, m_sub, dsub,
        ksub, n_bits, fastscan=False, rotation=None,
    ):
        self.codes = codes          # (nlist_pad, L_pad, m_bytes) u8 sharded
        #                             m_bytes = m_sub//2 packed (fast-scan)
        #                             or m_sub one-byte codes
        self.scalars = scalars      # (nlist_pad, L_pad) f32 sharded
        self.counts = counts        # (nlist_pad,) int32 sharded
        self.centroids = centroids  # (nlist_pad, d_pad) f32 replicated
        self.c_norm = c_norm        # (nlist_pad,) f32 replicated, inf pads
        self.codebooks = codebooks  # (m_sub, ksub, dsub) f32 replicated
        self.ids = ids              # (nlist_pad * L_pad,) int64 HOST, -1 pads
        self.rows = rows            # (nlist_pad * L_pad,) int64 HOST packed
        #                             row per slot, -1 pads (the refine map)
        self.n_items = n_items
        self.n_lists = n_lists
        self.nlist_pad = nlist_pad
        self.l_pad = l_pad
        self.dim = dim
        self.d_pad = d_pad
        self.m_sub = m_sub
        self.dsub = dsub
        self.ksub = ksub
        self.n_bits = n_bits
        self.fastscan = bool(fastscan)  # staged-layout route flag: the ONE
        #                                 derivation dispatch/warm read
        self.rotation = rotation        # HOST (d_pad, d_pad) f32 OPQ R or
        #                                 None; queries rotate host-side

    def device_bytes(self) -> int:
        """Global device-resident footprint (logical bytes across shards;
        ids/rows and the refine f32 payload stay host-side)."""
        return int(
            self.codes.nbytes + self.scalars.nbytes + self.counts.nbytes
            + self.centroids.nbytes + self.c_norm.nbytes
            + self.codebooks.nbytes
        )


def _pq_host_layout(packed: PackedPQ, mesh: Mesh) -> dict:
    """The mesh's padded HOST layout of a PackedPQ — the SAME pow2 bucket
    geometry as the flat index (L_pad = pow2 of the longest list, nlist_pad
    a multiple of lcm(8, n_dev), int32 position overflow guard) — shared by
    the all-resident and tiered stagers.  Fast-scan (n_bits=4) packs two
    codes per byte HERE, and OPQ rotates the coarse centroids HERE
    (c~ = c @ R.T, host f64 once-rounded): downstream of this layout the
    whole device side lives in rotated/packed space and the probe kernel's
    gathers/einsums never know the difference."""
    m_sub, dsub, d_pad = pq_geometry(packed.dim, packed.m_sub)
    ksub = packed.codebooks.shape[1]
    fastscan = pq_fastscan(packed.n_bits, m_sub)
    n_dev = mesh.shape[DATA_AXIS]
    mult = math.lcm(_LIST_ALIGN, n_dev)
    nlist_pad = -(-max(packed.n_lists, 1) // mult) * mult
    counts = np.zeros(nlist_pad, np.int64)
    counts[: packed.counts.shape[0]] = packed.counts
    l_pad = shape_bucket(int(max(counts.max(), 1)), lo=_MIN_LIST_SLOTS)
    if nlist_pad * l_pad > int(_POS_SENTINEL):
        raise ValueError(
            f"IVF-PQ layout overflows int32 positions: {nlist_pad} lists x "
            f"{l_pad} slots; raise nlist so lists shrink"
        )
    n = packed.codes.shape[0]
    offs = np.zeros(nlist_pad + 1, np.int64)
    np.cumsum(counts, out=offs[1:])
    row_list = np.repeat(np.arange(nlist_pad, dtype=np.int64), counts)
    slot = np.arange(n, dtype=np.int64) - offs[row_list]
    flat = row_list * l_pad + slot
    src = pack_codes4(packed.codes) if fastscan else packed.codes
    m_bytes = src.shape[1]
    codes = np.zeros((nlist_pad * l_pad, m_bytes), np.uint8)
    codes[flat] = src
    scal = np.zeros(nlist_pad * l_pad, np.float32)
    scal[flat] = packed.scalars
    ids_pad = np.full(nlist_pad * l_pad, -1, np.int64)
    ids_pad[flat] = packed.ids
    rows_pad = np.full(nlist_pad * l_pad, -1, np.int64)
    rows_pad[flat] = np.arange(n, dtype=np.int64)
    cpad = np.zeros((nlist_pad, d_pad), np.float32)
    cpad[: packed.n_lists] = _pad_features(packed.centroids, d_pad)
    if packed.rotation is not None:
        cpad = (
            cpad.astype(np.float64)
            @ packed.rotation.astype(np.float64).T
        ).astype(np.float32)
    c_norm = np.einsum(
        "nd,nd->n", cpad.astype(np.float64), cpad.astype(np.float64)
    ).astype(np.float32)
    c_norm[packed.n_lists :] = np.inf  # pad lists never win a probe slot
    return dict(
        codes=codes.reshape(nlist_pad, l_pad, m_bytes),
        scalars=scal.reshape(nlist_pad, l_pad),
        counts=counts,
        ids=ids_pad,
        rows=rows_pad,
        cpad=cpad,
        c_norm=c_norm,
        nlist_pad=nlist_pad,
        l_pad=l_pad,
        m_sub=m_sub,
        dsub=dsub,
        d_pad=d_pad,
        ksub=ksub,
        fastscan=fastscan,
    )


def index_from_packed_pq(packed: PackedPQ, mesh: Mesh) -> IVFPQIndex:
    """Expand a PackedPQ into this mesh's ALL-RESIDENT device layout:
    (nlist_pad, L_pad, m_bytes) uint8 codes + (nlist_pad, L_pad) f32 ADC
    scalars row-sharded on the LIST axis instead of f32 vectors."""
    lay = _pq_host_layout(packed, mesh)
    stage_bytes = int(lay["codes"].nbytes + lay["scalars"].nbytes)
    with profiling.phase("ann.stage", bytes=stage_bytes):
        index = IVFPQIndex(
            codes=jax.device_put(lay["codes"], axis_sharding(mesh, 0, 3)),
            scalars=jax.device_put(
                lay["scalars"], axis_sharding(mesh, 0, 2)
            ),
            counts=jax.device_put(
                lay["counts"].astype(np.int32), data_sharding(mesh)
            ),
            centroids=jax.device_put(lay["cpad"], replicated_sharding(mesh)),
            c_norm=jax.device_put(lay["c_norm"], replicated_sharding(mesh)),
            codebooks=jax.device_put(
                np.ascontiguousarray(packed.codebooks, np.float32),
                replicated_sharding(mesh),
            ),
            ids=lay["ids"],
            rows=lay["rows"],
            n_items=packed.n_items,
            n_lists=packed.n_lists,
            nlist_pad=lay["nlist_pad"],
            l_pad=lay["l_pad"],
            dim=packed.dim,
            d_pad=lay["d_pad"],
            m_sub=lay["m_sub"],
            dsub=lay["dsub"],
            ksub=lay["ksub"],
            n_bits=packed.n_bits,
            fastscan=lay["fastscan"],
            rotation=packed.rotation,
        )
    profiling.incr_counter("ann.stage_bytes", stage_bytes)
    return index


class TieredIVFPQIndex:
    """IVF-PQ index whose codes/scalars list planes live in a
    TieredListPlanes HBM pool (hot lists pinned, cold lists LRU-paged from
    host RAM) — the billion-scale capacity mode.  The small replicated
    planes (centroids, c_norm, codebooks) and the sharded counts stay fully
    resident; ids/rows/refine payload were host-side already.  Same search
    frame contract as IVFPQIndex; paging is a residency change, never a
    math change (the tiered-vs-resident bitwise gate)."""

    __slots__ = (
        "tier", "counts", "centroids", "c_norm", "codebooks", "ids",
        "rows", "n_items", "n_lists", "nlist_pad", "l_pad", "dim",
        "d_pad", "m_sub", "dsub", "ksub", "n_bits", "fastscan",
        "rotation", "hot_fraction",
    )

    def __init__(self, tier, counts, centroids, c_norm, codebooks, ids,
                 rows, n_items, n_lists, nlist_pad, l_pad, dim, d_pad,
                 m_sub, dsub, ksub, n_bits, fastscan, rotation,
                 hot_fraction):
        self.tier = tier            # TieredListPlanes over [codes, scalars]
        self.counts = counts
        self.centroids = centroids
        self.c_norm = c_norm
        self.codebooks = codebooks
        self.ids = ids
        self.rows = rows
        self.n_items = n_items
        self.n_lists = n_lists
        self.nlist_pad = nlist_pad
        self.l_pad = l_pad
        self.dim = dim
        self.d_pad = d_pad
        self.m_sub = m_sub
        self.dsub = dsub
        self.ksub = ksub
        self.n_bits = n_bits
        self.fastscan = bool(fastscan)
        self.rotation = rotation
        self.hot_fraction = float(hot_fraction)

    def device_bytes(self) -> int:
        return int(
            self.tier.device_bytes() + self.counts.nbytes
            + self.centroids.nbytes + self.c_norm.nbytes
            + self.codebooks.nbytes
        )

    def host_bytes(self) -> int:
        """Host-RAM side of the tier split (the warm list planes; the
        refine f32 payload stays accounted with the model, as before)."""
        return self.tier.host_bytes()


def tiered_index_from_packed_pq(
    packed: PackedPQ,
    mesh: Mesh,
    hot_fraction: float,
    pool_slots: Optional[int] = None,
) -> TieredIVFPQIndex:
    """Stage a PackedPQ with only `hot_fraction` of each shard's lists
    HBM-resident; the rest stay in the host padded layout and page in
    on probe.  Scalars carry the +inf sentinel (slot 0), so a probed
    list that somehow is not resident scores +inf and drops out instead
    of corrupting results."""
    lay = _pq_host_layout(packed, mesh)
    tier = TieredListPlanes(
        planes=[lay["codes"], lay["scalars"]],
        sentinels=[None, np.inf],
        counts=lay["counts"],
        mesh=mesh,
        hot_fraction=hot_fraction,
        pool_slots=pool_slots,
        name="ann.tier",
    )
    with profiling.phase("ann.stage", bytes=tier.device_bytes()):
        index = TieredIVFPQIndex(
            tier=tier,
            counts=jax.device_put(
                lay["counts"].astype(np.int32), data_sharding(mesh)
            ),
            centroids=jax.device_put(lay["cpad"], replicated_sharding(mesh)),
            c_norm=jax.device_put(lay["c_norm"], replicated_sharding(mesh)),
            codebooks=jax.device_put(
                np.ascontiguousarray(packed.codebooks, np.float32),
                replicated_sharding(mesh),
            ),
            ids=lay["ids"],
            rows=lay["rows"],
            n_items=packed.n_items,
            n_lists=packed.n_lists,
            nlist_pad=lay["nlist_pad"],
            l_pad=lay["l_pad"],
            dim=packed.dim,
            d_pad=lay["d_pad"],
            m_sub=lay["m_sub"],
            dsub=lay["dsub"],
            ksub=lay["ksub"],
            n_bits=packed.n_bits,
            fastscan=lay["fastscan"],
            rotation=packed.rotation,
            hot_fraction=hot_fraction,
        )
    return index


def _pq_probe_chunk(block: int, nprobe: int, l_pad: int, m_sub: int) -> int:
    """Power-of-two query-chunk size whose gathered code tile + the LUT
    gather intermediate fit the shared probe tile budget
    (SRML_ANN_TILE_BUDGET).  `block` is a pow2 bucket, so the chunk always
    divides it — the scan needs no ragged tail."""
    per_row = max(nprobe * l_pad * (4 * m_sub + 8), 1)
    c = max(1, _probe_tile_budget() // per_row)
    c = 1 << (c.bit_length() - 1)
    return min(c, block)


@partial(jax.jit, static_argnames=("mesh", "k", "nprobe", "chunk", "fastscan"))
def ivfpq_probe_kernel(
    codes: jax.Array,      # (nlist_pad, L_pad, m_bytes) u8 list-sharded
    scalars: jax.Array,    # (nlist_pad, L_pad) f32 list-sharded ADC scalars
    counts: jax.Array,     # (nlist_pad,) int32 list-sharded
    centroids: jax.Array,  # (nlist_pad, d_pad) replicated
    c_norm: jax.Array,     # (nlist_pad,) replicated, +inf pad rows
    codebooks: jax.Array,  # (m_sub, ksub, dsub) replicated
    queries: jax.Array,    # (Q, d_pad) replicated
    mesh: Mesh,
    k: int,
    nprobe: int,
    chunk: int,
    fastscan: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Probed IVF-PQ ADC search: (euclidean ADC distances (Q, k) ascending,
    positions (Q, k) into the padded list layout — the flat kernel's exact
    output contract, -1/inf sentinel mapping included).  Selection and the
    cross-shard merge are the flat kernel's own helpers, so the bitwise
    1-dev-vs-8-dev parity argument carries over verbatim: ADC terms reduce
    over fixed-shape tiles (m_sub-wide LUT rows, dsub-wide table einsum)
    identical on every mesh size, and every selection orders by the total
    (d2, pos) key.

    `fastscan` (cache-key static, set from the staged index's route flag)
    switches the LUT scan to the packed two-codes-per-byte kernel — the
    code tile is (.., m_sub//2) bytes, everything else is unchanged."""
    _nlist_pad, l_pad, m_bytes = codes.shape
    m_sub = codebooks.shape[0]
    ksub = codebooks.shape[1]
    dsub = codebooks.shape[2]
    scan = fastscan_lut_accumulate if fastscan else lut_accumulate

    def per_shard(cd_loc, sc_loc, cnt_loc, c, cn, cb, q):
        lps = cd_loc.shape[0]
        Q = q.shape[0]
        _qn, d2p, probes, lp, is_local = select_probes(
            q, c, cn, nprobe, lps, mesh
        )
        # the per-query ADC table T[q, j, c] = -2 q_j . cb[j, c] — computed
        # once per block on REPLICATED data, resident across the list scan
        tables = -2.0 * jnp.einsum(
            "qjd,jcd->qjc",
            q.reshape(Q, m_sub, dsub),
            cb,
            precision=jax.lax.Precision.HIGH,
            preferred_element_type=jnp.float32,
        )  # (Q, m_sub, ksub)
        slot = jnp.arange(l_pad, dtype=jnp.int32)

        def chunk_body(carry, i):
            d2p_c = jax.lax.dynamic_slice_in_dim(d2p, i * chunk, chunk)
            lp_c = jax.lax.dynamic_slice_in_dim(lp, i * chunk, chunk)
            loc_c = jax.lax.dynamic_slice_in_dim(is_local, i * chunk, chunk)
            pr_c = jax.lax.dynamic_slice_in_dim(probes, i * chunk, chunk)
            t_c = jax.lax.dynamic_slice_in_dim(tables, i * chunk, chunk)
            # gather the chunk's probed CODE lists from the resident shard:
            # (chunk, nprobe, L_pad, m_bytes) uint8 — m_sub bytes/item
            # (8-bit) or m_sub/2 (fast-scan), the whole bandwidth story
            ctile = jnp.take(cd_loc, lp_c, axis=0)
            stile = jnp.take(sc_loc, lp_c, axis=0)  # (chunk, nprobe, L_pad)
            acc = scan(
                t_c, ctile.reshape(chunk, nprobe * l_pad, m_bytes)
            ).reshape(chunk, nprobe, l_pad)
            # ADC distance: probe term + query-table term + item scalar,
            # fixed association order (parity: same shapes on every mesh)
            d2 = d2p_c[:, :, None] + (acc + stile)
            valid = loc_c[:, :, None] & (
                slot[None, None, :] < jnp.take(cnt_loc, lp_c, axis=0)[:, :, None]
            )
            d2 = jnp.where(valid, d2, jnp.inf)
            pos = pr_c[:, :, None] * l_pad + slot[None, None, :]
            pos = jnp.where(valid, pos, _POS_SENTINEL)
            bd, bp = _lex_topk(
                d2.reshape(chunk, -1), pos.reshape(chunk, -1), k
            )
            return carry, (bd, bp)

        n_chunks = Q // chunk
        _, (ds, ps) = jax.lax.scan(
            chunk_body, 0, jnp.arange(n_chunks, dtype=jnp.int32)
        )
        best_d, best_p = merge_shard_topk(
            ds.reshape(Q, k), ps.reshape(Q, k), mesh, k
        )
        return jnp.sqrt(jnp.maximum(best_d, 0.0)), best_p

    return shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(
            P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(), P(), P(), P(),
        ),
        out_specs=(P(), P()),
        check_vma=False,
    )(codes, scalars, counts, centroids, c_norm, codebooks, queries)


# the tiered PQ pager reuses the flat engine's selection-only kernel (ONE
# select_probes replica, stated once) under its own cache name
ivfpq_select_kernel = ivf_select_kernel


@partial(jax.jit, static_argnames=("mesh", "k", "nprobe", "chunk", "fastscan"))
def ivfpq_probe_tiered_kernel(
    codes: jax.Array,      # (n_dev * slots_per_shard, L_pad, m_bytes) u8
    scalars: jax.Array,    # (n_dev * slots_per_shard, L_pad) f32
    list_slot: jax.Array,  # (nlist_pad,) int32 list->local-slot, 0 sentinel
    counts: jax.Array,     # (nlist_pad,) int32 list-sharded
    centroids: jax.Array,  # (nlist_pad, d_pad) replicated
    c_norm: jax.Array,     # (nlist_pad,) replicated, +inf pad rows
    codebooks: jax.Array,  # (m_sub, ksub, dsub) replicated
    queries: jax.Array,    # (Q, d_pad) replicated
    mesh: Mesh,
    k: int,
    nprobe: int,
    chunk: int,
    fastscan: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """The resident probe kernel's body plus ONE indirection: probed local
    list ids map through list_slot into the shard's slot pool before the
    codes/scalars gathers.  Positions stay GLOBAL (probe * L_pad + slot) so
    ids/rows/refine are untouched, and the gathered tiles hold byte-for-
    byte the values the resident kernel gathers (paged copies of the same
    host rows, same shapes, same reduction order) — which is the whole
    tiered-vs-resident bitwise parity argument.  A probed list whose slot
    is 0 reads the sentinel (+inf scalars) and drops out: residency bugs
    degrade recall, never corrupt."""
    _rows, l_pad, m_bytes = codes.shape
    m_sub = codebooks.shape[0]
    dsub = codebooks.shape[2]
    scan = fastscan_lut_accumulate if fastscan else lut_accumulate

    def per_shard(cd_loc, sc_loc, slot_loc, cnt_loc, c, cn, cb, q):
        lps = cnt_loc.shape[0]
        Q = q.shape[0]
        _qn, d2p, probes, lp, is_local = select_probes(
            q, c, cn, nprobe, lps, mesh
        )
        tables = -2.0 * jnp.einsum(
            "qjd,jcd->qjc",
            q.reshape(Q, m_sub, dsub),
            cb,
            precision=jax.lax.Precision.HIGH,
            preferred_element_type=jnp.float32,
        )
        slot = jnp.arange(l_pad, dtype=jnp.int32)

        def chunk_body(carry, i):
            d2p_c = jax.lax.dynamic_slice_in_dim(d2p, i * chunk, chunk)
            lp_c = jax.lax.dynamic_slice_in_dim(lp, i * chunk, chunk)
            loc_c = jax.lax.dynamic_slice_in_dim(is_local, i * chunk, chunk)
            pr_c = jax.lax.dynamic_slice_in_dim(probes, i * chunk, chunk)
            t_c = jax.lax.dynamic_slice_in_dim(tables, i * chunk, chunk)
            # THE tiered indirection: local list -> pool slot, then gather
            # from the slot pool instead of the full list plane
            ls_c = jnp.take(slot_loc, lp_c, axis=0)
            ctile = jnp.take(cd_loc, ls_c, axis=0)
            stile = jnp.take(sc_loc, ls_c, axis=0)
            acc = scan(
                t_c, ctile.reshape(chunk, nprobe * l_pad, m_bytes)
            ).reshape(chunk, nprobe, l_pad)
            d2 = d2p_c[:, :, None] + (acc + stile)
            valid = loc_c[:, :, None] & (
                slot[None, None, :] < jnp.take(cnt_loc, lp_c, axis=0)[:, :, None]
            )
            d2 = jnp.where(valid, d2, jnp.inf)
            pos = pr_c[:, :, None] * l_pad + slot[None, None, :]
            pos = jnp.where(valid, pos, _POS_SENTINEL)
            bd, bp = _lex_topk(
                d2.reshape(chunk, -1), pos.reshape(chunk, -1), k
            )
            return carry, (bd, bp)

        n_chunks = Q // chunk
        _, (ds, ps) = jax.lax.scan(
            chunk_body, 0, jnp.arange(n_chunks, dtype=jnp.int32)
        )
        best_d, best_p = merge_shard_topk(
            ds.reshape(Q, k), ps.reshape(Q, k), mesh, k
        )
        return jnp.sqrt(jnp.maximum(best_d, 0.0)), best_p

    return shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(
            P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
            P(), P(), P(), P(),
        ),
        out_specs=(P(), P()),
        check_vma=False,
    )(codes, scalars, list_slot, counts, centroids, c_norm, codebooks,
      queries)


def _effective_nprobe(index: IVFPQIndex, nprobe: int) -> int:
    return int(max(1, min(nprobe, index.nlist_pad)))


def _probe_k(k_eff: int, refine_ratio: int, n_items: int) -> int:
    """Candidate count the probe kernel selects: k itself without refine,
    k * refine_ratio (clamped to the item count) with it.  Static — part
    of the kernel cache key, derived identically by warm and dispatch."""
    if refine_ratio <= 1:
        return k_eff
    return int(max(k_eff, min(k_eff * int(refine_ratio), n_items)))


def ivfpq_search_prepared(
    index: IVFPQIndex,
    queries,
    k: int,
    nprobe: int,
    mesh: Mesh,
    query_block: int = 8192,
    refine_items: Optional[np.ndarray] = None,
    refine_ratio: int = DEFAULT_REFINE_RATIO,
) -> Tuple[np.ndarray, np.ndarray]:
    """Probed ADC search + optional f32 refine: returns (distances
    (Q, k_eff) ascending euclidean, ids (Q, k_eff) int64, -1 unfillable),
    k_eff = min(k, n_items) — the flat engine's exact frame contract.

    With `refine_items` (the model's list-sorted f32 payload, the same
    array the exactSearch route scores), the kernel selects the top
    k * refine_ratio ADC candidates and the host re-scores them against
    the true vectors (expanded-form f32, lexicographic (d2, pos) ties) —
    deterministic given the probed candidates, which are themselves
    bitwise mesh-independent, so refined results inherit mesh parity.

    Query blocks ride the kNN engine's dispatch/collect pipeline and every
    kernel dispatch rides the AOT executable cache: repeat same-shape
    searches perform zero new compilations (refine adds none — it is host
    numpy)."""
    from ..ops.knn import _query_block_bucket

    q = np.asarray(queries, dtype=np.float32)
    if q.ndim != 2 or q.shape[1] != index.dim:
        raise ValueError(f"queries must be (n, {index.dim}); got {q.shape}")
    k_eff = min(k, index.n_items)
    if q.shape[0] == 0:
        return (
            np.zeros((0, k_eff), dtype=np.float32),
            np.zeros((0, k_eff), dtype=np.int64),
        )
    refine = refine_items is not None and int(refine_ratio) > 1
    kp = _probe_k(k_eff, int(refine_ratio) if refine else 1, index.n_items)
    np_eff = _effective_nprobe(index, nprobe)
    qp = _pad_features(q, index.d_pad)
    if index.rotation is not None:
        # OPQ: the device side lives in rotated space (rotated centroids,
        # rotated-residual codebooks) — rotate queries to match, host f64
        # once-rounded so every mesh sees the same f32 queries
        qp = (
            qp.astype(np.float64) @ index.rotation.astype(np.float64).T
        ).astype(np.float32)
    block = _query_block_bucket(q.shape[0], query_block)
    chunk = _pq_probe_chunk(block, np_eff, index.l_pad, index.m_sub)
    if isinstance(index, TieredIVFPQIndex):
        d_all, p_all = _tiered_probe_all(
            index, qp, kp, np_eff, mesh, block, chunk
        )
    else:
        d_all, p_all = _resident_probe_all(
            index, qp, kp, np_eff, mesh, block, chunk
        )
    profiling.incr_counter("ann.searches")
    if refine:
        with profiling.phase("ann.refine"):
            return _refine_host(
                index, refine_items, q, d_all, p_all, k_eff
            )
    with profiling.phase("ann.merge"):
        ids = index.ids[np.minimum(p_all, index.ids.size - 1)]
        ids[np.isinf(d_all)] = -1
        return d_all[:, :k_eff], ids[:, :k_eff]


def _resident_probe_all(
    index: IVFPQIndex,
    qp: np.ndarray,
    kp: int,
    np_eff: int,
    mesh: Mesh,
    block: int,
    chunk: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """All-resident probe sweep: query blocks ride the kNN engine's
    dispatch/collect pipeline, every dispatch rides the AOT cache."""
    from ..ops.knn import _pipeline_window, _run_block_pipeline

    n = qp.shape[0]
    starts = list(range(0, n, block))
    pending: list = []
    out_d, out_p = [], []

    def _dispatch(bi):
        start = starts[bi]
        qb = qp[start : start + block]
        n_q = qb.shape[0]
        if n_q != block:
            qb = np.concatenate(
                [qb, np.zeros((block - n_q, index.d_pad), np.float32)]
            )
        d, pos = cached_kernel(
            "ann_pq_probe", ivfpq_probe_kernel,
            index.codes, index.scalars, index.counts,
            index.centroids, index.c_norm, index.codebooks, jnp.asarray(qb),
            mesh=mesh, k=kp, nprobe=np_eff, chunk=chunk,
            fastscan=index.fastscan,
        )
        for h in (d, pos):
            try:
                h.copy_to_host_async()
            except (AttributeError, RuntimeError):
                break
        pending.append((d, pos, n_q))

    def _collect(bi):
        d, pos, n_q = pending.pop(0)
        d_host, pos_host = jax.device_get((d, pos))
        out_d.append(d_host[:n_q])
        out_p.append(pos_host[:n_q])
    _run_block_pipeline(
        len(starts), _dispatch, _collect, _pipeline_window(2),
        phase_prefix="ann",
    )
    return np.concatenate(out_d), np.concatenate(out_p)


def _tiered_probe_all(
    index: TieredIVFPQIndex,
    qp: np.ndarray,
    kp: int,
    np_eff: int,
    mesh: Mesh,
    block: int,
    chunk: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Tiered probe sweep: per block, (1) the selection kernel replays
    probe selection so the host learns which lists each query touches,
    (2) the planner splits the block into contiguous query groups whose
    distinct cold lists fit the slot pool, (3) each group pages in and
    dispatches the tiered kernel AT THE SAME BLOCK BUCKET with the group's
    queries at their ORIGINAL row offsets (zeros elsewhere).  Every ADC/
    selection op is row-independent, so a row's outputs are bitwise what
    the one-dispatch all-resident sweep produces for that row — slicing
    out the group rows is exact, and every dispatch reuses the same cached
    executables (zero new compiles at steady state)."""
    n = qp.shape[0]
    out_d = np.empty((n, kp), np.float32)
    out_p = np.empty((n, kp), np.int32)
    # Pass 1: dispatch every block's selection kernel, then ONE batched
    # device_get — the planner needs host probes, but not one sync per block.
    blocks = []
    sel = []
    for start in range(0, n, block):
        n_q = min(block, n - start)
        qb = np.zeros((block, index.d_pad), np.float32)
        qb[:n_q] = qp[start : start + n_q]
        blocks.append((start, n_q, qb))
        sel.append(
            cached_kernel(
                "ann_pq_select", ivfpq_select_kernel,
                index.centroids, index.c_norm, jnp.asarray(qb),
                mesh=mesh, nprobe=np_eff,
            )
        )
    # Pass 2: plan/page/dispatch per group, deferring the result fetch to
    # ONE device_get — tier buffers are immutably replaced on slot writes,
    # so earlier results stay valid on their old buffers.
    spans = []
    parts = []
    for (start, n_q, qb), probes in zip(blocks, jax.device_get(sel)):
        for s, e in index.tier.plan_groups(probes[:n_q]):
            planes, slot_map = index.tier.acquire(probes[s:e].ravel())
            gq = np.zeros((block, index.d_pad), np.float32)
            gq[s:e] = qb[s:e]
            spans.append((start, s, e))
            parts.append(
                cached_kernel(
                    "ann_pq_probe_tiered", ivfpq_probe_tiered_kernel,
                    planes[0], planes[1], slot_map, index.counts,
                    index.centroids, index.c_norm, index.codebooks,
                    jnp.asarray(gq),
                    mesh=mesh, k=kp, nprobe=np_eff, chunk=chunk,
                    fastscan=index.fastscan,
                )
            )
    for (start, s, e), (d_host, p_host) in zip(spans, jax.device_get(parts)):
        out_d[start + s : start + e] = d_host[s:e]
        out_p[start + s : start + e] = p_host[s:e]
    return out_d, out_p


def _refine_host(
    index: IVFPQIndex,
    items: np.ndarray,      # (N, dim) f32 list-sorted (the packed payload)
    q: np.ndarray,          # (Q, dim) f32 queries, true feature width
    d_probe: np.ndarray,    # (Q, R) ADC distances (inf = invalid)
    pos: np.ndarray,        # (Q, R) padded-layout positions
    k_eff: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Re-score the probed ADC candidates against the f32 vectors: the
    expanded-form distance the exact engine uses (||q||^2 - 2 q.x +
    ||x||^2, f32), lexicographic (d2, pos) selection — the ONE tie
    contract.  Chunked over queries so the gathered (chunk, R, D)
    candidate tile stays inside a fixed byte budget."""
    Q, R = d_probe.shape
    rows = index.rows[np.minimum(pos, index.rows.size - 1)]
    invalid = np.isinf(d_probe) | (rows < 0)
    rows = np.where(invalid, 0, rows)
    qn = np.einsum("qd,qd->q", q, q, dtype=np.float32)
    q_chunk = max(1, _REFINE_BUDGET // max(R * index.dim * 4, 1))
    out_d = np.empty((Q, k_eff), np.float32)
    out_i = np.empty((Q, k_eff), np.int64)
    for s in range(0, Q, q_chunk):
        e = min(s + q_chunk, Q)
        cand = items[rows[s:e]]                      # (c, R, D) f32
        xn = np.einsum("crd,crd->cr", cand, cand, dtype=np.float32)
        cross = np.einsum("cd,crd->cr", q[s:e], cand, dtype=np.float32)
        d2 = qn[s:e, None] - 2.0 * cross + xn
        d2 = np.where(invalid[s:e], np.inf, d2)
        order = np.lexsort((pos[s:e], d2), axis=-1)[:, :k_eff]
        rsel = np.take_along_axis(d2, order, axis=1)
        psel = np.take_along_axis(pos[s:e], order, axis=1)
        ids = index.ids[np.minimum(psel, index.ids.size - 1)]
        ids[np.isinf(rsel)] = -1
        out_d[s:e] = np.sqrt(np.maximum(rsel, 0.0))
        out_i[s:e] = ids
    profiling.incr_counter("ann.refined_queries", int(Q))
    return out_d, out_i


def warm_pq_probe_kernels(
    index: IVFPQIndex,
    k: int,
    nprobe: int,
    mesh: Mesh,
    n_queries: int = None,
    query_block: int = 8192,
    refine: bool = True,
    refine_ratio: int = DEFAULT_REFINE_RATIO,
) -> list:
    """Submit the AOT compilation the next same-shape probed PQ search will
    dispatch — key derived by the SAME kernel_cache_key/_probe_k/_pq_probe_chunk
    the dispatch path uses, so the first dispatch lands on the warmed
    executable (the serving entry's warm hook, flat-warm contract)."""
    from ..ops.knn import _query_block_bucket
    from ..ops.precompile import aval, global_precompiler

    k_eff = min(k, index.n_items)
    kp = _probe_k(k_eff, int(refine_ratio) if refine else 1, index.n_items)
    np_eff = _effective_nprobe(index, nprobe)
    block = _query_block_bucket(n_queries or query_block, query_block)
    chunk = _pq_probe_chunk(block, np_eff, index.l_pad, index.m_sub)
    q_aval = aval((block, index.d_pad), np.float32)
    statics = dict(k=kp, nprobe=np_eff, chunk=chunk, fastscan=index.fastscan)
    keys = []
    if isinstance(index, TieredIVFPQIndex):
        planes, slot_map = index.tier.snapshot()
        args = (
            planes[0], planes[1], slot_map, index.counts,
            index.centroids, index.c_norm, index.codebooks, q_aval,
        )
        key = kernel_cache_key("ann_pq_probe_tiered", args, mesh, statics)
        global_precompiler().submit(
            key, ivfpq_probe_tiered_kernel, *args, mesh=mesh, **statics
        )
        keys.append(key)
        sel_args = (index.centroids, index.c_norm, q_aval)
        sel_statics = dict(nprobe=np_eff)
        sel_key = kernel_cache_key(
            "ann_pq_select", sel_args, mesh, sel_statics
        )
        global_precompiler().submit(
            sel_key, ivfpq_select_kernel, *sel_args,
            mesh=mesh, **sel_statics,
        )
        keys.append(sel_key)
        return keys
    args = (
        index.codes, index.scalars, index.counts,
        index.centroids, index.c_norm, index.codebooks, q_aval,
    )
    key = kernel_cache_key("ann_pq_probe", args, mesh, statics)
    global_precompiler().submit(
        key, ivfpq_probe_kernel, *args, mesh=mesh, **statics
    )
    return [key]
