#
# srml-watch: the always-on health plane.
#
# srml-scope (profiling.py) made runs explainable AFTER the fact — but only
# while a trace session is open, and only if the run finishes.  A wedged
# collective rendezvous, a stuck serving worker, or an HBM blowup still died
# silently: the reference punts the whole failure class to barrier-stage
# task retry (core.py:488 dispatch) the way CUDA stacks punt to NCCL
# timeouts.  Production telemetry systems pair passive traces with an
# ACTIVE plane (Dapper-style tracing + Prometheus-style health/burn
# alerting — PAPERS.md monitoring entries); this module is that half:
#
#   1. FLIGHT RECORDER — a fixed-size ring of recent span-open/close and
#      counter events that is ALWAYS on (unlike trace sessions): O(1)
#      bounded memory, one small lock per event.  profiling.span() and
#      profiling.incr_counter() feed it through the `profiling._flight`
#      hook; dump() writes the ring as Chrome-trace-compatible
#      `flight-<tag>-*.json` under SRML_TRACE_DIR.  Dumps fire on
#      unhandled exception in a fit task / serving worker (flight_scope),
#      on watchdog firing, and on explicit dump().  The recorder also
#      tracks every thread's OPEN span stack, so "where is thread X right
#      now" is answerable at any moment — the question a hang poses.
#   2. STALL DETECTION — per-rank heartbeats published through the
#      existing control plane during barrier fits (HeartbeatPublisher; a
#      non-collective publish/read surface the FileControlPlane and
#      LocalControlPlane grow), and a driver-side StallWatchdog that —
#      after SRML_WATCH_STALL_S of frozen progress — names the stuck rank
#      AND the innermost open span it is wedged in.  Liveness is the
#      watched FIT thread's span-close count, not the publisher thread's
#      clock: a wedged fit with a healthy publisher still trips the dog.
#   3. DEVICE-MEMORY ACCOUNTING — HBM/host watermarks sampled via jax
#      device memory stats at span boundaries (free when the backend has
#      no stats, as XLA:CPU does not), per-phase peak-delta attribution
#      merged into TelemetrySnapshot.memory, and executable-cache
#      introspection from ops/precompile (entry count, bucket geometries,
#      estimated bytes).
#   4. HEALTH SURFACE — serving/engine.py owns the per-server lifecycle
#      states (WARMING/READY/DEGRADED/DRAINING/UNHEALTHY) and SLO burn;
#      this module provides the gauge registry plumbing
#      (profiling.register_gauges) that flows health + memory through
#      export_metrics()/render_prometheus().
#
# Everything here is observability: a failure inside watch must never fail
# the fit/search/server it watches (best-effort writes, Exception-scoped).
#
# docs/observability.md §7 documents the model and every SRML_WATCH_* knob.
#

from __future__ import annotations

import contextlib
import json
import logging
import os
import sys
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from . import profiling, sanitize

_log = logging.getLogger("spark_rapids_ml_tpu.watch")

WATCH_ENV = "SRML_WATCH"                    # "0" disables the flight recorder
RING_ENV = "SRML_WATCH_RING"                # ring capacity (events)
MAX_DUMPS_ENV = "SRML_WATCH_MAX_DUMPS"      # per-process dump bound
HEARTBEAT_ENV = "SRML_WATCH_HEARTBEAT_S"    # per-rank heartbeat period
STALL_ENV = "SRML_WATCH_STALL_S"            # stall threshold (0 = off)

_DEFAULT_RING = 4096
_DEFAULT_MAX_DUMPS = 32
_DEFAULT_HEARTBEAT_S = 1.0


from .utils import env_float as _env_float  # noqa: E402 - knob parsing


def stall_threshold_s() -> float:
    """SRML_WATCH_STALL_S: seconds of frozen progress before a rank or a
    serving worker is declared stalled.  0 (the default) disables stall
    detection — a legitimate cold XLA compile can freeze span progress for
    minutes, so the threshold is deployment policy, not a constant."""
    return _env_float(STALL_ENV, 0.0)


def heartbeat_interval_s() -> float:
    """SRML_WATCH_HEARTBEAT_S: the per-rank heartbeat period.  This is the
    ONE liveness cadence the health plane is expressed in — the srml-wire
    membership lease defaults to 1.5x this value (netplane.lease_interval_s),
    which is what makes "a lost rank is named within 2 heartbeat intervals"
    a contract instead of a coincidence."""
    return _env_float(HEARTBEAT_ENV, _DEFAULT_HEARTBEAT_S)


# -- the flight recorder ------------------------------------------------------

_wtls = threading.local()


class FlightRecorder:
    """Fixed-size, lock-cheap ring of recent observability events plus a
    registry of every thread's currently-OPEN span stack.

    Ring entries (tuples, kind first):
      ("span", name, t0, t1, ident, tname, depth, error)
      ("ctr",  name, amount, total, t, ident)
      ("exc",  tag, t, ident, tname, etype, message, failing_span)

    The per-thread open stack lives in the owning thread's TLS and is
    REGISTERED here so other threads (watchdogs, heartbeat publishers,
    dump()) can read "what is thread X inside right now".  Owner-writes /
    reader-snapshots under the GIL; readers copy before iterating."""

    def __init__(self, cap: Optional[int] = None):
        # clamped >= 1: a zero/negative SRML_WATCH_RING must degrade to a
        # tiny ring, never to IndexError inside every span/counter the
        # recorder watches (observability must not fail the work)
        raw = cap if cap is not None else _env_float(RING_ENV, _DEFAULT_RING)
        self.cap = max(1, int(raw))
        self._ring: List[Optional[tuple]] = [None] * self.cap
        self._idx = 0
        self._total = 0
        self._lock = sanitize.lockdep_lock("watch.ring")
        # ident -> [thread_obj, open_stack(list of (name, t_open)), closes]
        self._threads: Dict[int, list] = {}
        self._mem_lock = sanitize.lockdep_lock("watch.mem")
        self._phase_mem: Dict[str, list] = {}  # name -> [count, peak, sum_delta]
        self._mem_sampler: Optional[Callable[[], Optional[Tuple[float, float]]]] = None
        self._mem_probed = False

    # -- thread registry -----------------------------------------------------
    def _thread_slot(self) -> list:
        # keyed by RECORDER identity too: a thread whose TLS slot belongs
        # to a previous recorder (disable/enable cycle, test fixtures) gets
        # a fresh slot registered HERE, so open_spans()/progress() always
        # describe this recorder's own bookkeeping
        if getattr(_wtls, "rec", None) is self:
            return _wtls.slot
        th = threading.current_thread()
        slot = [th, [], 0]
        _wtls.slot = slot
        _wtls.rec = self
        _wtls.err_span = None
        # registration + prune under the ring lock: every instrumented
        # thread passes through here, and a concurrent insert during the
        # prune's items() scan would raise (dict changed size) — caught by
        # graftlint R12; the TLS fast path above keeps this once-per-thread
        with self._lock:
            self._threads[th.ident] = slot
            if len(self._threads) > 256:  # prune dead threads, bounded
                for ident in [
                    i for i, s in self._threads.items() if not s[0].is_alive()
                ]:
                    del self._threads[ident]
        return slot

    # -- event intake (called from profiling hooks) --------------------------
    def on_span_open(self, name: str) -> None:
        slot = self._thread_slot()
        mem = None
        if self._mem_sampler is not None:
            try:
                mem = self._mem_sampler()
            except Exception:
                mem = None
        elif not self._mem_probed:
            self._probe_memory()
        slot[1].append((name, profiling.now(), mem))

    def on_span_close(self, name: str, t0: float, t1: float, error: bool) -> None:
        slot = self._thread_slot()
        stack = slot[1]
        mem_open = None
        if stack and stack[-1][0] == name:
            mem_open = stack.pop()[2]
        depth = len(stack)
        slot[2] += 1  # progress: the liveness signal heartbeats publish
        if error:
            if getattr(_wtls, "err_span", None) is None:
                _wtls.err_span = name  # innermost failing span
        else:
            _wtls.err_span = None
        if mem_open is not None and self._mem_sampler is not None:
            try:
                now_mem = self._mem_sampler()
            except Exception:
                now_mem = None
            if now_mem is not None:
                in_use0, _peak0 = mem_open
                _in_use1, peak1 = now_mem
                with self._mem_lock:
                    agg = self._phase_mem.setdefault(name, [0, 0.0, 0.0])
                    agg[0] += 1
                    agg[1] = max(agg[1], float(peak1))
                    agg[2] += max(0.0, float(peak1) - float(in_use0))
        th = slot[0]
        self._append(("span", name, t0, t1, th.ident, th.name, depth, error))

    def on_counter(self, name: str, amount: int, total: int) -> None:
        self._append(
            ("ctr", name, amount, total, profiling.now(),
             threading.get_ident())
        )

    def record_exception(self, exc: BaseException, tag: str) -> None:
        """Ring-record an unhandled exception with the innermost failing
        span (the first span that closed with the error in flight)."""
        th = threading.current_thread()
        failing = getattr(_wtls, "err_span", None)
        if failing is None:
            stack = getattr(_wtls, "slot", [None, []])[1]
            failing = stack[-1][0] if stack else None
        # counter first: the exception instant must be the ring's (and the
        # dump's) LAST event, so "what failed" is the end of the timeline
        profiling.incr_counter("watch.exceptions")
        self._append(
            ("exc", tag, profiling.now(), th.ident, th.name,
             type(exc).__name__, str(exc)[:512], failing)
        )

    def _append(self, rec: tuple) -> None:
        with self._lock:
            self._ring[self._idx] = rec
            self._idx = (self._idx + 1) % self.cap
            self._total += 1

    # -- read surface --------------------------------------------------------
    def records(self) -> List[tuple]:
        """Ring contents, oldest first."""
        with self._lock:
            if self._total < self.cap:
                return [r for r in self._ring[: self._idx]]
            return [
                r
                for r in self._ring[self._idx :] + self._ring[: self._idx]
                if r is not None
            ]

    def event_count(self) -> int:
        """Lifetime events recorded (ring holds the most recent cap)."""
        with self._lock:
            return self._total

    def open_spans(self) -> Dict[int, Tuple[str, List[str]]]:
        """{thread ident: (thread name, open span names, outer->inner)} for
        every registered live thread — the hang-time question."""
        out: Dict[int, Tuple[str, List[str]]] = {}
        for ident, slot in list(self._threads.items()):
            th, stack = slot[0], list(slot[1])
            if th.is_alive():
                out[ident] = (th.name, [s[0] for s in stack])
        return out

    def innermost(self, ident: Optional[int] = None) -> Optional[str]:
        """Innermost open span of `ident` (default: calling thread)."""
        slot = self._threads.get(
            ident if ident is not None else threading.get_ident()
        )
        if not slot or not slot[1]:
            return None
        return slot[1][-1][0]

    def progress(self, ident: int) -> int:
        """Span closes observed on thread `ident` — the heartbeat liveness
        counter (a wedged thread's progress freezes even while other
        threads keep the process looking busy)."""
        slot = self._threads.get(ident)
        return slot[2] if slot else 0

    # -- memory sampling -----------------------------------------------------
    def set_memory_sampler(
        self, fn: Optional[Callable[[], Optional[Tuple[float, float]]]]
    ) -> None:
        """Install `fn() -> (bytes_in_use, peak_bytes)` as the span-boundary
        sampler (tests inject a fake; real backends get _device_mem)."""
        self._mem_sampler = fn
        self._mem_probed = True

    def _probe_memory(self) -> None:
        """One-time capability probe: XLA:CPU exposes no memory_stats, so
        the sampler stays None (zero per-span cost) off-TPU.  Deferred
        until jax is already imported — watch never pulls jax in."""
        if "jax" not in sys.modules:
            return
        self._mem_probed = True
        try:
            stats = _device_mem()
        except Exception:
            stats = None
        if stats is not None:
            self._mem_sampler = _device_mem

    def phase_memory(self) -> Dict[str, Dict[str, float]]:
        """{span name: {count, peak_bytes, sum_delta_bytes}} — per-phase
        peak-delta attribution accumulated over the process lifetime."""
        with self._mem_lock:
            return {
                k: {"count": v[0], "peak_bytes": v[1], "sum_delta_bytes": v[2]}
                for k, v in self._phase_mem.items()
            }

    def telemetry_memory(self) -> Dict[str, Dict[str, float]]:
        """The mergeable memory section a TelemetrySnapshot carries:
        per-phase attribution under mem.phase.*, device and host watermarks
        under mem.hbm / mem.host.  Merge algebra: count sums, peak_bytes
        maxes, sum_delta_bytes sums (see TelemetrySnapshot.merge)."""
        out: Dict[str, Dict[str, float]] = {}
        for name, d in self.phase_memory().items():
            out[f"mem.phase.{name}"] = d
        dev = None
        try:
            dev = _device_mem()
        except Exception:
            dev = None
        if dev is not None:
            out["mem.hbm"] = {
                "count": 1,
                "peak_bytes": float(dev[1]),
                "sum_delta_bytes": float(dev[0]),
            }
        host = _host_mem()
        if host is not None:
            out["mem.host"] = {
                "count": 1,
                "peak_bytes": float(host[1]),
                "sum_delta_bytes": float(host[0]),
            }
        return out


def _device_mem() -> Optional[Tuple[float, float]]:
    """(bytes_in_use, peak_bytes_in_use) summed over local devices, or None
    when the backend exposes no memory stats (XLA:CPU)."""
    import jax

    in_use = peak = 0.0
    seen = False
    for d in jax.local_devices():
        stats = d.memory_stats()
        if not stats:
            continue
        seen = True
        in_use += float(stats.get("bytes_in_use", 0))
        peak += float(stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0)))
    return (in_use, peak) if seen else None


def _host_mem() -> Optional[Tuple[float, float]]:
    """(current RSS bytes, peak RSS bytes) for this process, best-effort."""
    try:
        import resource

        peak = float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024.0
    except Exception:
        return None
    cur = 0.0
    try:
        with open("/proc/self/statm") as f:
            cur = float(f.read().split()[1]) * float(os.sysconf("SC_PAGE_SIZE"))
    except Exception:
        cur = peak
    return (cur, peak)


# -- module-level recorder + install ------------------------------------------

_recorder: Optional[FlightRecorder] = None
_install_lock = sanitize.lockdep_lock("watch.install")


def recorder() -> Optional[FlightRecorder]:
    """The installed process-wide recorder (None when SRML_WATCH=0)."""
    return _recorder


def failing_span() -> Optional[str]:
    """The calling thread's innermost FAILING span (the first span that
    closed with an error in flight), falling back to its innermost OPEN
    span, or None without a recorder.  This is what the srml-shield abort
    marker names: when TpuContext.__exit__ broadcasts an abort, surviving
    ranks' RemoteRankError quotes this span — "rank 1 failed in
    exchange.ring" — instead of a bare exception type."""
    err = getattr(_wtls, "err_span", None)
    if err is not None:
        return err
    rec = _recorder
    if rec is None:
        return None
    return rec.innermost()


def install() -> Optional[FlightRecorder]:
    """Install the flight recorder as profiling's span/counter hook and
    register the watch gauges.  Idempotent; called from profiling at import
    time so the recorder is on for every process that touches the package
    (SRML_WATCH=0 opts out)."""
    global _recorder
    with _install_lock:
        if _recorder is not None:
            return _recorder
        if os.environ.get(WATCH_ENV, "1") == "0":
            return None
        _recorder = FlightRecorder()
        profiling._flight = _recorder
        profiling.register_gauges("watch", _watch_gauges)
        return _recorder


def disable() -> None:
    """Detach the recorder (tests / embedders that want the pre-watch
    zero-hook span path).  enable() or install() re-attaches."""
    global _recorder
    with _install_lock:
        profiling._flight = None
        profiling.unregister_gauges("watch")
        _recorder = None


def enable() -> Optional[FlightRecorder]:
    return install()


def _watch_gauges() -> Dict[str, float]:
    """Memory watermarks + flight-recorder and executable-cache gauges for
    export_metrics()/render_prometheus().  Best-effort: a gauge that cannot
    be read is omitted, never raised."""
    out: Dict[str, float] = {}
    host = _host_mem()
    if host is not None:
        out["mem.host.rss_bytes"] = host[0]
        out["mem.host.peak_rss_bytes"] = host[1]
    try:
        dev = _device_mem() if "jax" in sys.modules else None
    except Exception:
        dev = None
    if dev is not None:
        out["mem.device.bytes_in_use"] = dev[0]
        out["mem.device.peak_bytes_in_use"] = dev[1]
    rec = _recorder
    if rec is not None:
        out["watch.flight_events"] = float(rec.event_count())
    pre = sys.modules.get("spark_rapids_ml_tpu.ops.precompile")
    if pre is not None:
        try:
            stats = pre.executable_cache_stats()
            out["precompile.cache.entries"] = float(stats["entries"])
            out["precompile.cache.in_flight"] = float(stats["in_flight"])
            if stats.get("est_code_bytes") is not None:
                out["precompile.cache.est_code_bytes"] = float(
                    stats["est_code_bytes"]
                )
        except Exception:
            pass
    return out


# -- serving health-plane gauge flattening ------------------------------------
# The ONE rule turning per-server/per-replica health dicts
# (serving/engine.ModelServer.health shape) into gauge keys for the
# srml_health Prometheus family.  ModelRegistry and the srml-router both
# ride it, so a dashboard keyed on health.<name>.* reads a flat registry
# and a replicated router identically — replicas just carry their
# "<model>-r<i>" names, and per-replica restart counts flow as
# health.<name>.restarts (the restart-storm signal per REPLICA, which the
# plane-wide rollup total hides).
def health_gauges(
    models: Dict[str, Dict[str, Any]], prefix: str = "health"
) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for name, h in models.items():
        out[f"{prefix}.{name}.state_code"] = float(h["state_code"])
        if "attainment" in h:
            out[f"{prefix}.{name}.attainment"] = float(h["attainment"])
            out[f"{prefix}.{name}.burn"] = float(h["burn"])
            out[f"{prefix}.{name}.queued_rows"] = float(h["queued_rows"])
            if h.get("p99_ms") is not None:
                out[f"{prefix}.{name}.p99_ms"] = float(h["p99_ms"])
        if "restarts" in h:
            out[f"{prefix}.{name}.restarts"] = float(h["restarts"])
    return out


# -- flight dump --------------------------------------------------------------

_dump_lock = sanitize.lockdep_lock("watch.dump")
_dump_seq = 0


def dump(tag: str = "flight", path: Optional[str] = None) -> Optional[str]:
    """Write the flight ring (plus every thread's currently-open spans) as
    one Chrome-trace-compatible JSON file: `flight-<tag>-<pid>-<seq>.json`
    under SRML_TRACE_DIR, or to an explicit `path`.  Returns the written
    path, or None when no recorder / no target dir / dump budget spent.
    Best-effort by design — a dump failure is logged, never raised."""
    global _dump_seq
    rec = _recorder
    if rec is None:
        return None
    if path is None:
        out_dir = os.environ.get(profiling.TRACE_ENV)
        if not out_dir:
            return None
        with _dump_lock:
            if _dump_seq >= int(_env_float(MAX_DUMPS_ENV, _DEFAULT_MAX_DUMPS)):
                return None
            _dump_seq += 1
            seq = _dump_seq
        safe = profiling._safe_tag(tag)
        path = os.path.join(
            out_dir, f"flight-{safe}-{os.getpid()}-{seq:04d}.json"
        )
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        doc = _flight_trace_doc(rec)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
        profiling.incr_counter("watch.dumps")
        _log.warning("flight recorder dumped %d event(s) -> %s",
                     len(doc["traceEvents"]), path)
        return path
    except Exception as exc:  # noqa: BLE001 - observability never fails work
        _log.warning("flight dump for %r failed: %s", tag, exc)
        return None


def _flight_trace_doc(rec: FlightRecorder) -> Dict[str, Any]:
    """Chrome trace-event document from the ring: closed spans as complete
    ("X") events, counters as counter ("C") events, exceptions as instant
    ("i") events, plus begin ("B") events for every span still OPEN at dump
    time (a hang dump shows where each thread is wedged) and thread_name
    metadata.  Timestamps are microseconds relative to the profiling epoch,
    the same base trace_session exports use."""
    pid = os.getpid()
    epoch = profiling._EPOCH
    tid_of: Dict[int, int] = {}
    names: Dict[int, str] = {}

    def tid(ident: int, tname: Optional[str] = None) -> int:
        t = tid_of.setdefault(ident, len(tid_of) + 1)
        if tname:
            names.setdefault(t, tname)
        return t

    events: List[Dict[str, Any]] = []
    for r in rec.records():
        kind = r[0]
        if kind == "span":
            _, name, t0, t1, ident, tname, depth, error = r
            args: Dict[str, Any] = {"depth": depth}
            if error:
                args["error"] = True
            events.append({
                "name": name, "cat": "srml-watch", "ph": "X",
                "ts": (t0 - epoch) * 1e6, "dur": (t1 - t0) * 1e6,
                "pid": pid, "tid": tid(ident, tname), "args": args,
            })
        elif kind == "ctr":
            _, name, _amount, total, t, ident = r
            events.append({
                "name": name, "cat": "srml-watch", "ph": "C",
                "ts": (t - epoch) * 1e6, "pid": pid, "tid": tid(ident),
                "args": {"value": total},
            })
        elif kind == "exc":
            _, tag, t, ident, tname, etype, msg, failing = r
            events.append({
                "name": "exception", "cat": "srml-watch", "ph": "i",
                "s": "t", "ts": (t - epoch) * 1e6,
                "pid": pid, "tid": tid(ident, tname),
                "args": {
                    "tag": tag, "type": etype, "message": msg,
                    "failing_span": failing,
                },
            })
    # open spans: B events at their open time so the wedged phase renders
    for ident, slot in list(rec._threads.items()):
        th, stack = slot[0], list(slot[1])
        if not th.is_alive():
            continue
        for name, t_open, _mem in stack:
            events.append({
                "name": name, "cat": "srml-watch", "ph": "B",
                "ts": (t_open - epoch) * 1e6,
                "pid": pid, "tid": tid(ident, th.name),
                "args": {"open": True},
            })
    events.sort(key=lambda e: e["ts"])
    meta = [
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": t,
         "args": {"name": n}}
        for t, n in sorted(names.items())
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


@contextlib.contextmanager
def flight_scope(tag: str) -> Iterator[None]:
    """Record-and-dump guard for a unit of work: an exception escaping the
    scope is ring-recorded (with the innermost failing span) and triggers a
    flight dump before propagating unchanged.  Wraps every top-level fit
    (core / parallel runner) and the serving dispatch path."""
    try:
        yield
    except BaseException as exc:
        rec = _recorder
        if rec is not None:
            try:
                rec.record_exception(exc, tag)
                dump(tag)
            except Exception:  # noqa: BLE001 - never mask the real error
                pass
        raise


# -- per-rank heartbeats + stall watchdog -------------------------------------


class HeartbeatPublisher:
    """Daemon thread publishing this rank's liveness through the control
    plane every SRML_WATCH_HEARTBEAT_S: payload carries the watched FIT
    thread's innermost open span and its span-close count (progress).  The
    publisher itself staying alive proves nothing — the watchdog keys on
    `progress`, which only the fit thread advances."""

    def __init__(
        self,
        control_plane: Any,
        rank: int,
        watch_ident: Optional[int] = None,
        interval_s: Optional[float] = None,
    ):
        self.cp = control_plane
        self.rank = int(rank)
        self.ident = (
            watch_ident if watch_ident is not None else threading.get_ident()
        )
        self.interval_s = (
            interval_s if interval_s is not None else heartbeat_interval_s()
        )
        self._stop = threading.Event()
        self._seq = 0
        self._thread = threading.Thread(
            target=self._run, name=f"srml-watch-hb-r{self.rank}", daemon=True
        )
        self._thread.start()

    def _payload(self) -> str:
        rec = _recorder
        return json.dumps({
            "rank": self.rank,
            "seq": self._seq,
            "span": rec.innermost(self.ident) if rec is not None else None,
            "progress": rec.progress(self.ident) if rec is not None else 0,
        })

    def _run(self) -> None:
        while True:
            try:
                self._seq += 1
                self.cp.publish_health(self._payload())
            except Exception as exc:  # noqa: BLE001 - observability only
                _log.debug("heartbeat publish failed: %s", exc)
            if self._stop.wait(self.interval_s):
                return

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


class StallWatchdog:
    """Driver-side watchdog over control-plane heartbeats: a rank whose
    `progress` counter has not advanced for `stall_s` (or that never
    heartbeats at all) is reported ONCE per stall episode — by rank and by
    the innermost open span its last heartbeat named.  This turns the
    known XLA:CPU rendezvous-deadlock class from a silent hang into a
    one-line diagnosis; firing also dumps the local flight ring."""

    def __init__(
        self,
        control_plane: Any,
        nranks: int,
        stall_s: Optional[float] = None,
        poll_s: Optional[float] = None,
        on_stall: Optional[Callable[[Dict[str, Any]], None]] = None,
    ):
        self.cp = control_plane
        self.nranks = int(nranks)
        self.stall_s = stall_s if stall_s is not None else stall_threshold_s()
        self.poll_s = poll_s if poll_s is not None else max(
            0.05, min(1.0, self.stall_s / 4.0 or 1.0)
        )
        self.on_stall = on_stall
        self.reports: List[Dict[str, Any]] = []
        self._last: Dict[int, Tuple[int, float, Dict[str, Any]]] = {}
        self._fired: Dict[int, bool] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="srml-watch-dog", daemon=True
        )
        self._start_t = profiling.now()
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self._check()
            except Exception as exc:  # noqa: BLE001 - the dog must not die
                _log.debug("watchdog check failed: %s", exc)

    def _check(self) -> None:
        now = profiling.now()
        raw = self.cp.read_health()
        for r in range(self.nranks):
            payload: Dict[str, Any] = {}
            if r in raw:
                try:
                    payload = json.loads(raw[r])
                except (ValueError, TypeError):
                    payload = {}
            progress = int(payload.get("progress", -1))
            prev = self._last.get(r)
            if prev is None or prev[0] != progress:
                self._last[r] = (progress, now, payload)
                self._fired[r] = False
                continue
            age = now - prev[1]
            if age > self.stall_s and not self._fired.get(r):
                self._fired[r] = True
                span = payload.get("span") if payload else None
                report = {
                    "rank": r,
                    "span": span if span else "<no open span>",
                    "age_s": round(age, 3),
                    "reason": (
                        "no heartbeat" if not payload else "progress frozen"
                    ),
                }
                self.reports.append(report)
                profiling.incr_counter("watch.stalls")
                _log.error(
                    "watchdog: rank %d stalled for %.1fs in span %r (%s) — "
                    "dumping flight recorder",
                    r, age, report["span"], report["reason"],
                )
                dump(f"stall-rank{r}")
                if self.on_stall is not None:
                    try:
                        self.on_stall(report)
                    except Exception:  # noqa: BLE001
                        pass

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


class _FitHealth:
    """Handle bundling the per-rank heartbeat publisher and (on rank 0) the
    driver-side watchdog for one barrier fit; stop() tears both down."""

    def __init__(self, publisher=None, watchdog=None):
        self.publisher = publisher
        self.watchdog = watchdog

    def stop(self) -> None:
        if self.publisher is not None:
            self.publisher.stop()
        if self.watchdog is not None:
            self.watchdog.stop()


def start_fit_health(
    control_plane: Any, rank: int, nranks: int
) -> _FitHealth:
    """Liveness plumbing for one barrier fit task: every rank publishes
    heartbeats (when the control plane supports the non-collective
    publish/read surface), and rank 0 additionally runs the stall watchdog
    when SRML_WATCH_STALL_S > 0.  No-op handle single-controller, when the
    plane is gather-only (live Spark's BarrierTaskContext), or when the
    recorder is off."""
    if (
        nranks <= 1
        or _recorder is None
        or not hasattr(control_plane, "publish_health")
        or heartbeat_interval_s() <= 0
    ):
        return _FitHealth()
    publisher = HeartbeatPublisher(control_plane, rank)
    watchdog = None
    if rank == 0 and stall_threshold_s() > 0 and hasattr(
        control_plane, "read_health"
    ):
        watchdog = StallWatchdog(control_plane, nranks)
    return _FitHealth(publisher, watchdog)


# -- introspection ------------------------------------------------------------


def ring_stats() -> Dict[str, Any]:
    """Flight-recorder self-description: capacity, lifetime events, open
    spans per live thread — the `watch` section of a health report."""
    rec = _recorder
    if rec is None:
        return {"enabled": False}
    return {
        "enabled": True,
        "capacity": rec.cap,
        "events": rec.event_count(),
        "open_spans": {
            name: spans for _i, (name, spans) in rec.open_spans().items()
        },
        "dumps": _dump_seq,
    }


# Self-install at module bottom.  profiling's own bootstrap covers the
# common import order (profiling first), but when THIS module is imported
# first its `from . import profiling` triggers that bootstrap against a
# partially-initialized watch namespace — install() does not exist yet and
# the bootstrap degrades to a warning.  Installing here (idempotent, honors
# SRML_WATCH=0 inside install()) makes the recorder always-on regardless of
# which module the embedding application touches first.
install()
