#
# Core estimator/model machinery: ingest -> mesh-sharded jax arrays, fit
# dispatch, transform dispatch, persistence.
#
# Structural counterpart of the reference's core
# (/root/reference/python/src/spark_rapids_ml/core.py): _CumlCaller
# _pre_process_data/_call_cuml_fit_func (:344-640), _CumlEstimator._fit_internal
# (:856), _FitMultipleIterator (:649), _CumlModel transform/evaluate plumbing
# (:1126-1377), and the writer/reader pairs (:139-226).  The execution model is
# redesigned TPU-first rather than translated:
#
#   reference: driver builds a closure -> mapInPandas -> barrier task per GPU
#              -> NCCL rank per task -> cuML MG kernels all-reduce per iter
#   here:      ingest concatenates Arrow/pandas partitions into host numpy,
#              zero-pads rows, device_puts with NamedSharding(P("data")) over a
#              jax Mesh, and calls a pure jax fit function; XLA/GSPMD inserts
#              psum/all_gather collectives (ICI intra-host, DCN inter-host).
#              One *process* spans many chips (single-controller); multi-host
#              runs extend the same mesh via parallel/context.TpuContext.
#
# Padded rows are masked through the `weight` vector so every solver is
# weighted by construction (weightCol support falls out for free).
#

from __future__ import annotations

import json
import os
import threading
from abc import abstractmethod
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np
import pandas as pd

import jax

from .dataframe import DataFrame, FEATURE_BLOCK_ATTR, as_dataframe
from .params import Param, Params, _TpuParams
from .parallel.mesh import get_mesh, shard_rows, data_sharding
from .parallel.partition import PartitionDescriptor
from .utils import get_logger, materialize_feature_block


def _is_pyspark_dataframe(dataset: Any) -> bool:
    """True for live pyspark DataFrames, detected by module name so pyspark
    is never imported here (it is absent on plain TPU-VM installs)."""
    return (type(dataset).__module__ or "").startswith("pyspark.sql")


def _use_executor_path(dataset: Any) -> bool:
    """Whether a dataset should run on the Spark executors (barrier fit /
    mapInPandas transform) rather than driver-local: a live pyspark
    DataFrame, unless SRML_SPARK_COLLECT=1 forces the old collect path
    (single TPU-VM notebooks where the driver owns the chips)."""
    return _is_pyspark_dataframe(dataset) and os.environ.get(
        "SRML_SPARK_COLLECT", "0"
    ) != "1"


def _maybe_x64(dtype: Any):
    """jax x64 scope for float64 fits; a no-op for float32."""
    import contextlib

    if np.dtype(dtype) == np.float64:
        from .compat import enable_x64

        return enable_x64(True)
    return contextlib.nullcontext()


# Reserved key a fit result dict carries its TelemetrySnapshot under —
# attached executor-side (parallel/runner) or by the local fit dispatch,
# popped by _fit_internal before the attrs reach _create_model, exposed as
# model.fit_telemetry().  Never a model attribute.
TELEMETRY_ATTR = "__srml_telemetry__"

# single-slot device-input cache; see _TpuCaller._build_fit_inputs
_FIT_INPUT_CACHE: Dict[str, Any] = {}


def clear_fit_cache() -> None:
    """Release the device-resident fit-input cache (frees the pinned HBM
    shardings and the host block references).  Also reachable via
    DataFrame.unpersist()."""
    _FIT_INPUT_CACHE.pop("slot", None)


def _partition_feature_block(part: pd.DataFrame, input_col: str):
    """Zero-copy contiguous feature block stashed by DataFrame.from_numpy,
    or None.  Guarded on row count plus first/last cell equality so
    partitions derived by filtering/slicing/reordering (pandas attrs
    propagation is version-dependent) never read a stale block."""
    holder = part.attrs.get(FEATURE_BLOCK_ATTR)
    block = holder.blocks.get(input_col) if holder is not None else None
    if block is None or block.shape[0] != len(part) or len(part) == 0:
        return None
    col = part[input_col]
    if hasattr(block, "tocsr"):
        # sparse CSR block: the placeholder column holds local row positions
        # (DataFrame.from_numpy); any row slice/reorder breaks the 0..n-1
        # run and the stale block is rejected
        if int(col.iloc[0]) == 0 and int(col.iloc[-1]) == len(part) - 1:
            return block
        return None
    if np.array_equal(col.iloc[0], block[0]) and np.array_equal(
        col.iloc[-1], block[-1]
    ):
        return block
    return None

def extract_partition_features(
    part: pd.DataFrame,
    input_col: Optional[str],
    input_cols: Optional[List[str]],
    dtype: np.dtype,
    densify_sparse: bool = True,
):
    """Feature matrix for one partition, honoring a stashed feature block —
    dense 2-D or sparse CSR (DataFrame.from_numpy).  Model-side consumers
    (transform-evaluate, kneighbors ingest) MUST use this instead of reading
    the column directly: sparse partitions carry a placeholder column whose
    cells are row positions, not features."""
    block = (
        _partition_feature_block(part, input_col) if input_col is not None else None
    )
    return materialize_feature_block(
        block, part, input_col, input_cols, dtype, densify_sparse=densify_sparse
    )


_SinglePdDataFrameBatchType = Tuple[pd.DataFrame, Optional[pd.DataFrame]]


@dataclass
class FitInputs:
    """Device-resident, row-sharded training inputs handed to fit functions."""

    X: jax.Array                      # (N_pad, D) row-sharded over mesh "data" axis
    weight: jax.Array                 # (N_pad,) user weight * valid-row mask
    y: Optional[jax.Array]            # (N_pad,) labels (supervised only)
    n_rows: int                       # valid rows (N_pad >= n_rows)
    n_cols: int
    mesh: Any
    pdesc: PartitionDescriptor
    dtype: np.dtype
    row_id: Optional[np.ndarray] = None   # original row numbers (host, unpadded)
    extra_cols: Dict[str, np.ndarray] = field(default_factory=dict)
    # host copies of the (unpadded) labels/weights when ingest had them —
    # single-controller label discovery reads these instead of round-
    # tripping the device label shards over the host link per fit
    host_y: Optional[np.ndarray] = None
    host_w: Optional[np.ndarray] = None
    # multi-controller context: which rank this process is, how many ranks
    # cooperate, and the string control plane they share (None single-
    # controller).  Fit functions that need host-side views of the inputs
    # must go through the local-shard helpers below + a control-plane
    # gather instead of np.asarray on the global arrays (which raises on
    # arrays spanning non-addressable devices).
    rank: int = 0
    nranks: int = 1
    control_plane: Any = None


def _aligned_shard_objs(*arrays: jax.Array):
    """Device-aligned tuples of addressable Shard objects of row-aligned
    global arrays, ordered by global row offset.  In single-controller mode
    this walks every shard (covering the whole array); in multi-process mode
    it only ever touches this process's addressable shards.  Shard .data
    stays on device — callers choose what (if anything) to fetch."""
    primary = sorted(
        arrays[0].addressable_shards, key=lambda s: s.index[0].start or 0
    )
    others = [{s.device: s for s in a.addressable_shards} for a in arrays[1:]]
    for s in primary:
        yield (s,) + tuple(o[s.device] for o in others)


def _row_aligned_shards(*arrays: jax.Array):
    """Host-numpy view of _aligned_shard_objs (fetches every local shard)."""
    for shards in _aligned_shard_objs(*arrays):
        yield tuple(np.asarray(s.data) for s in shards)


def discover_label_classes(
    inputs: FitInputs, cast: Optional[Any] = None
) -> np.ndarray:
    """Globally-sorted unique label values: per-rank np.unique over the
    rank's LOCAL shards (masked by weight > 0), unioned across ranks through
    the control plane — the reference's per-worker label discovery merged
    over the barrier allGather (classification.py:936-1001).  Safe in
    multi-process fits: never touches a non-addressable shard."""
    assert inputs.y is not None
    # the no-cast target is y's own dtype so every rank returns the same
    # dtype even when some rank holds zero valid rows
    target = np.dtype(cast) if cast is not None else np.dtype(inputs.y.dtype)
    if inputs.nranks == 1 and inputs.host_y is not None:
        # single-controller: the ingest's host label copy avoids fetching
        # the device label shards back over the host link on EVERY fit
        # (labels are re-uploaded per fit, so the fetch never warms)
        vals = inputs.host_y
        if inputs.host_w is not None:
            vals = vals[inputs.host_w > 0]
        if cast is not None:
            vals = vals.astype(target)
        return np.unique(vals).astype(target, copy=False)
    locs = []
    for y_loc, w_loc in _row_aligned_shards(inputs.y, inputs.weight):
        vals = y_loc[w_loc > 0]
        if cast is not None:
            vals = vals.astype(target)
        if vals.size:
            locs.append(np.unique(vals))
    local = (
        np.unique(np.concatenate(locs)) if locs else np.zeros(0, dtype=target)
    )
    if inputs.nranks > 1 and inputs.control_plane is not None:
        from .parallel.runner import allgather_ndarray

        merged = [
            m
            for m in allgather_ndarray(inputs.control_plane, inputs.rank, local)
            if m.size
        ]
        if merged:
            local = np.unique(np.concatenate([m.astype(target) for m in merged]))
    return local.astype(target, copy=False)


# fit function: (inputs, params-dict) -> model attribute dict (or list of
# dicts when fitting multiple param maps in a single pass)
FitFunc = Callable[[FitInputs, Dict[str, Any]], Union[Dict[str, Any], List[Dict[str, Any]]]]
# transform function: feature batch -> {output column name: column values}
TransformFunc = Callable[[np.ndarray], Dict[str, Any]]


class _TpuCaller(_TpuParams):
    """Shared ingest + fit-dispatch (reference _CumlCaller core.py:327-647)."""

    def _use_dtype(
        self, df: DataFrame, input_col: Optional[str], input_cols: Optional[List[str]]
    ) -> np.dtype:
        dev = getattr(df, "_device_features", None)
        if dev is not None:
            return np.dtype(dev[0].dtype)
        if self._float32_inputs:
            return np.dtype(np.float32)
        # float32_inputs=False preserves the input dtype (reference
        # core.py:363-401 keeps f64 data in f64 and f32 in f32)
        for part in df.partitions:
            if len(part) == 0:
                continue
            if input_col is not None:
                block = _partition_feature_block(part, input_col)
                if block is not None:
                    dt = block.dtype  # also covers sparse CSR blocks, whose
                    # placeholder column would misreport int64
                else:
                    dt = np.asarray(part[input_col].iloc[0]).dtype
            else:
                assert input_cols is not None
                dt = np.result_type(*(part[c].dtype for c in input_cols))
            if np.issubdtype(dt, np.floating):
                return np.dtype(dt)
            break
        return np.dtype(np.float64)

    def _extract_partition_features(
        self, part: pd.DataFrame, input_col: Optional[str], input_cols: Optional[List[str]], dtype: np.dtype
    ) -> np.ndarray:
        block = (
            _partition_feature_block(part, input_col) if input_col is not None else None
        )
        return materialize_feature_block(
            block,
            part,
            input_col,
            input_cols,
            dtype,
            densify_sparse=not self._supports_sparse_input,
            on_densify=lambda: get_logger(type(self)).warning(
                "%s has no sparse path; densifying the CSR partition",
                type(self).__name__,
            ),
        )

    def _fit_label_col(self) -> Optional[str]:
        """Column to extract as ``FitInputs.y``, or None.  Supervised
        estimators always consume their labelCol; optionally-supervised
        estimators (UMAP, reference umap.py:939-947) override this to opt in
        only when the user set one."""
        if isinstance(self, _TpuEstimatorSupervised) and self.hasParam("labelCol"):
            return self.getOrDefault("labelCol")
        return None

    def _pre_process_data(
        self, df: DataFrame
    ) -> Tuple[List[np.ndarray], Optional[List[np.ndarray]], Optional[List[np.ndarray]], np.dtype]:
        """Per-partition (features, label, weight) numpy extraction with dtype
        casting (reference core.py:344-422 + supervised label cast :918-952)."""
        input_col, input_cols = self._get_input_columns()
        dtype = self._use_dtype(df, input_col, input_cols)
        feats, labels, weights = [], None, None
        label_col = self._fit_label_col()
        weight_col = (
            self.getOrDefault("weightCol")
            if self.hasParam("weightCol") and self.isSet("weightCol")
            else None
        )
        if label_col is not None:
            labels = []
        if weight_col is not None:
            weights = []
        # labels/weights extract at >= float32 regardless of a low-precision
        # FEATURE dtype (float32_inputs=False + f16/bf16 features): integer
        # class labels above the half-precision mantissa are not exact and
        # would silently corrupt label discovery — same rule as the
        # from_device path (_build_fit_inputs_device)
        ldtype = np.dtype(np.float32) if np.dtype(dtype).itemsize < 4 else dtype
        for part in df.partitions:
            feats.append(self._extract_partition_features(part, input_col, input_cols, dtype))
            if labels is not None:
                labels.append(np.asarray(part[label_col].to_numpy(), dtype=ldtype))
            if weights is not None:
                weights.append(np.asarray(part[weight_col].to_numpy(), dtype=ldtype))
        return feats, labels, weights, dtype

    def _build_fit_inputs(
        self, df: DataFrame, keep_row_id: bool = False
    ) -> FitInputs:
        dev = getattr(df, "_device_features", None)
        if dev is not None:
            return self._build_fit_inputs_device(df, dev, keep_row_id)
        feats, labels, weights, dtype = self._pre_process_data(df)
        partition_rows = [f.shape[0] for f in feats]
        nonempty = [f for f in feats if f.shape[0] > 0]
        if not nonempty:
            raise RuntimeError("Dataset is empty; cannot fit")
        mesh = get_mesh(self.num_workers)
        from . import profiling

        # Device-resident input cache (single slot).  Repeated fits over the
        # same immutable block-backed DataFrame — fitMultiple, repeated
        # fit() calls in notebooks/benchmarks — reuse the sharded device
        # arrays instead of re-streaming GBs over PCIe/host link each fit.
        # This is the TPU analog of the reference riding spark-rapids'
        # GPU-resident columnar data (its executors hand cuML device-side
        # arrays when the plugin has the DataFrame cached on GPU).  Only
        # fits whose feature arrays ARE the DataFrame's zero-copy blocks
        # are cached (their ids are stable and pinned by the df itself);
        # generic-stacked partitions (from_pandas, multi_cols, CV fold
        # splits) produce fresh arrays every fit and are never stored.
        # clear_fit_cache() / DataFrame.unpersist() releases the slot.
        input_col, _input_cols = self._get_input_columns()
        cacheable = input_col is not None and all(
            f.shape[0] == 0 or f is _partition_feature_block(p, input_col)
            for f, p in zip(feats, df.partitions)
        )
        # Only the FEATURE arrays are cached: labels/weights are re-extracted
        # per fit (they are O(N) host arrays whose identity is NOT stable —
        # to_numpy() returns fresh objects, and labelCol/weightCol can change
        # between fits over the same cached features).
        cache_key = (tuple(id(f) for f in nonempty), str(dtype), id(mesh))
        cached = _FIT_INPUT_CACHE.get("slot")
        if cached is not None and cached[0] == cache_key:
            Xs, n_rows, n_cols, _host_refs = cached[1]
            profiling.incr_counter("ingest.cache_hit")
        elif any(hasattr(f, "tocsr") for f in nonempty):
            # sparse ingest: CSR partitions -> one padded ELL pair, row-
            # sharded like a dense block (ops/sparse.py).  No densification
            # at any point; nnz is the memory footprint.
            import scipy.sparse as sp

            from .ops.sparse import ell_device_from_scipy

            _FIT_INPUT_CACHE.pop("slot", None)
            csr = sp.vstack(nonempty).tocsr() if len(nonempty) > 1 else nonempty[0]
            n_rows, n_cols = csr.shape
            # ingest.staged counts DATASET uploads: the batched sweep's
            # "one staged dataset per sweep" contract is gated on it
            profiling.incr_counter("ingest.staged")
            with profiling.phase("srml.device_put"):
                Xs = ell_device_from_scipy(csr, dtype=dtype, mesh=mesh)
            if cacheable:
                _FIT_INPUT_CACHE["slot"] = (
                    cache_key,
                    (Xs, n_rows, n_cols, list(nonempty)),
                )
        else:
            # free the previous slot's device arrays BEFORE allocating the
            # new dataset so peak HBM is one dataset, not two
            _FIT_INPUT_CACHE.pop("slot", None)
            from .utils import _concat_and_free

            X = _concat_and_free(list(nonempty), order="C")
            n_rows, n_cols = X.shape
            profiling.incr_counter("ingest.staged")
            with profiling.phase("srml.device_put"):
                Xs, _ = shard_rows(X, mesh)
            if cacheable:
                _FIT_INPUT_CACHE["slot"] = (
                    cache_key,
                    (Xs, n_rows, n_cols, list(nonempty)),
                )
        n_pad = Xs.shape[0]
        # >= float32 for the O(N) label/weight vectors (see _pre_process_data)
        ldtype = np.dtype(np.float32) if np.dtype(dtype).itemsize < 4 else dtype
        y_np = np.concatenate(labels) if labels is not None else None
        w_np = (
            np.concatenate(weights)
            if weights is not None
            else np.ones(n_rows, dtype=ldtype)
        )
        mask = np.zeros(n_pad, dtype=ldtype)
        mask[:n_rows] = w_np
        ws = jax.device_put(mask, data_sharding(mesh))
        ys = None
        if y_np is not None:
            y_pad = np.zeros(n_pad, dtype=ldtype)
            y_pad[:n_rows] = y_np
            ys = jax.device_put(y_pad, data_sharding(mesh))
        pdesc = PartitionDescriptor.build(partition_rows, n_cols)
        return FitInputs(
            X=Xs,
            weight=ws,
            y=ys,
            n_rows=n_rows,
            n_cols=n_cols,
            mesh=mesh,
            pdesc=pdesc,
            dtype=dtype,
            row_id=np.arange(n_rows) if keep_row_id else None,
            host_y=y_np,
            host_w=w_np if weights is not None else None,
        )

    def _build_fit_inputs_device(
        self, df: DataFrame, dev: Any, keep_row_id: bool
    ) -> FitInputs:
        """FitInputs straight from a DataFrame.from_device feature array:
        no feature extraction, no upload.  Labels/weights still come from
        the (host) partitions; padded rows are masked through the weight
        vector exactly like the host-ingest path.  The built inputs are
        cached ON THE FRAME (keyed by the consuming label/weight columns),
        so repeated fits skip the per-fit label/mask device_puts the way
        the host path's input cache does."""
        Xs, n_rows, n_cols, _fcol = dev
        dtype = np.dtype(Xs.dtype)
        mesh = get_mesh(self.num_workers)
        n_pad = Xs.shape[0]
        label_col = self._fit_label_col()
        weight_col = (
            self.getOrDefault("weightCol")
            if self.hasParam("weightCol") and self.isSet("weightCol")
            else None
        )
        cache_key = (label_col, weight_col, id(mesh), bool(keep_row_id))
        cached = getattr(df, "_device_fit_inputs", None)
        if cached is not None and cached[0] == cache_key:
            return cached[1]
        # labels/weights are O(N) scalars — always at least float32: a
        # bf16 from_device FEATURE array must not round them (integer
        # class labels above 256 are not exact in bf16, silently
        # corrupting label discovery and training targets)
        ldtype = np.dtype(np.float32) if dtype.itemsize < 4 else dtype
        w_np = np.ones(n_rows, dtype=ldtype)
        if weight_col is not None:
            w_np = np.concatenate(
                [
                    np.asarray(p[weight_col].to_numpy(), dtype=ldtype)
                    for p in df.partitions
                ]
            )
        mask = np.zeros(n_pad, dtype=ldtype)
        mask[:n_rows] = w_np
        ws = jax.device_put(mask, data_sharding(mesh))
        ys = None
        if label_col is not None:
            y_np = np.concatenate(
                [
                    np.asarray(p[label_col].to_numpy(), dtype=ldtype)
                    for p in df.partitions
                ]
            )
            y_pad = np.zeros(n_pad, dtype=ldtype)
            y_pad[:n_rows] = y_np
            ys = jax.device_put(y_pad, data_sharding(mesh))
        inputs = FitInputs(
            X=Xs,
            weight=ws,
            y=ys,
            n_rows=n_rows,
            n_cols=n_cols,
            mesh=mesh,
            pdesc=PartitionDescriptor.build([n_rows], n_cols),
            dtype=dtype,
            row_id=np.arange(n_rows) if keep_row_id else None,
            host_y=y_np if label_col is not None else None,
            host_w=w_np if weight_col is not None else None,
        )
        df._device_fit_inputs = (cache_key, inputs)
        return inputs

    def _call_tpu_fit_func(
        self,
        dataset: Any,
        paramMaps: Optional[List[Dict[Param, Any]]] = None,
    ) -> Union[Dict[str, Any], List[Dict[str, Any]]]:
        """Dispatch one (or a batch of) fits on the device mesh (reference
        _call_cuml_fit_func core.py:488-640, single data load for all param
        maps as in _fit_internal core.py:723-752).

        A live pyspark DataFrame routes through the Spark barrier stage so
        training happens INSIDE the executors over a pod-wide jax.distributed
        mesh — the dataset is never collected to the driver.  Set
        SRML_SPARK_COLLECT=1 to force the old driver-local collect path
        (single TPU-VM notebooks where the driver owns the chips)."""
        if _use_executor_path(dataset):
            from .spark.adapter import barrier_fit_estimator

            # driver-side input-column check BEFORE launching the barrier
            # stage (pyspark DataFrames expose .columns, which is all
            # _validate_parameters reads) — a missing column must fail here,
            # not as an opaque executor traceback
            self._validate_parameters(dataset)
            extra = (
                [self._paramMap_to_tpu_overrides(pm) for pm in paramMaps]
                if paramMaps is not None
                else None
            )
            results = barrier_fit_estimator(self, dataset, extra_params=extra)
            # the executors' merged telemetry snapshot rides the result wire
            # (parallel/runner attaches it); the driver-side phase view comes
            # from it — on live Spark the fit never ran on this thread
            from . import profiling

            telem = results[0].get(TELEMETRY_ATTR) if results else None
            self._last_fit_phase_times = (
                profiling.TelemetrySnapshot.from_dict(telem).phase_seconds()
                if telem
                else {}
            )
            return results if paramMaps is not None else results[0]
        from . import profiling

        profiling.reset_phase_times()
        counters0 = profiling.counters()
        df = as_dataframe(dataset)
        self._validate_parameters(df)
        # float64 fits genuinely run in float64 (reference core.py:363-401
        # keeps f64 end-to-end): without x64, jax.device_put silently
        # canonicalizes f64 -> f32.  The x64 scope must cover BOTH ingest
        # (device_put) and the fit (trace-time dtypes); it recompiles the
        # kernels for f64, which TPUs execute via (slower) emulation.
        input_col, input_cols = self._get_input_columns()
        from . import watch

        # watch.flight_scope: an unhandled exception anywhere in the fit
        # dumps the always-on flight ring (with the innermost failing span)
        # to SRML_TRACE_DIR before propagating — the crash-time counterpart
        # of the trace session, which only exports on success
        with watch.flight_scope(
            f"fit-{type(self).__name__}"
        ), profiling.trace_session(f"fit-{type(self).__name__}"), _maybe_x64(
            self._use_dtype(df, input_col, input_cols)
        ):
            # srml-shield: the runner.fit injection site fires on BOTH fit
            # paths — here (driver-local) and in parallel/runner.fit (the
            # barrier task) — so a fault plan written against the site name
            # covers whichever launcher ran the fit
            from .parallel import faults

            faults.site("runner.fit", rank=0)
            with profiling.phase("srml.ingest"):
                inputs = self._build_fit_inputs(df)
            extra_params = None
            if paramMaps is not None:
                extra_params = [
                    self._paramMap_to_tpu_overrides(pm) for pm in paramMaps
                ]
            fit_func = self._get_tpu_fit_func(df, extra_params)
            logger = get_logger(type(self))
            logger.info(
                "Invoking TPU fit: %d rows x %d cols on %d-device mesh",
                inputs.n_rows, inputs.n_cols, inputs.mesh.devices.size,
            )
            from .sanitize import sanitize_scope

            with profiling.maybe_trace(type(self).__name__):
                with profiling.phase("srml.fit"), sanitize_scope():
                    result = fit_func(inputs, dict(self._tpu_params))
        self._last_fit_phase_times = profiling.phase_times()
        # telemetry rides the SAME attribute dicts the executor path ships,
        # so _fit_internal attaches model.fit_telemetry() uniformly (the
        # snapshot is shared across a single-pass multi-model fit — one
        # data load, one solver pass, one set of phase timers)
        snap = profiling.TelemetrySnapshot.capture(counters0, rank=0)
        for r in result if isinstance(result, list) else [result]:
            r[TELEMETRY_ATTR] = snap.to_dict()
        return result

    def _paramMap_to_tpu_overrides(self, paramMap: Dict[Param, Any]) -> Dict[str, Any]:
        mapping = self._param_mapping()
        overrides: Dict[str, Any] = {}
        for param, value in paramMap.items():
            solver = mapping.get(param.name)
            if solver:
                value_mapping = self._param_value_mapping()
                if solver in value_mapping:
                    mapped = value_mapping[solver](value)
                    if mapped is None:
                        raise ValueError(
                            f"Value '{value}' for param '{param.name}' is not supported on TPU"
                        )
                    value = mapped
                overrides[solver] = value
            elif solver is None and param.name in mapping:
                raise ValueError(f"Param '{param.name}' unsupported on TPU")
        return overrides

    def _validate_parameters(self, df: DataFrame) -> None:
        input_col, input_cols = self._get_input_columns()
        cols = df.columns
        missing = [
            c for c in ([input_col] if input_col else input_cols or []) if c not in cols
        ]
        if missing:
            raise ValueError(f"Input column(s) {missing} not found in dataset {cols}")

    # -- abstract ----------------------------------------------------------
    @abstractmethod
    def _get_tpu_fit_func(
        self, dataset: DataFrame, extra_params: Optional[List[Dict[str, Any]]] = None
    ) -> FitFunc:
        raise NotImplementedError


class _FitMultipleIterator:
    """Thread-safe (index, model) iterator over single-pass multi-model fits
    (reference core.py:649-721)."""

    def __init__(self, fit_multiple_models: Callable[[], List["_TpuModel"]], num_models: int):
        self.fit_multiple_models = fit_multiple_models
        self.num_models = num_models
        self.counter = 0
        self.lock = threading.Lock()
        self.models: Optional[List[_TpuModel]] = None

    def __iter__(self) -> "_FitMultipleIterator":
        return self

    def __next__(self) -> Tuple[int, "_TpuModel"]:
        with self.lock:
            index = self.counter
            if index >= self.num_models:
                raise StopIteration()
            self.counter += 1
            if self.models is None:
                self.models = self.fit_multiple_models()
        return index, self.models[index]


class _TpuEstimator(_TpuCaller):
    """Base estimator (reference _CumlEstimator core.py:717-916)."""

    # Whether this estimator's fit function runs correctly over a
    # multi-process (nranks > 1) mesh: it must never host-fetch the
    # row-sharded FitInputs arrays (np.asarray on an array spanning
    # non-addressable devices raises).  Estimators that do host-side label
    # discovery / binning mark themselves False until those steps move on
    # device or behind a gather.
    _supports_multicontroller_fit = True

    def __init__(self) -> None:
        super().__init__()
        self.logger = get_logger(type(self))

    # -- public API --------------------------------------------------------
    def fit(
        self, dataset: Any, params: Optional[Union[Dict[Param, Any], List[Dict[Param, Any]]]] = None
    ) -> Any:
        if isinstance(params, (list, tuple)):
            return [m for _, m in sorted(self.fitMultiple(dataset, list(params)))]
        if isinstance(params, dict) and params:
            return self.copy(params)._fit(dataset)
        return self._fit(dataset)

    def _fit(self, dataset: Any) -> "_TpuModel":
        return self._fit_internal(dataset, None)[0]

    def fitMultiple(
        self, dataset: Any, paramMaps: List[Dict[Param, Any]]
    ) -> Iterator[Tuple[int, "_TpuModel"]]:
        if self._enable_fit_multiple_in_single_pass():
            return _FitMultipleIterator(
                lambda: self._fit_internal(dataset, paramMaps), len(paramMaps)
            )
        return iter(
            [(i, self.copy(pm)._fit(dataset)) for i, pm in enumerate(paramMaps)]
        )

    def _fit_internal(
        self, dataset: Any, paramMaps: Optional[List[Dict[Param, Any]]]
    ) -> List["_TpuModel"]:
        results = self._call_tpu_fit_func(dataset, paramMaps)
        if paramMaps is None:
            results = [results] if isinstance(results, dict) else list(results)
            assert len(results) == 1
        models = []
        for i, attrs in enumerate(results if isinstance(results, list) else [results]):
            pm = paramMaps[i] if paramMaps is not None and i < len(paramMaps) else None
            models.append(self._materialize_model(attrs, pm))
        return models

    def _materialize_model(
        self, attrs: Dict[str, Any], paramMap: Optional[Dict[Param, Any]] = None
    ) -> "_TpuModel":
        """Model-attribute dict -> model, with the ONE materialization
        bookkeeping every fit route shares (_fit_internal's loop and the
        batched sweep's tuning._materialize_sweep_models): telemetry popped
        off the wire dict onto model._fit_telemetry, copied estimator
        values, synced solver params, and the param map's own grid values
        set through _set_params — so a sweep sub-model is indistinguishable
        from its sequential twin by construction, not by hand-synced
        copies."""
        telem = attrs.pop(TELEMETRY_ATTR, None)
        model = self._create_model(attrs)
        if telem is not None:
            from . import profiling

            model._fit_telemetry = profiling.TelemetrySnapshot.from_dict(telem)
        self._copyValues(model)
        model._tpu_params.update(self._tpu_params)
        model._num_workers = self._num_workers
        model._float32_inputs = self._float32_inputs
        if paramMap is not None:
            for p, v in paramMap.items():
                if model.hasParam(p.name):
                    # _set_params keeps the Spark param and the solver
                    # param dict in sync (raw set() would desync them)
                    model._set_params(**{p.name: v})
        return model

    def _enable_fit_multiple_in_single_pass(self) -> bool:
        return False

    def _supportsTransformEvaluate(self, evaluator: Any) -> bool:
        return False

    # -- batched hyperparameter sweep (srml-sweep) -------------------------
    def _supportsBatchedSweep(
        self, df: DataFrame, paramMaps: List[Dict[Param, Any]], evaluator: Any
    ) -> bool:
        """Whether a CrossValidator sweep over `paramMaps` can run as the
        one-dispatch batched engine (docs/tuning_engine.md): every grid
        param must map onto a lane-batchable solver knob and the evaluator
        must ride the single-pass transform-evaluate.  Estimators with
        vmappable solvers (the GLMs) override this; the default keeps the
        classic per-fold loop."""
        return False

    def _fitBatchedSweep(
        self,
        df: DataFrame,
        paramMaps: List[Dict[Param, Any]],
        n_folds: int,
        seed: int,
    ) -> List[List[Dict[str, Any]]]:
        """Fit every (fold, candidate) pair over ONE staged dataset (folds
        as weight masks, candidates as kernel lanes); returns n_folds lists
        of per-candidate model-attribute dicts.  Only called when
        _supportsBatchedSweep returned True."""
        raise NotImplementedError

    def _sweep_sparse_input(self, df: DataFrame) -> bool:
        """True when any partition carries a sparse CSR feature block —
        the batched sweep keeps those on the legacy loop (masked-fold ELL
        statistics are a documented non-goal, docs/tuning_engine.md)."""
        input_col, _ = self._get_input_columns()
        if input_col is None:
            return False
        for part in df.partitions:
            block = _partition_feature_block(part, input_col)
            if block is not None and hasattr(block, "tocsr"):
                return True
        return False

    # -- abstract ----------------------------------------------------------
    @abstractmethod
    def _create_model(self, result: Dict[str, Any]) -> "_TpuModel":
        raise NotImplementedError

    # -- persistence -------------------------------------------------------
    def write(self) -> "_TpuEstimatorWriter":
        return _TpuEstimatorWriter(self)

    def save(self, path: str) -> None:
        self.write().save(path)

    @classmethod
    def read(cls) -> "_TpuEstimatorReader":
        return _TpuEstimatorReader(cls)

    @classmethod
    def load(cls, path: str) -> "_TpuEstimator":
        return cls.read().load(path)


class _TpuEstimatorSupervised(_TpuEstimator):
    """Estimator consuming (features, label[, weight]) (reference
    _CumlEstimatorSupervised core.py:918-952)."""


class _TpuModel(_TpuParams):
    """Base model/transformer (reference _CumlModel core.py:954-1374)."""

    def __init__(self, **model_attributes: Any) -> None:
        super().__init__()
        self._model_attributes = model_attributes
        self._initialize_tpu_params()
        self.logger = get_logger(type(self))

    def _get_model_attributes(self) -> Dict[str, Any]:
        return self._model_attributes

    def fit_telemetry(self):
        """TelemetrySnapshot of the fit that produced this model — phase
        rollups, counter deltas, per-rank merge — on BOTH the local and the
        live-Spark (barrier executor) paths.  None for models built by
        hand, loaded from disk, or combined (telemetry describes one fit
        session, not a persisted artifact)."""
        return getattr(self, "_fit_telemetry", None)

    @classmethod
    def _construct(cls, attrs: Dict[str, Any]) -> "_TpuModel":
        """Rebuild a model from its (decoded) attribute dict.  Override
        when _get_model_attributes carries entries that are not
        constructor arguments (see _construct_model)."""
        return cls(**attrs)

    @property
    def hasSummary(self) -> bool:
        return False

    # -- transform ---------------------------------------------------------
    def transform(self, dataset: Any) -> DataFrame:
        """Column-appending inference (reference _CumlModelWithColumns._transform
        core.py:1277-1361): original columns are preserved, output columns
        named by the *Col params are appended.

        A live pyspark DataFrame runs partition-wise ON THE EXECUTORS via
        mapInPandas with the model riding the closure — the dataset is never
        collected to the driver (reference core.py:1277-1361; UMAP's
        distributed inference, umap.py:1147-1224).  SRML_SPARK_COLLECT=1
        forces the old driver-local collect path."""
        if _use_executor_path(dataset):
            from .spark.adapter import executor_transform

            return executor_transform(self, dataset)
        df = as_dataframe(dataset)
        if getattr(df, "_device_features", None) is not None:
            raise NotImplementedError(
                "DataFrame.from_device frames are fit-input only (their "
                "features column is a placeholder); transform host or "
                "pyspark frames instead"
            )
        input_col, input_cols = self._get_input_columns()
        dtype = self._transform_dtype(self._model_attributes.get("dtype"))
        transform_fn = self._get_tpu_transform_func(df)
        out_parts: List[Optional[pd.DataFrame]] = []
        out_col_names: Optional[List[str]] = None
        for part in df.partitions:
            if len(part) == 0:
                out_parts.append(None)  # filled once output columns are known
                continue
            block = (
                _partition_feature_block(part, input_col)
                if input_col is not None
                else None
            )
            # sparse partitions stay CSR when the model has a sparse path
            # (its transform converts CSR -> ELL)
            feats = materialize_feature_block(
                block,
                part,
                input_col,
                input_cols,
                dtype,
                densify_sparse=not self._supports_sparse_input,
            )
            new_part = part.copy()
            outputs = transform_fn(feats)
            for name, values in outputs.items():
                if isinstance(values, np.ndarray) and values.ndim == 2:
                    new_part[name] = list(values)
                else:
                    new_part[name] = values
            if out_col_names is None:
                out_col_names = list(outputs.keys())
            out_parts.append(new_part)
        # empty partitions get the same output columns (from the first
        # non-empty partition, falling back to the *Col params) so all
        # partitions share one schema
        if out_col_names is None:
            out_col_names = self._out_columns()
        filled = []
        for part, orig in zip(out_parts, df.partitions):
            if part is None:
                part = orig.copy()
                for name in out_col_names:
                    part[name] = []
            filled.append(part)
        return DataFrame(filled)

    def _out_columns(self) -> List[str]:
        cols = []
        for p in ("predictionCol", "probabilityCol", "rawPredictionCol", "outputCol"):
            if self.hasParam(p) and self.isDefined(p):
                cols.append(self.getOrDefault(p))
        return cols

    _OUT_COLUMN_DDL = {
        "predictionCol": "double",
        "probabilityCol": "array<double>",
        "rawPredictionCol": "array<double>",
        "outputCol": "array<double>",
    }

    def _out_schema_fields(self) -> List[Tuple[str, str]]:
        """(column name, Spark DDL type) per appended output column — the
        executor-transform mapInPandas schema (the reference's typed
        prediction columns, core.py:1294-1361).  Models whose outputs
        deviate from the defaults override _OUT_COLUMN_DDL."""
        return [
            (self.getOrDefault(p), self._OUT_COLUMN_DDL[p])
            for p in ("predictionCol", "probabilityCol", "rawPredictionCol", "outputCol")
            if self.hasParam(p) and self.isDefined(p)
        ]

    # -- abstract ----------------------------------------------------------
    @abstractmethod
    def _get_tpu_transform_func(self, dataset: DataFrame) -> TransformFunc:
        raise NotImplementedError

    # -- online serving -----------------------------------------------------
    def _serving_entry(self, mesh: Any = None):
        """ServingEntry for the online inference engine (serving/engine.py):
        a padded-batch dispatch through the AOT executable cache plus a
        bucket warm hook.  Served model classes override this; the base
        raises so serving.ModelServer gives an actionable error for models
        with no online path."""
        raise NotImplementedError(
            f"{type(self).__name__} has no serving entry; servable models "
            "are KMeans/PCA/LinearRegression/LogisticRegression/"
            "RandomForest*/NearestNeighbors/ApproximateNearestNeighbors"
        )

    # -- multi-model -------------------------------------------------------
    @classmethod
    def _combine(cls, models: List["_TpuModel"]) -> "_TpuModel":
        raise NotImplementedError

    def _transformEvaluate(self, dataset: Any, evaluator: Any) -> List[float]:
        raise NotImplementedError

    # -- persistence -------------------------------------------------------
    def write(self) -> "_TpuModelWriter":
        return _TpuModelWriter(self)

    def save(self, path: str) -> None:
        self.write().save(path)

    @classmethod
    def read(cls) -> "_TpuModelReader":
        return _TpuModelReader(cls)

    @classmethod
    def load(cls, path: str) -> "_TpuModel":
        return cls.read().load(path)


class _TpuModelWithPredictionCol(_TpuModel):
    """Model appending a predictionCol (reference core.py:1377-1387)."""

    def setPredictionCol(self, value: str) -> "_TpuModelWithPredictionCol":
        self._set_params(predictionCol=value)
        return self


# ---------------------------------------------------------------------------
# Persistence (reference core.py:139-226; model attrs as npz instead of the
# reference's JSON-in-text-file to keep large arrays binary and chunk-free)
# ---------------------------------------------------------------------------

_METADATA_FILE = "metadata.json"
_ARRAYS_FILE = "model_arrays.npz"
_ATTRS_FILE = "model_attrs.json"


def _params_metadata(instance: _TpuParams) -> Dict[str, Any]:
    return {
        "class": f"{type(instance).__module__}.{type(instance).__name__}",
        "uid": instance.uid,
        "paramMap": {p.name: _jsonable(v) for p, v in instance._paramMap.items()},
        "defaultParamMap": {p.name: _jsonable(v) for p, v in instance._defaultParamMap.items()},
        "tpu_params": {k: _jsonable(v) for k, v in instance._tpu_params.items()},
        "num_workers": instance._num_workers,
        "float32_inputs": instance._float32_inputs,
        "sparkRapidsMlTpuVersion": _version(),
    }


def _version() -> str:
    from .version import __version__

    return __version__


def _jsonable(v: Any) -> Any:
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v


def _apply_params_metadata(meta: Dict[str, Any], instance: _TpuParams) -> None:
    for name, value in meta.get("defaultParamMap", {}).items():
        if instance.hasParam(name):
            instance._defaultParamMap[instance.getParam(name)] = value
    for name, value in meta.get("paramMap", {}).items():
        if instance.hasParam(name):
            instance.set(instance.getParam(name), value)
    instance._tpu_params = dict(meta.get("tpu_params", {}))
    instance._num_workers = meta.get("num_workers")
    instance._float32_inputs = meta.get("float32_inputs", True)
    instance.uid = meta.get("uid", instance.uid)


def _resolve_class(qualname: str) -> type:
    import importlib

    module, _, name = qualname.rpartition(".")
    return getattr(importlib.import_module(module), name)


class _TpuEstimatorWriter:
    def __init__(self, instance: _TpuEstimator):
        self.instance = instance

    def overwrite(self) -> "_TpuEstimatorWriter":
        return self

    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, _METADATA_FILE), "w") as f:
            json.dump(_params_metadata(self.instance), f, indent=2)


class _TpuEstimatorReader:
    def __init__(self, cls: type):
        self.cls = cls

    def load(self, path: str) -> _TpuEstimator:
        with open(os.path.join(path, _METADATA_FILE)) as f:
            meta = json.load(f)
        cls = _resolve_class(meta["class"])
        est = cls()
        _apply_params_metadata(meta, est)
        return est


class _TpuModelWriter:
    def __init__(self, instance: _TpuModel):
        self.instance = instance

    def overwrite(self) -> "_TpuModelWriter":
        return self

    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, _METADATA_FILE), "w") as f:
            json.dump(_params_metadata(self.instance), f, indent=2)
        arrays, attrs = {}, {}
        for k, v in self.instance._get_model_attributes().items():
            if isinstance(v, np.ndarray):
                arrays[k] = v
            elif isinstance(v, jax.Array):
                arrays[k] = np.asarray(v)
            else:
                attrs[k] = _jsonable(v)
        np.savez(os.path.join(path, _ARRAYS_FILE), **arrays)
        with open(os.path.join(path, _ATTRS_FILE), "w") as f:
            json.dump(attrs, f)


def _construct_model(cls: type, attrs: Dict[str, Any]) -> "_TpuModel":
    """Instantiate a model from decoded attributes via the class's
    _construct hook — model classes whose attribute dict carries
    NON-constructor entries (e.g. a combined multi-model's sub-model
    split) override it to pop and reattach them, keeping this layer
    model-agnostic."""
    return cls._construct(dict(attrs))


class _TpuModelReader:
    def __init__(self, cls: type):
        self.cls = cls

    def load(self, path: str) -> _TpuModel:
        with open(os.path.join(path, _METADATA_FILE)) as f:
            meta = json.load(f)
        cls = _resolve_class(meta["class"])
        with open(os.path.join(path, _ATTRS_FILE)) as f:
            attrs = json.load(f)
        npz = np.load(os.path.join(path, _ARRAYS_FILE), allow_pickle=False)
        for k in npz.files:
            attrs[k] = npz[k]
        model = _construct_model(cls, attrs)
        _apply_params_metadata(meta, model)
        return model


def load(path: str) -> Union[_TpuEstimator, _TpuModel]:
    """Load any saved estimator/model, resolving the class from metadata."""
    with open(os.path.join(path, _METADATA_FILE)) as f:
        meta = json.load(f)
    cls = _resolve_class(meta["class"])
    return cls.load(path)
