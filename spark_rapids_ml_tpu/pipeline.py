#
# Pipeline / PipelineModel: chained stages over the framework DataFrame.
#
# The reference has no pipeline code of its own — its estimators plug into
# pyspark.ml.Pipeline (SURVEY.md L1: estimators "sit above user code,
# pyspark.ml.Pipeline, CrossValidator"). A standalone framework needs the
# equivalent composition surface, so this module provides a
# pyspark.ml.Pipeline-compatible API: fit() walks the stages, fitting
# estimators (then transforming with the fitted model to feed the next
# stage) and passing transformers through; PipelineModel.transform()
# applies every fitted stage in order.  Persistence mirrors Spark ML's
# layout: a pipeline directory with per-stage subdirectories.
#

from __future__ import annotations

import json
import os
from typing import Any, List, Optional

from .core import _TpuEstimator, load as _load_any
from .dataframe import DataFrame, as_dataframe

_PIPELINE_META = "metadata.json"


def _is_estimator(stage: Any) -> bool:
    # Spark's Pipeline keys on isinstance(Estimator)/isinstance(Transformer),
    # not on duck typing — our own types classify exactly.  A third-party
    # stage exposing BOTH fit and transform (sklearn style) is genuinely
    # ambiguous: treating it as a transformer silently skips training, while
    # treating it as an estimator silently refits an already-fitted object.
    # Either silent choice corrupts someone's pipeline, so ambiguous stages
    # fail loudly unless the user declares the role via `srml_stage_role`.
    if isinstance(stage, _TpuEstimator):
        return True
    has_fit, has_transform = hasattr(stage, "fit"), hasattr(stage, "transform")
    if has_fit and has_transform:
        role = getattr(stage, "srml_stage_role", None)
        if role in ("estimator", "transformer"):
            return role == "estimator"
        if role is not None:
            raise TypeError(
                f"Pipeline stage {type(stage).__name__!r} has unrecognized "
                f"srml_stage_role {role!r}; expected 'estimator' or "
                "'transformer'."
            )
        raise TypeError(
            f"Ambiguous pipeline stage {type(stage).__name__!r}: it defines "
            "both fit and transform but is not a framework estimator. Set "
            "stage.srml_stage_role = 'estimator' (fit it here) or "
            "'transformer' (apply as-is) to disambiguate."
        )
    return has_fit


class Pipeline:
    """pyspark.ml.Pipeline-compatible chain of estimators/transformers."""

    def __init__(self, stages: Optional[List[Any]] = None) -> None:
        self._stages: List[Any] = list(stages or [])

    def setStages(self, stages: List[Any]) -> "Pipeline":
        self._stages = list(stages)
        return self

    def getStages(self) -> List[Any]:
        return list(self._stages)

    def fit(self, dataset: Any) -> "PipelineModel":
        df = as_dataframe(dataset)
        fitted: List[Any] = []
        # find the last estimator: stages after it never need their
        # transform output during fit (Spark ML semantics)
        last_est = -1
        for i, stage in enumerate(self._stages):
            if _is_estimator(stage):
                last_est = i
        for i, stage in enumerate(self._stages):
            if _is_estimator(stage):
                model = stage.fit(df)
                fitted.append(model)
                if i < last_est:
                    df = as_dataframe(model.transform(df))
            else:
                fitted.append(stage)
                if i < last_est:
                    df = as_dataframe(stage.transform(df))
        return PipelineModel(fitted)

    def copy(self, extra: Optional[dict] = None) -> "Pipeline":
        return Pipeline([
            s.copy(extra) if hasattr(s, "copy") else s for s in self._stages
        ])

    def save(self, path: str) -> None:
        _save_stages(path, "Pipeline", self._stages)

    @classmethod
    def load(cls, path: str) -> "Pipeline":
        return cls(_load_stages(path))


class PipelineModel:
    """Fitted pipeline: applies every stage's transform in order.

    Deliberately NOT a _TpuModel subclass — it composes fitted models
    rather than being one (no params, no fit attrs of its own)."""

    def __init__(self, stages: List[Any]) -> None:
        self.stages: List[Any] = list(stages)

    def transform(self, dataset: Any) -> DataFrame:
        df = as_dataframe(dataset)
        for stage in self.stages:
            df = as_dataframe(stage.transform(df))
        return df

    def copy(self, extra: Optional[dict] = None) -> "PipelineModel":
        return PipelineModel([
            s.copy(extra) if hasattr(s, "copy") else s for s in self.stages
        ])

    def save(self, path: str) -> None:
        _save_stages(path, "PipelineModel", self.stages)

    @classmethod
    def load(cls, path: str) -> "PipelineModel":
        return cls(_load_stages(path))


def _save_stages(path: str, kind: str, stages: List[Any]) -> None:
    os.makedirs(path, exist_ok=True)
    meta = {
        "class": f"{Pipeline.__module__}.{kind}",
        "n_stages": len(stages),
    }
    with open(os.path.join(path, _PIPELINE_META), "w") as f:
        json.dump(meta, f, indent=2)
    for i, stage in enumerate(stages):
        stage.save(os.path.join(path, f"stage_{i:03d}"))


def _load_stages(path: str) -> List[Any]:
    with open(os.path.join(path, _PIPELINE_META)) as f:
        meta = json.load(f)
    return [
        _load_any(os.path.join(path, f"stage_{i:03d}"))
        for i in range(meta["n_stages"])
    ]
