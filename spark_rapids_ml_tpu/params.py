#
# Spark-ML-compatible parameter system + TPU-solver param translation layer.
#
# This is a from-scratch implementation of the public behavior of
# pyspark.ml.param.{Param,Params,TypeConverters} so the framework runs with or
# without pyspark installed, plus the two-way Spark<->solver param mapping whose
# *behavior* mirrors the reference's translation layer
# (/root/reference/python/src/spark_rapids_ml/params.py:64-477: _CumlClass
# _param_mapping / _param_value_mapping / _get_cuml_params_default, and
# _CumlParams with its cuml_params dict, num_workers inference and
# float32_inputs flag).  The implementation here is new and TPU-native: the
# solver params feed jax.jit'd solvers, and num_workers defaults to the number
# of addressable TPU devices in the active mesh.
#

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, TypeVar, Union

P = TypeVar("P", bound="Params")

_uid_lock = threading.Lock()
_uid_counters: Dict[str, int] = {}


def _gen_uid(cls_name: str) -> str:
    with _uid_lock:
        n = _uid_counters.get(cls_name, 0)
        _uid_counters[cls_name] = n + 1
    return f"{cls_name}_{n:04x}"


class Param:
    """A named parameter with a doc string and optional type converter.

    Params are class-level singletons on each Params subclass; identity-based
    dict keys (param maps) therefore work across instances of the same class.
    """

    __slots__ = ("parent", "name", "doc", "typeConverter")

    def __init__(
        self,
        parent: Any,
        name: str,
        doc: str,
        typeConverter: Optional[Callable[[Any], Any]] = None,
    ):
        self.parent = parent
        self.name = name
        self.doc = doc
        self.typeConverter = typeConverter or (lambda x: x)

    def __repr__(self) -> str:
        return f"{self.parent}__{self.name}"

    def __hash__(self) -> int:
        return hash(self.name)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Param) and self.name == other.name


class TypeConverters:
    """Type conversion helpers mirroring pyspark.ml.param.TypeConverters."""

    @staticmethod
    def toInt(value: Any) -> int:
        import numbers

        if isinstance(value, bool):
            raise TypeError(f"Could not convert {value} to int")
        if isinstance(value, numbers.Number) and float(value) == int(value):
            return int(value)
        raise TypeError(f"Could not convert {value} to int")

    @staticmethod
    def toFloat(value: Any) -> float:
        import numbers

        if isinstance(value, bool):
            raise TypeError(f"Could not convert {value} to float")
        if isinstance(value, numbers.Number):
            return float(value)
        raise TypeError(f"Could not convert {value} to float")

    @staticmethod
    def toString(value: Any) -> str:
        if isinstance(value, str):
            return value
        raise TypeError(f"Could not convert {value} to string")

    @staticmethod
    def toBoolean(value: Any) -> bool:
        if isinstance(value, bool):
            return value
        raise TypeError(f"Could not convert {value} to boolean")

    @staticmethod
    def toList(value: Any) -> list:
        if isinstance(value, (list, tuple)):
            return list(value)
        import numpy as np

        if isinstance(value, np.ndarray):
            return value.tolist()
        raise TypeError(f"Could not convert {value} to list")

    @staticmethod
    def toListFloat(value: Any) -> List[float]:
        return [TypeConverters.toFloat(v) for v in TypeConverters.toList(value)]

    @staticmethod
    def toListInt(value: Any) -> List[int]:
        return [TypeConverters.toInt(v) for v in TypeConverters.toList(value)]

    @staticmethod
    def toListString(value: Any) -> List[str]:
        return [TypeConverters.toString(v) for v in TypeConverters.toList(value)]

    @staticmethod
    def identity(value: Any) -> Any:
        return value


class Params:
    """Base class holding params, user-set values, and defaults.

    Public surface matches pyspark.ml.param.Params: params, hasParam, getParam,
    isSet, isDefined, getOrDefault, set, clear, extractParamMap, copy,
    explainParam(s), hasDefault.
    """

    def __init__(self) -> None:
        self.uid = _gen_uid(type(self).__name__)
        self._paramMap: Dict[Param, Any] = {}
        self._defaultParamMap: Dict[Param, Any] = {}

    # -- param discovery ---------------------------------------------------
    @property
    def params(self) -> List[Param]:
        seen = {}
        for klass in reversed(type(self).__mro__):
            for name, attr in vars(klass).items():
                if isinstance(attr, Param):
                    seen[attr.name] = attr
        return sorted(seen.values(), key=lambda p: p.name)

    def hasParam(self, paramName: str) -> bool:
        return any(p.name == paramName for p in self.params)

    def getParam(self, paramName: str) -> Param:
        for p in self.params:
            if p.name == paramName:
                return p
        raise AttributeError(f"{type(self).__name__} has no param '{paramName}'")

    def _resolveParam(self, param: Union[str, Param]) -> Param:
        return self.getParam(param) if isinstance(param, str) else self.getParam(param.name)

    # -- get/set -----------------------------------------------------------
    def isSet(self, param: Union[str, Param]) -> bool:
        return self._resolveParam(param) in self._paramMap

    def hasDefault(self, param: Union[str, Param]) -> bool:
        return self._resolveParam(param) in self._defaultParamMap

    def isDefined(self, param: Union[str, Param]) -> bool:
        return self.isSet(param) or self.hasDefault(param)

    def getOrDefault(self, param: Union[str, Param]) -> Any:
        param = self._resolveParam(param)
        if param in self._paramMap:
            return self._paramMap[param]
        if param in self._defaultParamMap:
            return self._defaultParamMap[param]
        raise KeyError(f"Param '{param.name}' is not set and has no default")

    def set(self, param: Union[str, Param], value: Any) -> "Params":
        param = self._resolveParam(param)
        self._paramMap[param] = param.typeConverter(value)
        return self

    def clear(self, param: Union[str, Param]) -> None:
        self._paramMap.pop(self._resolveParam(param), None)

    def _set(self, **kwargs: Any) -> "Params":
        for name, value in kwargs.items():
            if value is not None or name in ("weightCol",):
                self.set(self.getParam(name), value)
        return self

    def _setDefault(self, **kwargs: Any) -> "Params":
        for name, value in kwargs.items():
            self._defaultParamMap[self.getParam(name)] = value
        return self

    def extractParamMap(self, extra: Optional[Dict[Param, Any]] = None) -> Dict[Param, Any]:
        paramMap = dict(self._defaultParamMap)
        paramMap.update(self._paramMap)
        if extra:
            paramMap.update(extra)
        return paramMap

    def explainParam(self, param: Union[str, Param]) -> str:
        param = self._resolveParam(param)
        values = []
        if self.hasDefault(param):
            values.append(f"default: {self._defaultParamMap[param]}")
        if self.isSet(param):
            values.append(f"current: {self._paramMap[param]}")
        return f"{param.name}: {param.doc} ({', '.join(values) if values else 'undefined'})"

    def explainParams(self) -> str:
        return "\n".join(self.explainParam(p) for p in self.params)

    def copy(self: P, extra: Optional[Dict[Param, Any]] = None) -> P:
        import copy as _copy

        that = _copy.copy(self)
        that._paramMap = dict(self._paramMap)
        that._defaultParamMap = dict(self._defaultParamMap)
        if extra:
            for k, v in extra.items():
                that.set(k, v)
        return that

    def _copyValues(self, to: "Params", extra: Optional[Dict[Param, Any]] = None) -> "Params":
        paramMap = dict(self._paramMap)
        if extra:
            paramMap.update(extra)
        for p, v in self._defaultParamMap.items():
            if to.hasParam(p.name):
                to._defaultParamMap[to.getParam(p.name)] = v
        for p, v in paramMap.items():
            if to.hasParam(p.name):
                to._paramMap[to.getParam(p.name)] = v
        return to


def _dummy() -> Any:
    class _Dummy:
        uid = "undefined"

    return _Dummy()


# ---------------------------------------------------------------------------
# Shared param mixins (subset of pyspark.ml.param.shared we need)
# ---------------------------------------------------------------------------


class HasFeaturesCol(Params):
    featuresCol = Param(
        _dummy(), "featuresCol", "features column name", TypeConverters.toString
    )

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(featuresCol="features")

    def getFeaturesCol(self) -> str:
        return self.getOrDefault(self.featuresCol)


class HasFeaturesCols(Params):
    """Param for a list of scalar feature column names (multi-column input).

    Mirrors the reference's HasFeaturesCols
    (/root/reference/python/src/spark_rapids_ml/params.py:42-61).
    """

    featuresCols = Param(
        _dummy(),
        "featuresCols",
        "features column names for multi-column input",
        TypeConverters.toListString,
    )

    def getFeaturesCols(self) -> List[str]:
        return self.getOrDefault(self.featuresCols)


class HasLabelCol(Params):
    labelCol = Param(_dummy(), "labelCol", "label column name", TypeConverters.toString)

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(labelCol="label")

    def getLabelCol(self) -> str:
        return self.getOrDefault(self.labelCol)


class HasPredictionCol(Params):
    predictionCol = Param(
        _dummy(), "predictionCol", "prediction column name", TypeConverters.toString
    )

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(predictionCol="prediction")

    def getPredictionCol(self) -> str:
        return self.getOrDefault(self.predictionCol)


class HasProbabilityCol(Params):
    probabilityCol = Param(
        _dummy(),
        "probabilityCol",
        "column name for predicted class conditional probabilities",
        TypeConverters.toString,
    )

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(probabilityCol="probability")

    def getProbabilityCol(self) -> str:
        return self.getOrDefault(self.probabilityCol)


class HasRawPredictionCol(Params):
    rawPredictionCol = Param(
        _dummy(),
        "rawPredictionCol",
        "raw prediction (confidence) column name",
        TypeConverters.toString,
    )

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(rawPredictionCol="rawPrediction")

    def getRawPredictionCol(self) -> str:
        return self.getOrDefault(self.rawPredictionCol)


class HasInputCol(Params):
    inputCol = Param(_dummy(), "inputCol", "input column name", TypeConverters.toString)

    def getInputCol(self) -> str:
        return self.getOrDefault(self.inputCol)


class HasInputCols(Params):
    inputCols = Param(
        _dummy(), "inputCols", "input column names", TypeConverters.toListString
    )

    def getInputCols(self) -> List[str]:
        return self.getOrDefault(self.inputCols)


class HasOutputCol(Params):
    outputCol = Param(
        _dummy(), "outputCol", "output column name", TypeConverters.toString
    )

    def getOutputCol(self) -> str:
        return self.getOrDefault(self.outputCol)


class HasWeightCol(Params):
    weightCol = Param(
        _dummy(), "weightCol", "weight column name", TypeConverters.toString
    )

    def getWeightCol(self) -> str:
        return self.getOrDefault(self.weightCol)


class HasMaxIter(Params):
    maxIter = Param(
        _dummy(), "maxIter", "max number of iterations (>= 0)", TypeConverters.toInt
    )

    def getMaxIter(self) -> int:
        return self.getOrDefault(self.maxIter)


class HasTol(Params):
    tol = Param(
        _dummy(),
        "tol",
        "the convergence tolerance for iterative algorithms (>= 0)",
        TypeConverters.toFloat,
    )

    def getTol(self) -> float:
        return self.getOrDefault(self.tol)


class HasRegParam(Params):
    regParam = Param(
        _dummy(), "regParam", "regularization parameter (>= 0)", TypeConverters.toFloat
    )

    def getRegParam(self) -> float:
        return self.getOrDefault(self.regParam)


class HasElasticNetParam(Params):
    elasticNetParam = Param(
        _dummy(),
        "elasticNetParam",
        "the ElasticNet mixing parameter, in range [0, 1]. alpha = 0 -> L2, alpha = 1 -> L1",
        TypeConverters.toFloat,
    )

    def getElasticNetParam(self) -> float:
        return self.getOrDefault(self.elasticNetParam)


class HasFitIntercept(Params):
    fitIntercept = Param(
        _dummy(),
        "fitIntercept",
        "whether to fit an intercept term",
        TypeConverters.toBoolean,
    )

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(fitIntercept=True)

    def getFitIntercept(self) -> bool:
        return self.getOrDefault(self.fitIntercept)


class HasStandardization(Params):
    standardization = Param(
        _dummy(),
        "standardization",
        "whether to standardize the training features before fitting the model",
        TypeConverters.toBoolean,
    )

    def getStandardization(self) -> bool:
        return self.getOrDefault(self.standardization)


class HasSeed(Params):
    seed = Param(_dummy(), "seed", "random seed", TypeConverters.toInt)

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        import zlib

        self._setDefault(seed=zlib.crc32(type(self).__name__.encode()) % (1 << 31))

    def getSeed(self) -> int:
        return self.getOrDefault(self.seed)


class HasVerbose(Params):
    verbose = Param(
        _dummy(),
        "verbose",
        "solver logging verbosity (bool or 0-6 int level)",
        TypeConverters.identity,
    )

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(verbose=False)


# ---------------------------------------------------------------------------
# Spark <-> TPU-solver param translation
# ---------------------------------------------------------------------------


class _TpuClass:
    """Declares how Spark ML params translate to TPU-solver params.

    Semantics mirror the reference's _CumlClass
    (/root/reference/python/src/spark_rapids_ml/params.py:64-146):
      - ``_param_mapping`` maps each Spark param name to a solver param name;
        an empty-string value means "unsupported, silently ignore"; ``None``
        means "unsupported, raise if the user sets a non-default value".
      - ``_param_value_mapping`` maps a solver param name to a function that
        remaps/validates values, returning None for unsupported values.
      - ``_get_tpu_params_default`` returns default solver params.
    """

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {}

    @classmethod
    def _param_value_mapping(cls) -> Dict[str, Callable[[Any], Union[None, Any]]]:
        return {}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {}

    @classmethod
    def _param_excludes(cls) -> List[str]:
        return []


class _TpuParams(_TpuClass, Params):
    """Params mixin holding the ``tpu_params`` dict fed to the jax solvers.

    Mirrors the behavior of the reference's _CumlParams
    (/root/reference/python/src/spark_rapids_ml/params.py:148-477): keeps the
    Spark Param space and the solver param dict in sync in both directions,
    reserves ``num_workers`` / ``float32_inputs`` / ``verbose`` kwargs, and
    infers num_workers from the available device mesh when unset.
    """

    _tpu_params: Dict[str, Any]
    _num_workers: Optional[int] = None
    _float32_inputs: bool = True
    # estimators/models with a real sparse (CSR -> ELL) compute path set this
    # True (the GLMs, mirroring cuML's sparse qn fit); everything else
    # densifies sparse input partition-by-partition with a warning
    _supports_sparse_input: bool = False

    @property
    def tpu_params(self) -> Dict[str, Any]:
        return self._tpu_params

    # reference alias, eases porting user code
    @property
    def cuml_params(self) -> Dict[str, Any]:
        return self._tpu_params

    @property
    def num_workers(self) -> int:
        return self._infer_num_workers() if self._num_workers is None else self._num_workers

    @num_workers.setter
    def num_workers(self, value: int) -> None:
        self._num_workers = value

    def _infer_num_workers(self) -> int:
        """Default parallelism: one logical worker per addressable device in
        the active mesh (reference infers from cluster GPU confs,
        params.py:353-385; on TPU the mesh is the cluster)."""
        from .parallel.mesh import default_num_workers

        return default_num_workers()

    def _initialize_tpu_params(self) -> None:
        self._tpu_params = self._get_tpu_params_default()
        # push current Spark-side defaults into solver params
        for spark_name, solver_name in self._param_mapping().items():
            if not solver_name:
                continue
            if self.hasParam(spark_name) and self.isDefined(spark_name):
                self._set_tpu_value(solver_name, self.getOrDefault(spark_name))

    def _set_params(self: P, **kwargs: Any) -> P:
        """Set params by Spark name or solver name; mirrors _CumlParams._set_params
        (/root/reference/python/src/spark_rapids_ml/params.py:237-316)."""
        mapping = self._param_mapping()
        for k, v in kwargs.items():
            if k == "num_workers":
                self._num_workers = v
            elif k == "float32_inputs":
                self._float32_inputs = v
            elif self.hasParam(k):
                self.set(self.getParam(k), v)
                if k in mapping:
                    solver_name = mapping[k]
                    if solver_name:
                        self._set_tpu_value(solver_name, self.getOrDefault(k))
                    elif solver_name is None:
                        raise ValueError(
                            f"Param '{k}' is not supported by the TPU implementation of "
                            f"{type(self).__name__}."
                        )
            elif k in self._tpu_params:
                self._set_tpu_value(k, v)
                # reflect back to the Spark param if one maps to it
                for spark_name, solver_name in mapping.items():
                    if solver_name == k and self.hasParam(spark_name):
                        self.set(self.getParam(spark_name), v)
            else:
                raise ValueError(f"Unsupported param: {k}")
        return self

    def copy(self: P, extra: Optional[Dict[Any, Any]] = None) -> P:
        """Copy keeping spark params and solver params in sync (the base copy
        would alias the mutable _tpu_params dict and skip the translation)."""
        that = super().copy(None)
        if hasattr(self, "_tpu_params"):
            that._tpu_params = dict(self._tpu_params)
        if extra:
            for k, v in extra.items():
                name = k.name if isinstance(k, Param) else k
                that._set_params(**{name: v})
        return that

    def _set_tpu_value(self, name: str, value: Any) -> None:
        value_mapping = self._param_value_mapping()
        if name in value_mapping:
            mapped = value_mapping[name](value)
            if mapped is None:
                raise ValueError(
                    f"Value '{value}' for param '{name}' is not supported by the TPU "
                    f"implementation of {type(self).__name__}."
                )
            value = mapped
        self._tpu_params[name] = value

    def _set_spark_and_tpu(self, spark_name: str, value: Any) -> None:
        self.set(self.getParam(spark_name), value)
        solver = self._param_mapping().get(spark_name)
        if solver:
            self._set_tpu_value(solver, self.getOrDefault(spark_name))

    def _transform_dtype(self, model_dtype: Optional[str] = None):
        """Single source of truth for the inference dtype: float32 when
        float32_inputs (the default), else the dtype recorded at fit time."""
        import numpy as np

        if self._float32_inputs:
            return np.dtype(np.float32)
        return np.dtype(model_dtype or np.float64)

    # ------------------------------------------------------------------
    def _get_input_columns(self) -> tuple:
        """Returns (featuresCol-or-None, featuresCols-or-None); mirrors
        _CumlParams._get_input_columns (reference params.py:318-351)."""
        input_col, input_cols = None, None
        if self.hasParam("featuresCols") and self.isDefined("featuresCols"):
            input_cols = self.getOrDefault("featuresCols")
        elif self.hasParam("featuresCol") and self.isDefined("featuresCol"):
            input_col = self.getOrDefault("featuresCol")
        elif self.hasParam("inputCols") and self.isDefined("inputCols"):
            input_cols = self.getOrDefault("inputCols")
        elif self.hasParam("inputCol") and self.isDefined("inputCol"):
            input_col = self.getOrDefault("inputCol")
        else:
            raise ValueError("Please set inputCol(s) or featuresCol(s)")
        return input_col, input_cols

    def setFeaturesCol(self: P, value: Union[str, List[str]]) -> P:
        if isinstance(value, str):
            self._set_params(featuresCol=value)
        else:
            self._set_params(featuresCols=value)
        return self

    def setFeaturesCols(self: P, value: List[str]) -> P:
        return self._set_params(featuresCols=value)

    def setLabelCol(self: P, value: str) -> P:
        return self._set_params(labelCol=value)

    def setPredictionCol(self: P, value: str) -> P:
        return self._set_params(predictionCol=value)
