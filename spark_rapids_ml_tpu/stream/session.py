#
# StreamingSession: train-while-serve orchestration (srml-stream).
#
# Consumes a chunk iterator through a streaming engine, tracks
# rows-ingested and staleness (rows/chunks/seconds since the serving plane
# last saw a snapshot), and on refresh() materializes a model snapshot and
# pushes it through the PR 11 zero-downtime swap — ModelRegistry.swap
# and/or rolling Router.swap — so replicas keep taking traffic across the
# cut-over (warm-before-cutover from the retained AOT cache: a same-shape
# refresh performs zero new compilations; gated in tests/test_streaming.py
# under live router load with zero client-visible errors).
#

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional

from .. import profiling, sanitize
from .engines import StreamingEngine


class StreamingSession:
    """One continuously-learning model: engine + serving-refresh wiring.

    `registry`/`router` are optional serving planes; refresh() registers
    the first snapshot under `name` (ModelRegistry.register /
    Router.serve) and hot-swaps every later one (swap).  With neither
    plane, refresh() still snapshots and resets the staleness clock —
    callers can push the returned model wherever they serve."""

    def __init__(
        self,
        engine: StreamingEngine,
        name: Optional[str] = None,
        registry: Any = None,
        router: Any = None,
        **serve_kwargs: Any,
    ):
        if (registry is not None or router is not None) and not name:
            raise ValueError("a serving plane needs a model name; pass name=")
        self._engine = engine
        self._name = name
        self._registry = registry
        self._router = router
        self._serve_kwargs = dict(serve_kwargs)
        self._refreshes = 0
        self._rows_at_refresh = 0
        self._chunks_at_refresh = 0
        self._last_refresh_t: Optional[float] = None
        self._model: Any = None
        # refresh-under-load (graftlint R12): a staleness watcher calling
        # refresh() concurrently with the ingest loop's refresh_every_rows
        # trigger must not interleave two swaps — the staleness clock would
        # be reset against a model that never reached the serving plane.
        # One lock serializes snapshot+swap+bookkeeping as a unit.
        self._refresh_lock = sanitize.lockdep_lock("stream.session.refresh")

    # -- ingest ------------------------------------------------------------
    @property
    def engine(self) -> StreamingEngine:
        return self._engine

    def partial_fit(self, chunk: Any, y: Any = None, weight: Any = None):
        """Ingest one chunk (spans stream.ingest around the engine's
        stream.update; staleness attrs make 'how stale is serving' readable
        straight off a trace)."""
        with profiling.span(
            "stream.ingest",
            engine=self._engine.kind,
            stale_rows=self.staleness_rows,
        ):
            self._engine.partial_fit(chunk, y=y, weight=weight)
        return self

    def ingest(self, chunks: Iterable[Any], refresh_every_rows: int = 0):
        """Drain a chunk iterator; with refresh_every_rows > 0, refresh()
        fires whenever that many rows have accumulated since the last
        snapshot (the simple staleness policy; callers needing time-based
        refresh drive refresh() themselves)."""
        for chunk in chunks:
            self.partial_fit(chunk)
            if (
                refresh_every_rows > 0
                and self.staleness_rows >= refresh_every_rows
            ):
                self.refresh()
        return self

    # -- staleness ---------------------------------------------------------
    @property
    def rows_ingested(self) -> int:
        return self._engine.rows_ingested

    @property
    def staleness_rows(self) -> int:
        """Rows ingested since the serving plane last saw a snapshot."""
        return self._engine.rows_ingested - self._rows_at_refresh

    @property
    def staleness_chunks(self) -> int:
        return self._engine.chunks_ingested - self._chunks_at_refresh

    @property
    def staleness_seconds(self) -> Optional[float]:
        if self._last_refresh_t is None:
            return None
        return profiling.now() - self._last_refresh_t

    def stats(self) -> Dict[str, Any]:
        return {
            "name": self._name,
            "engine": self._engine.kind,
            "rows_ingested": self._engine.rows_ingested,
            "chunks_ingested": self._engine.chunks_ingested,
            "refreshes": self._refreshes,
            "staleness_rows": self.staleness_rows,
            "staleness_chunks": self.staleness_chunks,
            "staleness_seconds": self.staleness_seconds,
        }

    # -- refresh -----------------------------------------------------------
    def snapshot(self) -> Any:
        """Materialize a fitted model from the current state WITHOUT
        touching the serving planes or the staleness clock."""
        return self._engine.finalize()

    def refresh(self) -> Any:
        """Snapshot the current state and push it through the serving
        plane(s): first refresh registers (ModelRegistry.register /
        Router.serve), every later one rides the zero-downtime swap —
        the old generation drains while the new one, warmed from the
        retained AOT cache, takes the traffic.  Returns the snapshot."""
        with self._refresh_lock:
            with profiling.span(
                "stream.refresh",
                engine=self._engine.kind,
                rows=self._engine.rows_ingested,
            ):
                model = self.snapshot()
                if self._registry is not None:
                    if self._name in self._registry:
                        self._registry.swap(self._name, model)
                    else:
                        self._registry.register(
                            self._name, model, **self._serve_kwargs
                        )
                if self._router is not None:
                    if self._name in self._router:
                        self._router.swap(self._name, model)
                    else:
                        self._router.serve(
                            self._name, model, **self._serve_kwargs
                        )
            self._model = model
            self._refreshes += 1
            self._rows_at_refresh = self._engine.rows_ingested
            self._chunks_at_refresh = self._engine.chunks_ingested
            self._last_refresh_t = profiling.now()
        profiling.incr_counter("stream.refreshes")
        return model
