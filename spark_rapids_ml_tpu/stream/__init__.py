# srml-stream: streaming incremental fit + train-while-serve.
#
# partial_fit/merge/finalize engines over the batch estimators
# (engines.py), the mergeable cross-rank state algebra (state.py), and the
# StreamingSession orchestrator wiring snapshots into the zero-downtime
# serving swap (session.py).  Live IVF index mutation lives next to the
# index it mutates: ann/mutable.py.  docs/streaming.md is the contract.

from .engines import (
    StreamingEngine,
    StreamingKMeans,
    StreamingLinearRegression,
    StreamingLogisticRegression,
    StreamingPCA,
    streaming_fit,
)
from .session import StreamingSession
from .state import StreamState, allgather_merge, merge_all

__all__ = [
    "StreamingEngine",
    "StreamingKMeans",
    "StreamingLinearRegression",
    "StreamingLogisticRegression",
    "StreamingPCA",
    "StreamingSession",
    "StreamState",
    "allgather_merge",
    "merge_all",
    "streaming_fit",
]
