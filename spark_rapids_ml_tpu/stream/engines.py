#
# Streaming incremental-fit engines (srml-stream).
#
# The partial_fit / merge / finalize contract over the batch estimators:
# each engine wraps one configured estimator, ingests row chunks (numpy
# blocks, pandas partitions, or facade DataFrames — frame chunks route
# through utils.materialize_feature_block, THE shared ingest
# materialization), stages every chunk device-resident through the
# existing pow2 shape buckets + AOT executable cache
# (ops/precompile.cached_kernel, so a steady stream of same-bucket chunks
# performs ZERO new compilations after the first bucket), and folds the
# chunk's device-computed partials into a small mergeable StreamState
# (stream/state.py).  finalize() materializes a REGULAR fitted model of
# the batch model class through the estimator's own _materialize_model
# bookkeeping — a streamed model persists, transforms, and serves exactly
# like its batch twin.
#
# Chunk math is SINGLE-DEVICE by design (the same mesh-independence
# argument as ann/ivfflat.train_coarse_quantizer): a chunk's partial
# statistics reduce in an order fixed by the chunk, never by a mesh, so
# streamed states are mesh-independent data and multi-rank scale-out
# comes from the state merge algebra across ranks (state.allgather_merge
# over the control plane), not from intra-chunk sharding.
#
# Equality contract (gated in tests/test_streaming.py and the CI 3o step;
# the full argument is docs/streaming.md §exactness):
#   - linreg / PCA: partial_fit over k chunks == batch fit on the union
#     BITWISE on the exact-arithmetic data families (integer-valued
#     features, pow2 row counts) — chunk partials are exact f32 sums, the
#     f64 host fold is exact, and finalize routes through the SAME solver
#     kernels (ops/glm.solve_linear / ops/linalg._pca_from_moments) the
#     batch fit dispatches.
#   - kmeans / logreg: quality-gated (inertia / classification metric) —
#     one-pass mini-batch Lloyd and warm-started chunk L-BFGS are online
#     approximations with no bitwise twin.
#

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import profiling
from ..ops.precompile import cached_kernel, shape_bucket
from ..utils import materialize_feature_block
from .state import StreamState

# smallest streamed-chunk row bucket: matches the ANN assign-block floor so
# tiny chunks do not shatter the executable cache into sub-256 geometries
# (SRML_STREAM_BUCKET_LO overrides; tests shrink it to exercise ladders)
_CHUNK_BUCKET_LO = 256
BUCKET_LO_ENV = "SRML_STREAM_BUCKET_LO"

H2D_COUNTER = "stream.h2d_transfers"
BYTES_COUNTER = "stream.bytes"


def chunk_bucket(n: int) -> int:
    """The ONE pow2 row bucket streamed chunks stage at (shared with every
    engine's warm/update dispatch so same-bucket chunks reuse executables)."""
    import os

    try:
        lo = int(os.environ.get(BUCKET_LO_ENV, _CHUNK_BUCKET_LO))
    except ValueError:
        lo = _CHUNK_BUCKET_LO
    return shape_bucket(n, lo=max(1, lo))


def _chunk_arrays(
    chunk: Any,
    y: Optional[Any],
    weight: Optional[Any],
    dtype: np.dtype,
    input_col: Optional[str],
    input_cols: Optional[List[str]],
    label_col: str,
    weight_col: str,
) -> Tuple[np.ndarray, Optional[np.ndarray], Optional[np.ndarray]]:
    """Coerce one streamed chunk into host (X, y, w) arrays.  Frame chunks
    (facade DataFrame or a single pandas partition) materialize through
    utils.materialize_feature_block — the same zero-copy block path batch
    ingest rides — and read labels/weights from the configured columns;
    numpy chunks pass through with explicit y/weight."""
    import pandas as pd

    from ..core import _partition_feature_block
    from ..dataframe import DataFrame as _Facade

    if isinstance(chunk, _Facade):
        parts = [p for p in chunk.partitions if len(p)]
    elif isinstance(chunk, pd.DataFrame):
        parts = [chunk] if len(chunk) else []
    else:
        X = np.ascontiguousarray(np.asarray(chunk), dtype=dtype)
        if X.ndim != 2:
            raise ValueError(f"streamed chunk must be 2-D, got shape {X.shape}")
        yv = None if y is None else np.asarray(y)
        wv = None if weight is None else np.asarray(weight)
        for name, v in (("y", yv), ("weight", wv)):
            if v is not None and v.shape[0] != X.shape[0]:
                # a silent zero-pad here would fold fabricated labels into
                # the state with full weight — fail before any math
                raise ValueError(
                    f"chunk {name} has {v.shape[0]} rows but X has "
                    f"{X.shape[0]}"
                )
        return X, yv, wv
    if y is not None or weight is not None:
        raise ValueError(
            "frame chunks carry labels/weights in their own columns; pass "
            "y/weight only with numpy chunks"
        )
    if not parts:
        return np.zeros((0, 0), dtype=dtype), None, None
    Xs, ys, ws = [], [], []
    for part in parts:
        block = (
            _partition_feature_block(part, input_col)
            if input_col is not None and input_col in part.columns
            else None
        )
        Xs.append(
            materialize_feature_block(
                block,
                part,
                input_col if input_col in part.columns else None,
                input_cols,
                dtype,
            )
        )
        if label_col in part.columns:
            ys.append(np.asarray(part[label_col].to_numpy()))
        if weight_col in part.columns:
            ws.append(np.asarray(part[weight_col].to_numpy(), dtype))
    X = np.concatenate(Xs) if len(Xs) > 1 else Xs[0]
    yv = (np.concatenate(ys) if len(ys) > 1 else ys[0]) if ys else None
    wv = (np.concatenate(ws) if len(ws) > 1 else ws[0]) if ws else None
    for name, col, v in (("label", label_col, yv), ("weight", weight_col, wv)):
        if v is not None and v.shape[0] != X.shape[0]:
            # some partitions carried the column and some did not — a
            # silent zero-pad would fold fabricated values at full weight
            raise ValueError(
                f"frame chunk's {col!r} {name} column covers {v.shape[0]} "
                f"of {X.shape[0]} rows (column missing from some "
                "partitions?)"
            )
    return X, yv, wv


def _stage(arr: np.ndarray, bucket: int, dtype) -> jax.Array:
    """Zero-pad one host array to the chunk bucket and device_put it,
    counted under the stream.h2d_transfers / stream.bytes pair (the
    umap.h2d_transfers pattern) so ingest volume shows up in
    export_metrics() and the standings bytes column."""
    a = np.asarray(arr, dtype=dtype)
    pad = bucket - a.shape[0]
    if pad:
        a = np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
    dev = jax.device_put(a)
    profiling.incr_counter(H2D_COUNTER)
    profiling.incr_counter(BYTES_COUNTER, int(a.nbytes))
    return dev


class StreamingEngine:
    """Shared partial_fit plumbing: column config from the wrapped
    estimator, chunk staging, row accounting, state wire helpers."""

    kind: str = ""

    def __init__(self, estimator: Any):
        self._estimator = estimator
        self._params: Dict[str, Any] = dict(estimator._tpu_params)
        input_col, input_cols = estimator._get_input_columns()
        self._input_col = input_col
        self._input_cols = input_cols
        self._label_col = (
            estimator.getOrDefault("labelCol")
            if estimator.hasParam("labelCol") and estimator.isDefined("labelCol")
            else "label"
        )
        self._weight_col = (
            estimator.getOrDefault("weightCol")
            if estimator.hasParam("weightCol") and estimator.isDefined("weightCol")
            else "weight"
        )
        self._dtype = np.dtype(np.float32)  # streaming is f32-only (docs)
        self._n_cols: Optional[int] = None
        self._rows: int = 0
        self._chunks: int = 0
        self._state: Optional[StreamState] = None

    # -- public surface ----------------------------------------------------
    @property
    def rows_ingested(self) -> int:
        return self._rows

    @property
    def chunks_ingested(self) -> int:
        return self._chunks

    @property
    def state(self) -> StreamState:
        if self._state is None:
            raise RuntimeError(
                f"Streaming{type(self._estimator).__name__} has ingested no "
                "chunks yet; call partial_fit first"
            )
        return self._state

    def state_dict(self) -> Dict[str, Any]:
        """JSON-able wire form of the accumulated state (the control-plane
        allGather payload; see state.allgather_merge)."""
        return self.state.to_dict()

    # state field whose trailing axis is the feature width — lets a FRESH
    # engine that adopts a peer's state (its own partition was empty, the
    # multicontroller uneven-rank case) recover n_cols without a chunk
    _N_COLS_FIELD = {
        "pca": "xwsum",
        "linreg": "xwsum",
        "logreg": "WS",
        "kmeans": "init_centers",
    }

    def merge(self, other: Any) -> "StreamingEngine":
        """Fold another stream's state into this engine: `other` may be a
        peer engine, a StreamState, or its wire dict.  Row/chunk accounting
        sums; engine-specific derived values refresh from the merged
        state.  A FRESH engine (zero chunks ingested — e.g. a rank whose
        partition was empty) adopts the peer state wholesale, identity
        anchors included."""
        if isinstance(other, StreamingEngine):
            peer_state, peer_rows, peer_chunks = (
                other.state, other._rows, other._chunks
            )
        elif isinstance(other, StreamState):
            peer_state, peer_rows, peer_chunks = other, 0, 0
        else:
            peer_state, peer_rows, peer_chunks = (
                StreamState.from_dict(other), 0, 0
            )
        if self._state is None:
            self._state = peer_state.copy()
        else:
            self._state = self._state.merge(peer_state)
        if self._n_cols is None:
            field = self._N_COLS_FIELD[self.kind]
            self._n_cols = int(self._state.arrays[field].shape[-1])
        self._rows += peer_rows
        self._chunks += peer_chunks
        self._post_merge()
        return self

    def partial_fit(
        self, chunk: Any, y: Any = None, weight: Any = None
    ) -> "StreamingEngine":
        """Ingest one chunk: stage device-resident at the pow2 bucket,
        dispatch the engine's update kernel through the AOT executable
        cache, fold the partials into the mergeable state."""
        X, yv, wv = _chunk_arrays(
            chunk, y, weight, self._dtype, self._input_col, self._input_cols,
            self._label_col, self._weight_col,
        )
        n = X.shape[0]
        if n == 0:
            return self
        if self._n_cols is None:
            self._n_cols = int(X.shape[1])
        elif int(X.shape[1]) != self._n_cols:
            raise ValueError(
                f"chunk feature width {X.shape[1]} != stream width "
                f"{self._n_cols}"
            )
        if wv is None:
            wv = np.ones(n, self._dtype)
        with profiling.span(
            "stream.update", rows=n, engine=self.kind
        ):
            self._update(X, yv, wv)
        self._rows += n
        self._chunks += 1
        profiling.incr_counter("stream.rows", n)
        profiling.incr_counter("stream.chunks")
        return self

    def finalize(self) -> Any:
        """Materialize a fitted model of the batch model class from the
        accumulated state (the estimator's own _materialize_model
        bookkeeping, so params/columns/dtype land exactly like a batch
        fit's)."""
        with profiling.span("stream.finalize", engine=self.kind):
            result = self._finalize_result()
            return self._estimator._materialize_model(result)

    # -- engine hooks ------------------------------------------------------
    def _update(self, X: np.ndarray, y, w: np.ndarray) -> None:
        raise NotImplementedError

    def _finalize_result(self) -> Dict[str, Any]:
        raise NotImplementedError

    def _post_merge(self) -> None:
        pass


class StreamingPCA(StreamingEngine):
    """PCA over an unbounded row stream: per-chunk weighted moments
    (ops/linalg.stream_moments_chunk_kernel) folded into f64 (wsum, xwsum,
    scatter); finalize routes the accumulated covariance through the SAME
    eigh derivation as the batch kernel (_pca_from_moments), device or
    native-host per the pca_fit routing rule."""

    kind = "pca"

    def _update(self, X, y, w) -> None:
        from ..ops.linalg import stream_moments_chunk_kernel

        bucket = chunk_bucket(X.shape[0])
        Xd = _stage(X, bucket, self._dtype)
        wd = _stage(w, bucket, self._dtype)
        wsum, xwsum, scatter = jax.device_get(
            cached_kernel("stream.pca_update", stream_moments_chunk_kernel, Xd, wd)
        )
        if self._state is None:
            d = self._n_cols
            self._state = StreamState(
                "pca",
                {
                    "wsum": np.zeros(()),
                    "xwsum": np.zeros(d),
                    "scatter": np.zeros((d, d)),
                },
            )
        self._state.add_(
            {"wsum": wsum, "xwsum": xwsum, "scatter": scatter}
        )

    def _finalize_result(self) -> Dict[str, Any]:
        from ..ops.linalg import pca_finalize_moments

        st = self.state.arrays
        d = self._n_cols
        k = self._params.get("n_components") or min(self._rows, d)
        k = min(int(k), d)
        # downcast the exact f64 fold to the compute dtype BEFORE the
        # derived divisions, so finalize's mean is the same single-rounded
        # f32 quotient the batch moment pass computes
        mean, components, var, ratio, sv = pca_finalize_moments(
            st["wsum"].astype(self._dtype),
            st["xwsum"].astype(self._dtype),
            st["scatter"].astype(self._dtype),
            k,
        )
        return {
            "mean_": np.asarray(mean, dtype=np.float64),
            "components_": np.asarray(components, dtype=np.float64),
            "explained_variance_": np.asarray(var, dtype=np.float64),
            "explained_variance_ratio_": np.asarray(ratio, dtype=np.float64),
            "singular_values_": np.asarray(sv, dtype=np.float64),
            "n_cols": self._n_cols,
            "dtype": str(np.dtype(self._dtype)),
        }


class StreamingLinearRegression(StreamingEngine):
    """Linear regression over a row stream: per-chunk unreduced sufficient
    statistics (ops/glm.stream_linreg_chunk_kernel) folded into f64;
    finalize solves the SAME closed-form / coordinate-descent kernels the
    batch fit dispatches (ops/glm.solve_linear / solve_elasticnet_cd) on
    the downcast stats, with the shared host-f64 intercept derivation."""

    kind = "linreg"

    def _update(self, X, y, w) -> None:
        from ..ops.glm import stream_linreg_chunk_kernel

        if y is None:
            raise ValueError(
                "StreamingLinearRegression chunks need labels (y= for numpy "
                f"chunks, a {self._label_col!r} column for frame chunks)"
            )
        bucket = chunk_bucket(X.shape[0])
        Xd = _stage(X, bucket, self._dtype)
        yd = _stage(np.asarray(y, self._dtype), bucket, self._dtype)
        wd = _stage(w, bucket, self._dtype)
        wsum, xwsum, G, ysum, c, y2 = jax.device_get(
            cached_kernel(
                "stream.linreg_update", stream_linreg_chunk_kernel, Xd, yd, wd
            )
        )
        if self._state is None:
            d = self._n_cols
            self._state = StreamState(
                "linreg",
                {
                    "wsum": np.zeros(()),
                    "xwsum": np.zeros(d),
                    "G": np.zeros((d, d)),
                    "ysum": np.zeros(()),
                    "c": np.zeros(d),
                    "y2": np.zeros(()),
                },
            )
        self._state.add_(
            {"wsum": wsum, "xwsum": xwsum, "G": G, "ysum": ysum, "c": c, "y2": y2}
        )

    def _finalize_result(self) -> Dict[str, Any]:
        from ..models.linear_regression import _host_intercept
        from ..ops.glm import LinregStats, solve_elasticnet_cd, solve_linear

        st = self.state.arrays
        dt = self._dtype
        wsum = st["wsum"].astype(dt)
        xwsum = st["xwsum"].astype(dt)
        ysum = st["ysum"].astype(dt)
        stats = LinregStats(
            wsum=jnp.asarray(wsum),
            x_mean=jnp.asarray(xwsum / wsum),  # single-rounded f32 quotient
            y_mean=jnp.asarray(ysum / wsum),
            G=jnp.asarray(st["G"].astype(dt)),
            c=jnp.asarray(st["c"].astype(dt)),
            y2=jnp.asarray(st["y2"].astype(dt)),
        )
        p = self._params
        alpha = float(p["alpha"])
        l1_ratio = float(p["l1_ratio"])
        fit_intercept = bool(p["fit_intercept"])
        normalize = bool(p["normalize"])
        # the batch _single_fit solver choice, verbatim
        if alpha == 0.0 or l1_ratio == 0.0:
            coef, _ = solve_linear(
                stats, alpha, fit_intercept=fit_intercept, normalize=normalize
            )
        else:
            coef, _, _ = solve_elasticnet_cd(
                stats,
                alpha,
                l1_ratio,
                fit_intercept=fit_intercept,
                normalize=normalize,
                max_iter=int(p["max_iter"]),
                tol=float(p["tol"]),
            )
        coef64 = np.asarray(jax.device_get(coef), dtype=np.float64)
        return {
            "coef_": coef64,
            "intercept_": _host_intercept(
                coef64, xwsum / wsum, ysum / wsum, fit_intercept
            ),
            "n_cols": self._n_cols,
            "dtype": str(np.dtype(dt)),
        }


class StreamingKMeans(StreamingEngine):
    """Mini-batch Lloyd over a row stream: the FIRST chunk trains the
    initial centers with the existing k-means|| init + Lloyd kernels
    (single-device, mesh-independent — the coarse-quantizer pattern);
    every chunk then assigns its rows to the CURRENT running centers
    (ops/kmeans.stream_kmeans_chunk_kernel) and folds count-weighted
    per-center sums into the state, so running centers are the exact
    weighted mean of every row ever assigned to them.  Merge adds
    per-center (sums, counts) — ranks must share the init anchor."""

    kind = "kmeans"

    def __init__(self, estimator: Any):
        super().__init__(estimator)
        self._centers: Optional[np.ndarray] = None  # running f64 centers
        self._init_centers: Optional[np.ndarray] = None
        self._cost: float = 0.0

    def _init_from_chunk(self, X: np.ndarray, w: np.ndarray) -> np.ndarray:
        from ..ops.kmeans import (
            lloyd_iterations,
            random_init,
            scalable_kmeans_pp_init,
        )
        from ..parallel.mesh import data_sharding, get_mesh

        p = self._params
        k = int(p["n_clusters"])
        seed = int(p["random_state"]) & 0x7FFFFFFF
        mesh1 = get_mesh(1)
        Xd = jax.device_put(np.asarray(X, self._dtype), data_sharding(mesh1))
        wd = jax.device_put(np.asarray(w, self._dtype), data_sharding(mesh1))
        if p["init"] == "random":
            centers0 = random_init(Xd, wd, k, seed)
        else:
            oversample = float(p["oversampling_factor"])
            round_size = max(1, min(int(oversample * k), X.shape[0]))
            centers0 = scalable_kmeans_pp_init(
                Xd, wd, k, seed, oversample, rounds=4, round_size=round_size
            )
        centers, _, _ = lloyd_iterations(
            Xd, wd, centers0, mesh1, int(p["max_iter"]), float(p["tol"]),
            min(int(p["max_samples_per_batch"]), X.shape[0]),
        )
        return np.asarray(jax.device_get(centers), np.float64)

    def _update(self, X, y, w) -> None:
        from ..ops.kmeans import stream_kmeans_chunk_kernel

        if self._centers is None:
            with profiling.span("stream.kmeans_init", rows=X.shape[0]):
                self._centers = self._init_from_chunk(X, w)
                self._init_centers = self._centers.copy()
        bucket = chunk_bucket(X.shape[0])
        Xd = _stage(X, bucket, self._dtype)
        wd = _stage(w, bucket, self._dtype)
        cd = jax.device_put(np.asarray(self._centers, self._dtype))
        sums, counts, cost = jax.device_get(
            cached_kernel(
                "stream.kmeans_update", stream_kmeans_chunk_kernel, Xd, wd, cd
            )
        )
        if self._state is None:
            k, d = self._centers.shape
            self._state = StreamState(
                "kmeans",
                {
                    "sums": np.zeros((k, d)),
                    "counts": np.zeros(k),
                    "cost": np.zeros(()),
                    "init_centers": self._init_centers,
                },
            )
        self._state.add_({"sums": sums, "counts": counts, "cost": cost})
        self._refresh_centers()

    def _refresh_centers(self) -> None:
        st = self.state.arrays
        counts = st["counts"]
        nonempty = counts > 0
        self._centers = np.where(
            nonempty[:, None],
            st["sums"] / np.maximum(counts, 1.0)[:, None],
            st["init_centers"],
        )

    def _post_merge(self) -> None:
        self._init_centers = self.state.arrays["init_centers"]
        self._refresh_centers()

    def _finalize_result(self) -> Dict[str, Any]:
        return {
            "cluster_centers_": np.asarray(self._centers, np.float64),
            "n_cols": self._n_cols,
            "dtype": str(np.dtype(self._dtype)),
            "n_iter_": self._chunks,
            "inertia_": float(self.state.arrays["cost"]),
        }


class StreamingLogisticRegression(StreamingEngine):
    """Logistic regression over a row stream: each chunk runs the batch
    objective's L-BFGS/OWL-QN WARM-STARTED from the running streamed
    coefficients (ops/logistic.logistic_warm_fit_kernel — identical
    objective, different starting point), and the state folds
    count-weighted coefficient sums (iterate averaging), so merge across
    ranks is the row-weighted mean of per-rank streams.  The class set is
    an identity anchor: declared up front (classes=) or discovered from
    the first chunk; later chunks with unseen labels fail loudly."""

    kind = "logreg"

    def __init__(self, estimator: Any, classes: Optional[Any] = None):
        super().__init__(estimator)
        self._classes = (
            None if classes is None else np.unique(np.asarray(classes, np.float64))
        )
        self._W: Optional[np.ndarray] = None  # running averaged (k, D)
        self._b: Optional[np.ndarray] = None

    def _update(self, X, y, w) -> None:
        from ..ops.logistic import logistic_warm_fit_kernel

        if y is None:
            raise ValueError(
                "StreamingLogisticRegression chunks need labels (y= for "
                f"numpy chunks, a {self._label_col!r} column for frame chunks)"
            )
        yv = np.asarray(y, np.float64)
        if self._classes is None:
            self._classes = np.unique(yv)
            if len(self._classes) < 2:
                raise ValueError(
                    "first chunk holds a single label class; declare the "
                    "full class set via streaming(classes=...) when early "
                    "chunks may be single-class"
                )
        idx = np.searchsorted(self._classes, yv)
        idx = np.clip(idx, 0, len(self._classes) - 1)
        if not np.array_equal(self._classes[idx], yv):
            unseen = sorted(set(np.unique(yv)) - set(self._classes))
            raise ValueError(
                f"chunk contains labels outside the stream's class set: "
                f"{unseen}; declare them up front via streaming(classes=...)"
            )
        num_classes = len(self._classes)
        kcls = 1 if num_classes == 2 else num_classes
        d = self._n_cols
        if self._W is None:
            self._W = np.zeros((kcls, d), np.float64)
            self._b = np.zeros((kcls,), np.float64)
        p = self._params
        C = float(p["C"])
        reg = 1.0 / C if C > 0 else 0.0
        l1_ratio = float(p.get("l1_ratio") or 0.0)
        use_owlqn = reg > 0 and l1_ratio > 0
        bucket = chunk_bucket(X.shape[0])
        Xd = _stage(X, bucket, self._dtype)
        yd = _stage(idx.astype(np.int32), bucket, np.int32)
        wd = _stage(w, bucket, self._dtype)
        W0 = jax.device_put(np.asarray(self._W, self._dtype))
        b0 = jax.device_put(np.asarray(self._b, self._dtype))
        W, b, _n_iter, _conv = jax.device_get(
            cached_kernel(
                "stream.logreg_update",
                logistic_warm_fit_kernel,
                Xd, yd, wd, W0, b0,
                jnp.asarray(reg, self._dtype),
                jnp.asarray(l1_ratio, self._dtype),
                jnp.asarray(float(p["tol"]), self._dtype),
                k=kcls,
                fit_intercept=bool(p["fit_intercept"]),
                max_iter=int(p["max_iter"]),
                use_owlqn=use_owlqn,
            )
        )
        cw = float(np.asarray(w, np.float64).sum())
        if self._state is None:
            self._state = StreamState(
                "logreg",
                {
                    "WS": np.zeros((kcls, d)),
                    "bs": np.zeros((kcls,)),
                    "wsum": np.zeros(()),
                    "classes": self._classes,
                },
            )
        self._state.add_(
            {"WS": cw * np.asarray(W, np.float64),
             "bs": cw * np.asarray(b, np.float64),
             "wsum": cw}
        )
        self._refresh_coefs()

    def _refresh_coefs(self) -> None:
        st = self.state.arrays
        wsum = max(float(st["wsum"]), 1e-30)
        self._W = st["WS"] / wsum
        self._b = st["bs"] / wsum

    def _post_merge(self) -> None:
        self._classes = self.state.arrays["classes"]
        self._refresh_coefs()

    def _finalize_result(self) -> Dict[str, Any]:
        return {
            "coef_": np.asarray(self._W, np.float64),
            "intercept_": np.asarray(self._b, np.float64),
            "classes_": np.asarray(self._classes, np.float64),
            "n_cols": self._n_cols,
            "dtype": str(np.dtype(self._dtype)),
            "num_iters": self._chunks,
        }


_ENGINES = {
    "KMeans": StreamingKMeans,
    "PCA": StreamingPCA,
    "LinearRegression": StreamingLinearRegression,
    "LogisticRegression": StreamingLogisticRegression,
}


def streaming_fit(estimator: Any, **kwargs: Any) -> StreamingEngine:
    """The streaming engine for a configured estimator — the functional
    form of the estimators' .streaming() hook."""
    name = type(estimator).__name__
    cls = _ENGINES.get(name)
    if cls is None:
        raise TypeError(
            f"{name} has no streaming engine; streamable estimators: "
            f"{sorted(_ENGINES)} (forest/UMAP streaming is a documented "
            "non-goal — docs/streaming.md)"
        )
    return cls(estimator, **kwargs)
