#
# Mergeable streaming-fit state (srml-stream).
#
# Every streaming engine's accumulated knowledge is one StreamState: a
# small pytree of float64 host arrays (counts / weighted sums / Gram and
# covariance moments / count-weighted coefficient sums) whose merge is
# FIELD-WISE ADDITION — the same associative+commutative algebra the
# telemetry snapshots (profiling.TelemetrySnapshot) ride across ranks, so
# multi-rank streams reduce their states through the existing control
# plane (allGather of the JSON wire form + fold) with no new collective
# machinery.  A few fields are identity anchors rather than statistics
# (the kmeans init centers, the logreg class set): those merge under the
# "equal" reducer — both sides must carry the same bits, because adding
# two streams that disagree on their anchor is a user error, not algebra.
#
# float64 on the HOST is deliberate: chunk partials are computed on device
# in the fit dtype (exact f32 sums on the equality-gate data families —
# see docs/streaming.md §exactness), and the host fold keeps every partial
# exactly, so merge order can never change the finalized model on the
# gated data.  This module is host-side numpy only — no jax.
#

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

WIRE_SCHEMA = "srml-stream/v1"

# per-kind field reducers; any field not listed merges under "add"
_EQUAL_FIELDS = {
    "kmeans": ("init_centers",),
    "logreg": ("classes",),
}

# the known kinds (one per streaming engine) — wire decode rejects others
KINDS = ("kmeans", "pca", "linreg", "logreg")


class StreamState:
    """One engine's mergeable accumulator: kind tag + named f64 arrays.

    merge() is pure (returns a NEW state) so rank folds can reduce
    gathered states without aliasing; engines hold a private mutable copy
    and fold chunk partials in place via add_()."""

    __slots__ = ("kind", "arrays")

    def __init__(self, kind: str, arrays: Dict[str, np.ndarray]):
        if kind not in KINDS:
            raise ValueError(f"unknown stream state kind {kind!r}; one of {KINDS}")
        self.kind = str(kind)
        self.arrays = {
            name: np.asarray(a, np.float64) for name, a in arrays.items()
        }

    def _check_compatible(self, other: "StreamState") -> None:
        if self.kind != other.kind:
            raise ValueError(
                f"cannot merge stream states of kind {self.kind!r} and "
                f"{other.kind!r}"
            )
        if set(self.arrays) != set(other.arrays):
            raise ValueError(
                f"stream state field mismatch: {sorted(self.arrays)} vs "
                f"{sorted(other.arrays)}"
            )
        for name, a in self.arrays.items():
            b = other.arrays[name]
            if a.shape != b.shape:
                raise ValueError(
                    f"stream state field {name!r} shape mismatch: "
                    f"{a.shape} vs {b.shape} (different k/D streams?)"
                )

    def add_(self, partials: Dict[str, Any]) -> "StreamState":
        """Fold one chunk's partials into this state IN PLACE (engine-side
        hot path; additive fields only)."""
        equal = _EQUAL_FIELDS.get(self.kind, ())
        for name, v in partials.items():
            if name in equal:
                raise ValueError(f"field {name!r} is an identity anchor, not additive")
            self.arrays[name] = self.arrays[name] + np.asarray(v, np.float64)
        return self

    def merge(self, other: "StreamState") -> "StreamState":
        """Associative + commutative combine of two streams' states:
        additive fields sum; identity anchors must agree bitwise."""
        self._check_compatible(other)
        equal = _EQUAL_FIELDS.get(self.kind, ())
        out = {}
        for name, a in self.arrays.items():
            b = other.arrays[name]
            if name in equal:
                if not np.array_equal(a, b):
                    raise ValueError(
                        f"cannot merge {self.kind} streams with different "
                        f"{name!r} anchors (streams must share their seed/"
                        "init — see docs/streaming.md §merge)"
                    )
                out[name] = a.copy()
            else:
                out[name] = a + b
        return StreamState(self.kind, out)

    def copy(self) -> "StreamState":
        return StreamState(self.kind, {n: a.copy() for n, a in self.arrays.items()})

    # -- wire format (control-plane allGather payload) ---------------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "schema": WIRE_SCHEMA,
            "kind": self.kind,
            "arrays": {
                name: {"shape": list(a.shape), "data": a.ravel().tolist()}
                for name, a in sorted(self.arrays.items())
            },
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "StreamState":
        if d.get("schema") != WIRE_SCHEMA:
            raise ValueError(
                f"unknown stream state schema {d.get('schema')!r}; "
                f"expected {WIRE_SCHEMA}"
            )
        arrays = {
            name: np.asarray(spec["data"], np.float64).reshape(spec["shape"])
            for name, spec in d["arrays"].items()
        }
        return cls(d["kind"], arrays)

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, StreamState)
            and self.kind == other.kind
            and set(self.arrays) == set(other.arrays)
            and all(
                np.array_equal(a, other.arrays[n]) for n, a in self.arrays.items()
            )
        )

    def __repr__(self) -> str:
        fields = ", ".join(
            f"{n}{list(a.shape)}" for n, a in sorted(self.arrays.items())
        )
        return f"StreamState({self.kind}: {fields})"


def merge_all(states: List[StreamState]) -> StreamState:
    """Left fold of merge() over a non-empty state list (rank order — the
    deterministic fold every rank applies to an allGathered list)."""
    if not states:
        raise ValueError("merge_all of zero states")
    out = states[0]
    for s in states[1:]:
        out = out.merge(s)
    return out


def allgather_merge(control_plane: Any, state: StreamState) -> StreamState:
    """Reduce this rank's state with every peer's through the control
    plane: allGather the JSON wire form (rank-indexed, the ControlPlane
    ordering contract) and fold in rank order — every rank computes the
    IDENTICAL merged state, exactly like the fit-telemetry reduction in
    parallel/runner.py."""
    import json

    msgs = control_plane.allGather(json.dumps(state.to_dict()))
    return merge_all([StreamState.from_dict(json.loads(m)) for m in msgs])
