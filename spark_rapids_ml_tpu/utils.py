#
# Shared utilities: logging, dtype mapping, array layout helpers.
#
# Functional counterpart of the reference's utils
# (/root/reference/python/src/spark_rapids_ml/utils.py): get_logger (:250),
# dtype mapping (:233), memory-careful concat (:199).  GPU-id discovery
# (:98-130) has no TPU analog — device binding is the jax mesh's job
# (see parallel/mesh.py).
#

from __future__ import annotations

import logging
import os
import sys
from typing import Any, Iterator, List, Optional, Union

import numpy as np


def env_float(name: str, default: float) -> float:
    """Float env knob with a default on unset/empty/garbage — the ONE
    parse-env-with-fallback helper (watch, serving, and the control plane
    each grew a private copy before this; a future tweak to the parsing
    must land once)."""
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def get_logger(cls: Union[type, str], level: int = logging.INFO) -> logging.Logger:
    """Per-class stderr logger with a standard format (reference utils.py:250-267)."""
    name = cls if isinstance(cls, str) else f"spark_rapids_ml_tpu.{cls.__name__}"
    logger = logging.getLogger(name)
    logger.setLevel(level)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
        logger.addHandler(handler)
        logger.propagate = False
    return logger


def dtype_to_pyspark_type(dtype: Union[np.dtype, str]) -> str:
    """numpy dtype -> Spark SQL type name (reference utils.py:233-247)."""
    dtype = np.dtype(dtype)
    if dtype == np.float32:
        return "float"
    if dtype == np.float64:
        return "double"
    if dtype == np.int32:
        return "int"
    if dtype == np.int64:
        return "long"
    if dtype == np.int16:
        return "short"
    raise RuntimeError(f"Unsupported dtype: {dtype}")


def _concat_and_free(array_list: List[np.ndarray], order: str = "F") -> np.ndarray:
    """Concatenate row chunks while freeing inputs incrementally to bound peak
    host memory (behavioral analog of reference utils.py:199-221).  C-order
    float matrices route through the threaded native runtime when built
    (native.concat_rows), the host-bandwidth half of ingest."""
    if len(array_list) == 1:
        arr = array_list.pop()
        return np.asarray(arr, order=order)  # type: ignore[call-overload]
    if (
        order == "C"
        and array_list[0].ndim == 2
        and array_list[0].dtype in (np.float32, np.float64)
    ):
        from . import native

        if native.available():
            out = native.concat_rows(array_list, array_list[0].dtype)
            array_list.clear()
            return out
    rows = sum(a.shape[0] for a in array_list)
    if array_list[0].ndim == 1:
        out = np.empty((rows,), dtype=array_list[0].dtype)
    else:
        out = np.empty((rows, array_list[0].shape[1]), dtype=array_list[0].dtype, order=order)  # type: ignore[call-overload]
    offset = 0
    while array_list:
        a = array_list.pop(0)
        out[offset : offset + a.shape[0]] = a
        offset += a.shape[0]
        del a
    return out


def stack_feature_cells(cells: Any, dtype: np.dtype) -> np.ndarray:
    """Column of array-like cells -> 2-D array.

    Accepts the Spark array<float> layout (ndarray/list cells), pyspark
    ``DenseVector``/``SparseVector`` cells (the reference ingests both,
    e.g. Vectors.sparse doctests at classification.py:418,435), and scipy
    sparse row matrices.  Sparse inputs are densified: the MXU wants dense
    tiles, and every solver here is a dense formulation."""
    n = len(cells)
    if n == 0:
        return np.zeros((0, 0), dtype=dtype)
    first = cells[0]
    if np.ndim(first) == 0 and np.issubdtype(np.asarray(first).dtype, np.integer):
        # scalar-int cells are the sparse-block placeholder column written by
        # DataFrame.from_numpy(csr) — fail loudly instead of returning row
        # positions as "features"
        raise TypeError(
            "feature column holds sparse-block placeholders, not vectors; "
            "read this partition via core.extract_partition_features (its "
            "features live in a CSR block in partition .attrs)"
        )
    if hasattr(first, "toArray"):  # pyspark Vector cells
        size = len(first)
        out = np.zeros((n, size), dtype=dtype)
        for i, c in enumerate(cells):
            idx = getattr(c, "indices", None)
            if idx is not None:  # SparseVector: fill nonzeros only
                out[i, np.asarray(idx, dtype=np.int64)] = c.values
            else:
                out[i] = c.toArray()
        return out
    if hasattr(first, "toarray") and hasattr(first, "tocsr"):  # scipy sparse rows
        import scipy.sparse as sp

        return np.asarray(sp.vstack(list(cells)).toarray(), dtype=dtype)
    try:
        out = np.stack(cells)
    except ValueError as e:
        raise ValueError(
            "feature column cells must all be arrays of the same length"
        ) from e
    return np.asarray(out, dtype=dtype)


def materialize_feature_block(
    block: Any,
    part: Any,
    input_col: Optional[str],
    input_cols: Optional[List[str]],
    dtype: np.dtype,
    densify_sparse: bool = True,
    on_densify: Optional[Any] = None,
) -> np.ndarray:
    """One partition's feature matrix from a stashed feature block (dense
    2-D or sparse CSR, or None) with a column fallback — THE shared ingest
    materialization: estimator ingest, model transform, and the standalone
    extract_partition_features all route here (it was triplicated across
    core.py before graftlint's duplicate-code finding).

    `block` is the partition's pre-validated feature block from
    core._partition_feature_block (None when absent or when reading
    input_cols).  Sparse blocks stay CSR when densify_sparse=False;
    otherwise they densify — the ONE sanctioned np.asarray(toarray())
    site (graftlint R1 allowlists this function) — calling `on_densify`
    first so callers can warn."""
    if block is not None and hasattr(block, "tocsr"):
        if not densify_sparse:
            return block  # CSR stays sparse through to ELL ingest
        if on_densify is not None:
            on_densify()
        return np.asarray(block.toarray(), dtype=dtype)
    if block is not None:
        return np.asarray(block, dtype=dtype)
    if input_col is not None:
        return stack_feature_cells(part[input_col].tolist(), dtype)
    assert input_cols is not None
    return np.asarray(part[input_cols].to_numpy(), dtype=dtype)


def pad_rows(arr: np.ndarray, multiple: int) -> np.ndarray:
    """Zero-pad rows so arr.shape[0] is a multiple of `multiple` (static shapes
    for XLA; padded rows are masked by zero weights downstream)."""
    n = arr.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return arr
    pad_shape = (rem,) + arr.shape[1:]
    return np.concatenate([arr, np.zeros(pad_shape, dtype=arr.dtype)], axis=0)


def chunk_iter(n: int, chunk: int) -> Iterator[slice]:
    for start in range(0, n, chunk):
        yield slice(start, min(start + chunk, n))
