#
# Exact NearestNeighbors estimator/model.
#
# Capability parity with the reference's NearestNeighbors
# (/root/reference/python/src/spark_rapids_ml/knn.py:154-683): fit just
# captures the item dataframe (no training, knn.py:297-317), kneighbors
# returns (item_df_withid, query_df_withid, knn_df(query_id, indices,
# distances)) with euclidean distances and float32 inputs (knn.py:411-466),
# exactNearestNeighborsJoin builds the exploded join frame (knn.py:604-672),
# and neither estimator nor model is persistable (knn.py:333-345, 674-683).
# The UCX p2p partition exchange is replaced by the mesh block schedule in
# ops/knn.py — on multi-shard meshes the candidate exchange is the
# ring-permute route by default (query blocks rotate neighbor-to-neighbor
# with a traveling top-k; SRML_KNN_EXCHANGE selects; docs/knn_pipeline.md).
#

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np
import pandas as pd

from ..core import _TpuEstimatorSupervised, _TpuModel
from ..dataframe import DataFrame, as_dataframe
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    Param,
    TypeConverters,
    _dummy,
    _TpuParams,
)
from ..parallel.mesh import get_mesh


class NearestNeighborsClass(_TpuParams):
    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {"k": "n_neighbors"}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {"n_neighbors": 5, "verbose": False, "algorithm": "brute", "metric": "euclidean"}


class _NearestNeighborsParams(NearestNeighborsClass, HasFeaturesCol, HasFeaturesCols):
    k = Param(_dummy(), "k", "the number of nearest neighbors to retrieve (> 0)", TypeConverters.toInt)
    idCol = Param(_dummy(), "idCol", "id column name; if unset a monotonically increasing id column is generated", TypeConverters.toString)

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(k=5)

    def getK(self) -> int:
        return self.getOrDefault("k")

    def setK(self, value: int):
        return self._set_params(k=value)

    def getIdCol(self) -> str:
        return self.getOrDefault("idCol") if self.isDefined("idCol") else "unique_id"

    def setIdCol(self, value: str):
        self.set(self.getParam("idCol"), value)
        return self

    def setInputCol(self, value: Union[str, List[str]]):
        if isinstance(value, str):
            self._set_params(featuresCol=value)
        else:
            self._set_params(featuresCols=value)
        return self


class NearestNeighbors(_NearestNeighborsParams, _TpuEstimatorSupervised):
    """Exact brute-force kNN over the TPU mesh (API parity knn.py:154-345)."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._initialize_tpu_params()
        self._set_params(**kwargs)

    def _fit(self, dataset: Any) -> "NearestNeighborsModel":
        from ..core import _use_executor_path

        if getattr(dataset, "_device_features", None) is not None:
            # fitting would silently DROP the device array (the captured
            # frame only carries the placeholder column) and kneighbors
            # would later read placeholder garbage; device-resident item
            # sets enter through NearestNeighborsModel.seed_staging instead
            raise NotImplementedError(
                "NearestNeighbors.fit does not take DataFrame.from_device "
                "frames (their features column is a placeholder); fit a "
                "host frame and install the device-resident index with "
                "model.seed_staging(...)"
            )
        if _use_executor_path(dataset):
            # live pyspark input: hold the DataFrame itself — item partitions
            # stay on the executors until kneighbors runs its barrier stage
            # (reference fit just captures the frame too, knn.py:297-317).
            # Nothing is collected to the driver here or later.
            from ..spark.adapter import ensure_id_col

            df = ensure_id_col(dataset, self.getIdCol())
        else:
            df = as_dataframe(dataset)
            if not self.isDefined("idCol"):
                df = df.with_row_id("unique_id")
        model = NearestNeighborsModel(item_df=df)
        self._copyValues(model)
        model._tpu_params.update(self._tpu_params)
        model._num_workers = self._num_workers
        model._float32_inputs = self._float32_inputs
        model._item_df = df
        return model

    def fit(self, dataset: Any, params: Optional[Dict] = None) -> "NearestNeighborsModel":
        return self._fit(dataset)

    def _get_tpu_fit_func(self, dataset, extra_params=None):  # pragma: no cover
        raise NotImplementedError("NearestNeighbors overrides _fit")

    def _create_model(self, result):  # pragma: no cover
        raise NotImplementedError("NearestNeighbors overrides _fit")

    def write(self):
        raise NotImplementedError(
            "NearestNeighbors does not support saving/loading, just re-create the estimator."
        )

    @classmethod
    def read(cls):
        raise NotImplementedError(
            "NearestNeighbors does not support saving/loading, just re-create the estimator."
        )


class NearestNeighborsModel(_NearestNeighborsParams, _TpuModel):
    def __init__(self, item_df: Optional[DataFrame] = None, **kwargs: Any) -> None:
        super().__init__()
        self._item_df = item_df
        # device-staging caches for repeated kneighbors calls on one model
        # (the TPU analog of cuML keeping the index device-resident): the
        # prepared item blocks are cached when the whole set fits the HBM
        # budget, and each query partition's upload is cached keyed by the
        # identity of its zero-copy feature block.  Both die with the model.
        self._staged_items: Optional[Tuple[Any, Any]] = None
        self._staged_queries: Dict[int, Tuple[int, Any]] = {}

    def _iter_item_blocks(self, id_col: str, dtype, mesh):
        """(features, ids) stream over the item partitions — the host never
        holds more than one partition before the device-block packer."""
        from ..core import extract_partition_features
        from ..ops.knn import iter_prepared_item_blocks

        input_col, input_cols = self._get_input_columns()

        def _parts():
            for part in self._item_df.partitions:
                if len(part) == 0:
                    continue
                yield (
                    extract_partition_features(part, input_col, input_cols, dtype),
                    np.asarray(part[id_col].to_numpy(), np.int64),
                )

        return iter_prepared_item_blocks(_parts(), mesh, dtype)

    def kneighbors(
        self, query_df: Any
    ) -> Tuple[DataFrame, DataFrame, DataFrame]:
        """Exact k nearest item neighbors for every query row; float32
        euclidean (the reference converts all input to float32, knn.py:425).
        On TPU hardware the large-shard fast path is exact up to ~1e-6-
        relative ties at the kth distance — candidates inside that float32
        sliver are interchangeable, ordered as arbitrarily as any exact f32
        sort orders true ties (ops/knn.knn_block_adaptive).

        Partition-streamed on BOTH sides (the reference keeps partitions on
        the workers and exchanges p2p, knn.py:452-560): item partitions pack
        into device-resident blocks one at a time, each query partition's
        candidates merge on the host, and the result frame keeps the query
        partitioning.  Peak driver memory is O(one item block + one query
        partition + k * n_query) — never the concatenated item set."""
        assert self._item_df is not None, "fit() must be called before kneighbors"
        from ..core import _is_pyspark_dataframe, extract_partition_features
        from ..ops.knn import knn_search_streamed

        if _is_pyspark_dataframe(self._item_df):
            # executor-side path: the barrier stage exchanges query blocks
            # and candidate lists between tasks; item partitions never leave
            # their executors and nothing is collected to the driver
            # (reference knn.py:452-560)
            if not _is_pyspark_dataframe(query_df):
                raise TypeError(
                    "the fitted item dataframe is a live pyspark DataFrame; "
                    "kneighbors requires a pyspark query DataFrame too"
                )
            from ..spark.adapter import (
                ensure_id_col,
                infer_spark_num_workers,
                run_barrier_kneighbors,
            )

            id_col = self.getIdCol()
            qdf_spark = ensure_id_col(query_df, id_col)
            input_col, input_cols = self._get_input_columns()
            num_workers = infer_spark_num_workers(
                self, query_df.sparkSession
            )
            knn_df = run_barrier_kneighbors(
                self._item_df,
                qdf_spark,
                self.getK(),
                id_col,
                input_col,
                input_cols,
                num_workers,
            )
            return self._item_df, qdf_spark, knn_df

        qdf = as_dataframe(query_df)
        id_col = self.getIdCol()
        if id_col not in qdf.columns:
            qdf = qdf.with_row_id(id_col)
        dtype = np.float32
        input_col, input_cols = self._get_input_columns()
        q_parts = list(qdf.partitions)  # ALL partitions: the result frame
        # must align partition-for-partition with the query frame
        if not any(len(p) > 0 for p in q_parts):
            empty = pd.DataFrame(
                {f"query_{id_col}": [], "indices": [], "distances": []}
            )
            return (
                self._item_df,
                qdf,
                DataFrame([empty.copy() for _ in range(max(1, len(q_parts)))]),
            )

        def _query_feats(p: int) -> np.ndarray:
            return extract_partition_features(
                q_parts[p], input_col, input_cols, dtype
            )

        mesh = get_mesh(self.num_workers)
        from .. import profiling

        # the candidate-exchange route each dispatched block actually took
        # lands in the knn.exchange_route.<route> counters (incremented at
        # the ops-layer dispatch chokepoint, so the adaptive Pallas route —
        # which runs no exchange — is never misattributed); traces and
        # metric exports distinguish ring from all-gather deployments
        # without reading env state
        with profiling.trace_session("search-NearestNeighbors"):
            per_part = self._search_partitions(
                id_col, dtype, mesh, q_parts, _query_feats, self.getK()
            )
        out_parts = []
        for part, (dists, ids) in zip(q_parts, per_part):
            out_parts.append(
                pd.DataFrame(
                    {
                        f"query_{id_col}": part[id_col].to_numpy()
                        if len(part)
                        else np.zeros(0, np.int64),
                        "indices": list(ids),
                        "distances": list(dists.astype(np.float32)),
                    }
                )
            )
        return self._item_df, qdf, DataFrame(out_parts)

    def _search_partitions(self, id_col, dtype, mesh, q_parts, query_feats, k):
        """Exact search of every query partition against the item set.

        In-core item sets (fitting the per-replica HBM budget) are staged to
        the device ONCE and cached on the model, so repeated kneighbors
        calls — batch inference loops, benchmarks — pay only compute;
        query partition uploads are cached the same way (keyed by the
        identity of the extracted feature array, with the host array
        pinned so the id cannot be recycled).  Larger-than-HBM item sets
        keep the uncached streaming path (knn_search_streamed)."""
        from ..ops.knn import knn_search_prepared, knn_search_streamed

        prepared, leftover_blocks, _reason = self._stage_in_core_items(
            id_col, dtype, mesh
        )
        if prepared is None:
            # degrade to the (uncached) streaming path, reusing any blocks
            # the staging attempt already packed to device
            return knn_search_streamed(
                leftover_blocks
                if leftover_blocks is not None
                else self._iter_item_blocks(id_col, dtype, mesh),
                query_feats,
                [len(p) for p in q_parts],
                k,
                mesh,
            )
        # AOT-warm the query kernels for the largest partition's block
        # bucket: XLA compiles on the precompile worker pool while the
        # query features extract below, instead of serially inside the
        # first dispatched block (the dominant share of kNN cold_sec);
        # repeat kneighbors calls hit the same cached executables
        from ..ops.knn import warm_search_kernels

        q_rows_max = max((len(p) for p in q_parts), default=0)
        if q_rows_max:
            warm_search_kernels(
                prepared, k, mesh,
                n_queries=q_rows_max, d_query=self._frame_dim(dtype),
            )
        k_eff = min(k, prepared.n_items)
        out = []
        for p in range(len(q_parts)):
            if len(q_parts[p]) == 0:
                out.append(
                    (
                        np.zeros((0, k_eff), dtype),
                        np.zeros((0, k_eff), np.int64),
                    )
                )
                continue
            feats = query_feats(p)
            out.append(
                knn_search_prepared(
                    prepared, self._staged_query(p, feats, dtype), k, mesh
                )
            )
        return out

    def _stage_in_core_items(self, id_col: str, dtype, mesh):
        """THE one definition of 'can this item set live device-resident,
        and is it staged?' — shared by the kneighbors fast path and the
        serving entry so the two can never disagree on the in-core
        estimate, the staging key, or the block-split boundary case.

        Returns (prepared, leftover_blocks, reason):
          - (PreparedItems, None, None): staged (and cached on the model);
          - (None, blocks_iter | None, reason): not stageable — `reason`
            says why, and `blocks_iter`, when not None, carries device
            blocks a failed staging attempt already packed so a streaming
            fallback need not re-upload them."""
        from ..ops.knn import _hbm_budget_bytes
        from ..parallel.mesh import DATA_AXIS

        rows = sum(len(p) for p in self._item_df.partitions)
        dim = self._frame_dim(dtype)
        n_dev = mesh.shape[DATA_AXIS]
        in_core = (
            dim is not None
            and rows * dim * np.dtype(dtype).itemsize
            <= _hbm_budget_bytes() * n_dev
        )
        if not in_core:
            self._staged_items = None
            return (
                None,
                None,
                f"item set ({rows} x {dim}) exceeds the per-replica HBM "
                "budget (SRML_KNN_HBM_BUDGET)",
            )
        key = self._staging_key(mesh, rows, dim)
        if self._staged_items is None or self._staged_items[0] != key:
            blocks = list(self._iter_item_blocks(id_col, dtype, mesh))
            if len(blocks) != 1:
                # the packer's n_dev-rounded per-block row bound can split
                # right at the HBM-budget boundary even though the estimate
                # above said in-core
                self._staged_items = None
                return (
                    None,
                    iter(blocks),
                    "item set split across device blocks at the HBM-budget "
                    "boundary",
                )
            self._staged_items = (key, blocks[0])
            self._staged_queries.clear()
        return self._staged_items[1], None, None

    def _frame_dim(self, dtype):
        """Feature dimensionality of the item frame, from ONE row —
        extracting a whole partition would re-stack O(rows x D) cell
        features per call for list-cell frames.  ONE definition shared by
        the cache lookup and seed_staging: the key must describe the SOURCE
        frame, not a prepared layout (prepare_items may tile-align columns,
        so prepared.items.shape[1] can exceed the frame dim — deriving the
        key from it silently defeated the seeded cache)."""
        parts = [p for p in self._item_df.partitions if len(p)]
        if not parts:
            return None
        from ..core import extract_partition_features

        input_col, input_cols = self._get_input_columns()
        return extract_partition_features(
            parts[0].iloc[:1], input_col, input_cols, dtype
        ).shape[1]

    def _staging_key(self, mesh, rows: int, dim: int):
        """Identity of the staged item set — ONE definition shared by the
        lookup in _search_partitions and seed_staging, so external seeding
        can never drift from the cache-hit check."""
        return (
            tuple(id(p) for p in self._item_df.partitions),
            id(mesh),
            rows,
            dim,
        )

    def seed_staging(self, prepared, query_blocks=None, mesh=None) -> None:
        """Install an already device-resident item set (ops.knn
        PreparedItems) — and optionally per-query-partition device arrays —
        as this model's staging caches.  For callers whose data is already
        on device (jax-native pipelines, benchmarks): subsequent kneighbors
        calls are compute-only, and a key mismatch is impossible because
        the key is computed here by the same _staging_key the lookup
        uses."""
        mesh = mesh or get_mesh(self.num_workers)
        rows = sum(len(p) for p in self._item_df.partitions)
        dim = self._frame_dim(np.float32)
        if dim is None:
            raise ValueError(
                "cannot seed staging for an empty item frame (no rows to "
                "derive the feature dimensionality from)"
            )
        if prepared.items.shape[1] < dim:
            raise ValueError(
                f"prepared item columns ({prepared.items.shape[1]}) are "
                f"narrower than the frame's feature dim ({dim}); the "
                "seeded index would search truncated vectors"
            )
        if prepared.n_items != rows:
            raise ValueError(
                f"prepared item count ({prepared.n_items}) != the frame's "
                f"row count ({rows}); the seeded index would silently "
                "serve results from a mismatched item set"
            )
        self._staged_items = (self._staging_key(mesh, rows, dim), prepared)
        self._staged_queries.clear()
        if query_blocks:
            for p, (feats, dev) in query_blocks.items():
                self._staged_queries[p] = (feats, dev)
        # seeding is the device-resident fast path (benchmarks, jax-native
        # pipelines): warm the default production query-block geometry too,
        # so the first kneighbors call after seeding is compile-free
        from ..ops.knn import warm_search_kernels

        warm_search_kernels(prepared, self.getK(), mesh, d_query=dim)

    def _staged_query(self, p: int, feats: np.ndarray, dtype):
        import jax.numpy as jnp

        ent = self._staged_queries.get(p)
        if (
            ent is not None
            and ent[0] is feats  # pinned host array: identity is stable
            and ent[1].shape == feats.shape
        ):
            return ent[1]
        dev = jnp.asarray(np.asarray(feats, dtype))
        self._staged_queries[p] = (feats, dev)
        return dev

    def exactNearestNeighborsJoin(
        self, query_df: Any, distCol: str = "distCol"
    ) -> DataFrame:
        """Exploded knn join: rows (item_df struct, query_df struct, distCol)
        (reference knn.py:604-672; structs here are dicts of the source
        rows)."""
        id_col = self.getIdCol()
        from ..core import _is_pyspark_dataframe

        if _is_pyspark_dataframe(self._item_df):
            # executor-side join: explode the knn pairs partition-wise and
            # run two real Spark equi-joins (reference knn.py:604-672) —
            # neither frame is ever collected to the driver
            from ..spark.adapter import spark_knn_join

            item_df, query_df_withid, knn_df = self.kneighbors(query_df)
            return spark_knn_join(
                item_df,
                query_df_withid,
                knn_df,
                id_col,
                distCol,
                drop_generated_id=not self.isDefined("idCol"),
            )
        # sparse-built DataFrames carry a placeholder features column (row
        # positions, not vectors; see DataFrame.from_numpy) — building join
        # structs from it would silently emit indices as "features"
        from ..dataframe import FEATURE_BLOCK_ATTR

        for df_ in (self._item_df, as_dataframe(query_df)):
            for part in df_.partitions:
                holder = part.attrs.get(FEATURE_BLOCK_ATTR)
                if holder is not None and any(
                    hasattr(b, "tocsr") for b in holder.blocks.values()
                ):
                    raise TypeError(
                        "exactNearestNeighborsJoin does not support "
                        "sparse-built DataFrames (their feature column is a "
                        "placeholder); densify the input first"
                    )
        item_df, query_df_withid, knn_df = self.kneighbors(query_df)
        item_pdf = item_df.toPandas().set_index(id_col, drop=False)
        query_pdf = query_df_withid.toPandas().set_index(id_col, drop=False)
        drop_generated = not self.isDefined("idCol")
        # fully vectorized explode: positional id->row maps + one
        # to_dict("records") per side (the per-element iterrows/.loc loop
        # this replaces was O(n*k) Python-object work — unusable at the
        # reference's scale, where the same result is two Spark joins,
        # knn.py:604-672)
        knn_pdf = knn_df.toPandas()
        cols = ["item_df", "query_df", distCol]
        if len(knn_pdf) == 0:
            return DataFrame.from_pandas(
                pd.DataFrame({c: [] for c in cols}), query_df_withid.num_partitions
            )
        qids = knn_pdf[f"query_{id_col}"].to_numpy()
        ind = np.asarray(knn_pdf["indices"].tolist())
        dist = np.asarray(knn_pdf["distances"].tolist(), dtype=np.float64)
        k = ind.shape[1]
        q_side = query_pdf.drop(columns=[id_col]) if drop_generated else query_pdf
        i_side = item_pdf.drop(columns=[id_col]) if drop_generated else item_pdf
        q_structs = q_side.iloc[query_pdf.index.get_indexer(qids)].to_dict("records")
        i_structs = i_side.iloc[
            item_pdf.index.get_indexer(ind.ravel())
        ].to_dict("records")
        out = pd.DataFrame(
            {
                "item_df": i_structs,
                # one struct per query, shared by its k join rows (same
                # sharing the per-row loop produced)
                "query_df": np.repeat(np.asarray(q_structs, dtype=object), k),
                distCol: dist.ravel(),
            }
        )
        return DataFrame.from_pandas(out, query_df_withid.num_partitions)

    def _get_tpu_transform_func(self, dataset):  # pragma: no cover
        raise NotImplementedError(
            "NearestNeighborsModel has no transform; use kneighbors instead."
        )

    def _ensure_staged_items(self, mesh, dtype=np.float32):
        """Device-resident prepared item index (ops.knn.PreparedItems) for
        the serving path — same staging helper as kneighbors, but an
        unstageable item set is a hard error here (an online server must
        never stream the index per batch), as is a pyspark-backed item
        frame (serving is in-process)."""
        from ..core import _is_pyspark_dataframe

        assert self._item_df is not None, "fit() must be called before serving"
        if _is_pyspark_dataframe(self._item_df):
            raise ValueError(
                "serving requires an in-process item frame; collect the "
                "pyspark item dataframe (SRML_SPARK_COLLECT=1) before "
                "registering the model"
            )
        prepared, _blocks, reason = self._stage_in_core_items(
            self.getIdCol(), dtype, mesh
        )
        if prepared is None:
            raise ValueError(f"{reason}; out-of-core indexes are kneighbors-only")
        return prepared

    def _serving_entry(self, mesh: Any = None):
        """Online inference hook (serving/): each coalesced batch is ONE
        knn_search_prepared call against the staged device-resident index.
        The engine's pow2 buckets feed the search's own >=64 query-block
        bucketing (_query_block_bucket), so warm_search_kernels covers every
        geometry the steady state dispatches."""
        from ..ops.knn import (
            _exchange_route,
            knn_search_prepared,
            warm_search_kernels,
        )
        from ..serving.entry import ServingEntry

        mesh = mesh or get_mesh(self.num_workers)
        dtype = np.dtype(np.float32)
        prepared = self._ensure_staged_items(mesh, dtype)
        dim = self._frame_dim(dtype)
        k = self.getK()

        def call(batch: np.ndarray) -> Dict[str, np.ndarray]:
            dists, ids = knn_search_prepared(prepared, batch, k, mesh)
            return {
                "indices": np.asarray(ids),
                "distances": np.asarray(dists, dtype=np.float32),
            }

        def warm(buckets) -> list:
            keys = []
            # distinct engine buckets can collapse onto one >=64 search
            # bucket; warm each resulting geometry once
            for b in sorted({max(int(x), 64) for x in buckets}):
                keys.extend(
                    warm_search_kernels(
                        prepared, k, mesh, n_queries=b, d_query=dim
                    )
                )
            return keys

        return ServingEntry(
            name="serve.knn",
            n_cols=int(dim),
            dtype=dtype,
            out_cols=["indices", "distances"],
            call=call,
            warm=warm,
            info={
                "k": int(min(k, prepared.n_items)),
                "n_items": int(prepared.n_items),
                # the CONFIGURED exact-exchange route for this mesh (per-
                # dispatch actuals land in knn.exchange_route.* counters)
                "exchange_route": _exchange_route(mesh),
            },
        )

    def write(self):
        raise NotImplementedError(
            "NearestNeighborsModel does not support saving/loading, just re-fit the estimator to re-create a model."
        )

    @classmethod
    def read(cls):
        raise NotImplementedError(
            "NearestNeighborsModel does not support saving/loading, just re-fit the estimator to re-create a model."
        )
