#
# LinearRegression estimator/model (OLS, Ridge, Lasso/ElasticNet).
#
# Capability parity with the reference's LinearRegression/
# LinearRegressionModel (/root/reference/python/src/spark_rapids_ml/
# regression.py:173-777): same Spark param mapping (:174-187), same value
# mapping for loss/solver (:189-205), same solver defaults (:207-221), same
# solver choice by (regParam, elasticNetParam) incl. the Spark-parity ridge
# alpha scaling (:499-556), single-pass fitMultiple (:588-605), model combine
# (:743-766) and single-pass transform-evaluate with RegressionMetrics
# (:85-168, :768-776).  The solver is sufficient-statistics + replicated
# solve/CD (ops/glm.py) instead of cuML MG classes — the data is read once
# for ALL param maps.
#

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

import jax

from ..core import FitInputs, _TpuEstimatorSupervised, _TpuModelWithPredictionCol
from ..dataframe import DataFrame, as_dataframe
from ..metrics.regression import RegressionMetrics, _SummarizerBuffer
from ..params import (
    HasElasticNetParam,
    HasFeaturesCol,
    HasFeaturesCols,
    HasFitIntercept,
    HasLabelCol,
    HasMaxIter,
    HasPredictionCol,
    HasRegParam,
    HasStandardization,
    HasTol,
    HasVerbose,
    HasWeightCol,
    Param,
    TypeConverters,
    _dummy,
    _TpuParams,
)
from ..ops.glm import (
    linear_predict_kernel,
    linreg_sufficient_stats,
    multi_linear_predict_kernel,
    solve_elasticnet_cd,
    solve_linear,
    sweep_linreg_fold_stats,
    sweep_solve_elasticnet_cd,
    sweep_solve_linear,
)
from ..utils import get_logger


class _RegressionModelEvaluationMixIn:
    """Single-pass transform+evaluate shared by LinearRegressionModel and
    RandomForestRegressionModel (reference regression.py:85-168)."""

    def _partition_metrics(
        self, part: Any, evaluator: Any, num_models: int, predict_all=None
    ) -> List[RegressionMetrics]:
        """One partition's per-model mergeable metric partials — shared by
        the local evaluate loop and the Spark executor UDF.  Callers looping
        over partitions pass a hoisted predict_all so the model arrays are
        device-staged once per evaluate, not once per partition."""
        from ..core import extract_partition_features

        input_col, input_cols = self._get_input_columns()
        dtype = self._transform_dtype(self._model_attributes.get("dtype"))
        feats = extract_partition_features(part, input_col, input_cols, dtype)
        labels = part[self.getOrDefault("labelCol")].to_numpy()
        if predict_all is None:
            predict_all = self._get_eval_predict_func()
        preds = predict_all(feats)  # (num_models, n)
        return [
            RegressionMetrics.from_arrays(labels, preds[i])
            for i in range(num_models)
        ]

    def _transform_evaluate(
        self, dataset: Any, evaluator: Any, num_models: int
    ) -> List[float]:
        from ..core import _use_executor_path
        from ..evaluation import RegressionEvaluator

        if not isinstance(evaluator, RegressionEvaluator):
            raise NotImplementedError(f"{evaluator} is unsupported yet.")
        if _use_executor_path(dataset):
            from ..spark.adapter import executor_transform_evaluate

            return executor_transform_evaluate(
                self, dataset, evaluator, num_models
            )
        df = as_dataframe(dataset)
        label_col = self.getOrDefault("labelCol")
        if label_col not in df.columns:
            raise RuntimeError("Label column is not existing.")
        predict_all = self._get_eval_predict_func()
        metrics: List[Optional[RegressionMetrics]] = [None] * num_models
        for part in df.partitions:
            if len(part) == 0:
                continue
            for i, m in enumerate(
                self._partition_metrics(part, evaluator, num_models, predict_all)
            ):
                metrics[i] = m if metrics[i] is None else metrics[i].merge(m)
        return [m.evaluate(evaluator) for m in metrics]  # type: ignore[union-attr]


def _host_intercept(
    coef64: np.ndarray, x_mean, y_mean, fit_intercept: bool
) -> float:
    """intercept = y_mean - x_mean . coef, derived on HOST in float64 from
    the replicated means.  Kept out of the solver kernels deliberately: the
    same 6-element f32 dot compiles with different fusion (fma) context in
    the solo-fit and lane-batched sweep programs and drifts a ulp, which is
    exactly the drift the sweep's batched == sequential exact-equality gate
    exists to forbid.  The host form is identical on both routes by
    construction (and slightly more precise)."""
    if not fit_intercept:
        return 0.0
    return float(
        np.asarray(y_mean, dtype=np.float64)
        - np.asarray(x_mean, dtype=np.float64) @ coef64
    )


class LinearRegressionClass(_TpuParams):
    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {
            "aggregationDepth": "",
            "elasticNetParam": "l1_ratio",
            "epsilon": "",
            "fitIntercept": "fit_intercept",
            "loss": "loss",
            "maxBlockSizeInMB": "",
            "maxIter": "max_iter",
            "regParam": "alpha",
            "solver": "solver",
            "standardization": "normalize",
            "tol": "tol",
            "weightCol": None,
        }

    @classmethod
    def _param_value_mapping(cls):
        return {
            "loss": lambda x: {
                "squaredError": "squared_loss",
                "squared_loss": "squared_loss",
            }.get(x),
            "solver": lambda x: {
                "auto": "eig",
                "normal": "eig",
                "eig": "eig",
            }.get(x),
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "algorithm": "eig",
            "fit_intercept": True,
            "normalize": False,
            "verbose": False,
            "alpha": 0.0001,
            "solver": "eig",
            "loss": "squared_loss",
            "l1_ratio": 0.15,
            "max_iter": 1000,
            "tol": 0.001,
            "shuffle": True,
        }


class _LinearRegressionParams(
    LinearRegressionClass,
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasPredictionCol,
    HasMaxIter,
    HasTol,
    HasRegParam,
    HasElasticNetParam,
    HasFitIntercept,
    HasStandardization,
    HasWeightCol,
    HasVerbose,
):
    # CSR input fits/transforms without densification via the ELL kernels
    # (ops/sparse.py: chunk-densified MXU Gram pass)
    _supports_sparse_input = True

    loss = Param(_dummy(), "loss", "the loss function to be optimized (squaredError)", TypeConverters.toString)
    solver = Param(_dummy(), "solver", "the solver algorithm (auto|normal|eig)", TypeConverters.toString)
    aggregationDepth = Param(_dummy(), "aggregationDepth", "suggested depth for treeAggregate", TypeConverters.toInt)
    epsilon = Param(_dummy(), "epsilon", "shape parameter of huber loss (unsupported loss)", TypeConverters.toFloat)
    maxBlockSizeInMB = Param(_dummy(), "maxBlockSizeInMB", "maximum memory in MB for stacking input data", TypeConverters.toFloat)

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(
            maxIter=100,
            regParam=0.0,
            elasticNetParam=0.0,
            tol=1e-6,
            loss="squaredError",
            solver="auto",
            standardization=True,
            aggregationDepth=2,
            epsilon=1.35,
            maxBlockSizeInMB=0.0,
        )

    def setMaxIter(self, value: int):
        return self._set_params(maxIter=value)

    def setRegParam(self, value: float):
        return self._set_params(regParam=value)

    def setElasticNetParam(self, value: float):
        return self._set_params(elasticNetParam=value)

    def setStandardization(self, value: bool):
        return self._set_params(standardization=value)

    def setTol(self, value: float):
        return self._set_params(tol=value)

    def setFitIntercept(self, value: bool):
        return self._set_params(fitIntercept=value)

    def setLossFunction(self, value: str):
        return self._set_params(loss=value)


class LinearRegression(_LinearRegressionParams, _TpuEstimatorSupervised):
    """Distributed linear regression on a TPU mesh.

    One fused pass computes the normal-equation statistics; OLS/Ridge solve
    closed-form, Lasso/ElasticNet run covariance-update coordinate descent —
    all param maps of a fitMultiple share the single data pass (the TPU
    formulation of the reference's single-load multi-fit,
    regression.py:588-605)."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._initialize_tpu_params()
        self._set_params(**kwargs)

    def _enable_fit_multiple_in_single_pass(self) -> bool:
        return True

    def _supportsTransformEvaluate(self, evaluator: Any) -> bool:
        from ..evaluation import RegressionEvaluator

        return isinstance(evaluator, RegressionEvaluator)

    def _get_tpu_fit_func(self, dataset: DataFrame, extra_params=None):
        logger = get_logger(type(self))

        def _single_fit(stats, params: Dict[str, Any], inputs: FitInputs) -> Dict[str, Any]:
            alpha = float(params["alpha"])
            l1_ratio = float(params["l1_ratio"])
            fit_intercept = bool(params["fit_intercept"])
            normalize = bool(params["normalize"])
            n_iter = None
            if alpha == 0.0 or l1_ratio == 0.0:
                # OLS ("eig") or Ridge with Spark-parity alpha*n scaling —
                # scaling handled inside solve_linear (reg = alpha * wsum)
                coef, _ = solve_linear(
                    stats, alpha, fit_intercept=fit_intercept, normalize=normalize
                )
            else:
                # n_iter joins the batched fetch below — int() here would
                # pay its own device round-trip
                coef, _, n_iter = solve_elasticnet_cd(
                    stats,
                    alpha,
                    l1_ratio,
                    fit_intercept=fit_intercept,
                    normalize=normalize,
                    max_iter=int(params["max_iter"]),
                    tol=float(params["tol"]),
                )
            # one batched device fetch (separate np.asarray/float coercions
            # each cost a host round-trip through the tunneled device)
            coef_h, xm_h, ym_h, n_iter_h = jax.device_get(
                (coef, stats.x_mean, stats.y_mean, n_iter)
            )
            if n_iter_h is not None:
                logger.info("CD sweeps: %d", int(n_iter_h))
            coef64 = np.asarray(coef_h, dtype=np.float64)
            return {
                "coef_": coef64,
                "intercept_": _host_intercept(coef64, xm_h, ym_h, fit_intercept),
                "n_cols": inputs.n_cols,
                "dtype": str(inputs.dtype),
            }

        def _fit(inputs: FitInputs, params: Dict[str, Any]):
            assert inputs.y is not None
            from ..ops.sparse import EllMatrix, ell_sufficient_stats

            if isinstance(inputs.X, EllMatrix):
                # CSR ingest: chunk-densify + MXU Gram pass, never the whole
                # matrix (ops/sparse.py); downstream solves are unchanged —
                # the sufficient statistics are dense either way
                stats = ell_sufficient_stats(
                    inputs.X, inputs.y, inputs.weight, mesh=inputs.mesh
                )
            else:
                stats = linreg_sufficient_stats(
                    inputs.X, inputs.y, inputs.weight, mesh=inputs.mesh
                )
            if extra_params:
                results = []
                for override in extra_params:
                    p = dict(params)
                    p.update(override)
                    results.append(_single_fit(stats, p, inputs))
                return results
            return _single_fit(stats, params, inputs)

        return _fit

    def _create_model(self, result: Dict[str, Any]) -> "LinearRegressionModel":
        return LinearRegressionModel(**result)

    def streaming(self):
        """Streaming incremental-fit engine over this configured estimator:
        mergeable Gram-moment accumulation finalized through the SAME
        solve kernels as the batch fit (streamed == batch bitwise on the
        exact-arithmetic data families) — partial_fit/merge/finalize
        (srml-stream, docs/streaming.md)."""
        from ..stream.engines import StreamingLinearRegression

        return StreamingLinearRegression(self)

    # -- batched hyperparameter sweep (srml-sweep) -------------------------
    def _supportsBatchedSweep(self, df, paramMaps, evaluator) -> bool:
        if not paramMaps or not self._supportsTransformEvaluate(evaluator):
            return False
        try:
            overrides = [self._paramMap_to_tpu_overrides(pm) for pm in paramMaps]
        except ValueError:
            # unsupported value: let the legacy loop raise its own error
            return False
        if any(set(ov) - {"alpha", "l1_ratio"} for ov in overrides):
            return False  # only the regularizer axes batch as lanes
        return not self._sweep_sparse_input(df)

    def _fitBatchedSweep(self, df, paramMaps, n_folds, seed):
        """All n_folds x len(paramMaps) linreg fits as a fused masked-fold
        stats pass + one stacked-lane solve dispatch per solver family over
        the ONE staged dataset (ops/glm.py sweep kernels; exact-equality
        contract in docs/tuning_engine.md)."""
        from .. import profiling
        from ..core import _maybe_x64
        from ..ops import sweep as sweep_ops
        from ..sanitize import sanitize_scope

        input_col, input_cols = self._get_input_columns()
        params = dict(self._tpu_params)
        cand = []
        for pm in paramMaps:
            p = dict(params)
            p.update(self._paramMap_to_tpu_overrides(pm))
            cand.append((float(p["alpha"]), float(p["l1_ratio"])))
        fit_intercept = bool(params["fit_intercept"])
        normalize = bool(params["normalize"])
        statics = {"fit_intercept": fit_intercept, "normalize": normalize}
        # same solver choice per candidate as _single_fit: OLS/Ridge closed
        # form when the L1 term vanishes, covariance-update CD otherwise
        closed = [i for i, (a, l1r) in enumerate(cand) if a == 0.0 or l1r == 0.0]
        cd = [i for i in range(len(cand)) if i not in closed]
        with _maybe_x64(self._use_dtype(df, input_col, input_cols)):
            with profiling.phase("srml.ingest"):
                inputs = self._build_fit_inputs(df)
            assert inputs.y is not None
            mesh = inputs.mesh
            fid = sweep_ops.stage_fold_ids(
                inputs.n_rows, inputs.X.shape[0], n_folds, seed, mesh
            )
            # warm the solve kernels at sweep entry: their lowerings are
            # known from shapes alone (stacked stats are mesh-replicated),
            # so they compile on the pool WHILE the stats pass runs
            compute_dt = np.dtype(inputs.dtype)
            if compute_dt in (np.dtype(np.float32), np.dtype(np.float64)):
                d = inputs.n_cols
                aval = lambda shape: sweep_ops.replicated_aval(  # noqa: E731
                    shape, compute_dt, mesh
                )
                from ..ops.glm import LinregStats

                stats_avals = LinregStats(
                    wsum=aval((n_folds,)),
                    x_mean=aval((n_folds, d)),
                    y_mean=aval((n_folds,)),
                    G=aval((n_folds, d, d)),
                    c=aval((n_folds, d)),
                    y2=aval((n_folds,)),
                )
                entries = []
                if closed:
                    mb = sweep_ops.candidate_bucket(len(closed))
                    entries.append(
                        (
                            "sweep.linreg.solve",
                            sweep_solve_linear,
                            (stats_avals, aval((mb,))),
                            dict(statics),
                        )
                    )
                if cd:
                    mb = sweep_ops.candidate_bucket(len(cd))
                    entries.append(
                        (
                            "sweep.linreg.cd",
                            sweep_solve_elasticnet_cd,
                            (stats_avals, aval((mb,)), aval((mb,)), aval(())),
                            dict(statics, max_iter=int(params["max_iter"])),
                        )
                    )
                sweep_ops.warm(entries, mesh=mesh)
            with sanitize_scope():
                with profiling.span(
                    "tuning.sweep.stats", folds=n_folds, rows=inputs.n_rows
                ):
                    stats = sweep_ops.dispatch(
                        "sweep.linreg.stats",
                        sweep_linreg_fold_stats,
                        inputs.X,
                        inputs.y,
                        inputs.weight,
                        fid,
                        mesh=mesh,
                        k=n_folds,
                    )
                results: List[List[Dict[str, Any]]] = [
                    [None] * len(cand) for _ in range(n_folds)  # type: ignore[list-item]
                ]
                xm_h, ym_h = jax.device_get((stats.x_mean, stats.y_mean))

                def _collect(idxs, coef_h, n_iter_h=None):
                    for j, i in enumerate(idxs):
                        for f in range(n_folds):
                            coef64 = np.asarray(coef_h[f, j], dtype=np.float64)
                            results[f][i] = {
                                "coef_": coef64,
                                # same host float64 derivation as _single_fit
                                # (see _host_intercept): bit-equal across the
                                # batched and sequential routes
                                "intercept_": _host_intercept(
                                    coef64, xm_h[f], ym_h[f], fit_intercept
                                ),
                                "n_cols": inputs.n_cols,
                                "dtype": str(inputs.dtype),
                            }
                    if n_iter_h is not None:
                        get_logger(type(self)).info(
                            "sweep CD sweeps (fold x candidate): %s",
                            np.asarray(n_iter_h)[:, : len(idxs)].tolist(),
                        )

                with profiling.span(
                    "tuning.sweep.solve", candidates=len(cand), folds=n_folds
                ):
                    if closed:
                        _, (alphas,) = sweep_ops.pack_lane_subset(cand, closed)
                        coef, _ = sweep_ops.dispatch(
                            "sweep.linreg.solve",
                            sweep_solve_linear,
                            stats,
                            alphas,
                            mesh=mesh,
                            **statics,
                        )
                        _collect(closed, jax.device_get(coef))
                    if cd:
                        _, (alphas, l1s) = sweep_ops.pack_lane_subset(
                            cand, cd, fields=(0, 1)
                        )
                        tol = jax.numpy.asarray(
                            np.float64(float(params["tol"]))
                        )
                        coef, _, n_iter = sweep_ops.dispatch(
                            "sweep.linreg.cd",
                            sweep_solve_elasticnet_cd,
                            stats,
                            alphas,
                            l1s,
                            tol,
                            mesh=mesh,
                            max_iter=int(params["max_iter"]),
                            **statics,
                        )
                        coef_h, n_iter_h = jax.device_get((coef, n_iter))
                        _collect(cd, coef_h, n_iter_h)
        return results


class LinearRegressionModel(
    _LinearRegressionParams, _RegressionModelEvaluationMixIn, _TpuModelWithPredictionCol
):
    def __init__(
        self,
        coef_: Union[np.ndarray, List],
        intercept_: Union[float, List[float]],
        n_cols: int,
        dtype: str,
    ) -> None:
        super().__init__(
            coef_=np.asarray(coef_), intercept_=intercept_, n_cols=int(n_cols), dtype=str(dtype)
        )
        self.coef_ = np.asarray(coef_)
        self.intercept_ = intercept_
        self.n_cols = int(n_cols)
        self.dtype = str(dtype)

    @property
    def _num_models(self) -> int:
        return len(self.intercept_) if isinstance(self.intercept_, (list, np.ndarray)) and self.coef_.ndim == 2 else 1

    @property
    def coefficients(self) -> np.ndarray:
        assert self._num_models == 1
        return self.coef_

    @property
    def intercept(self) -> float:
        assert self._num_models == 1
        return float(self.intercept_)

    @property
    def scale(self) -> float:
        """huber loss unsupported: constant 1.0 for API compatibility
        (reference regression.py:693-697)."""
        return 1.0

    @property
    def hasSummary(self) -> bool:
        return False

    def predict(self, value: np.ndarray) -> float:
        np_dtype = self._transform_dtype(self.dtype)
        x = np.asarray(value, dtype=np_dtype)
        return float(
            linear_predict_kernel(
                jax.numpy.asarray(x[None, :]),
                jax.numpy.asarray(self.coef_.astype(np_dtype)),
                jax.numpy.asarray(np_dtype.type(self.intercept_)),
            )[0]
        )

    def cpu(self):
        from ..spark.interop import to_spark_linear_model

        return to_spark_linear_model(self)

    def _get_tpu_transform_func(self, dataset: DataFrame):
        assert self._num_models == 1, "transform() on a combined multi-model is unsupported; use _transformEvaluate"
        np_dtype = self._transform_dtype(self.dtype)
        coef = jax.device_put(np.asarray(self.coef_, dtype=np_dtype))
        intercept = jax.numpy.asarray(np_dtype.type(self.intercept_))
        pred_col = self.getOrDefault("predictionCol")

        def _transform(features: np.ndarray) -> Dict[str, Any]:
            if hasattr(features, "tocsr"):  # CSR partition -> device ELL
                from ..ops.sparse import ell_device_from_scipy

                Xd = ell_device_from_scipy(features, np_dtype)
            else:
                Xd = jax.device_put(np.asarray(features, dtype=np_dtype))
            preds = linear_predict_kernel(Xd, coef, intercept)
            return {pred_col: np.asarray(preds, dtype=np.float64)}

        return _transform

    def _serving_entry(self, mesh: Any = None):
        """Online inference hook (serving/): the dense Xw + b prediction as
        one bucket-padded kernel through the AOT executable cache (serving
        requests arrive as dense rows; sparse bulk scoring stays on the
        batch transform path)."""
        assert self._num_models == 1, "combined multi-models are not servable"
        from ..serving.entry import kernel_entry

        np_dtype = self._transform_dtype(self.dtype)
        coef = jax.device_put(np.asarray(self.coef_, dtype=np_dtype))
        intercept = jax.numpy.asarray(np_dtype.type(self.intercept_))
        pred_col = self.getOrDefault("predictionCol")
        return kernel_entry(
            "serve.linreg",
            linear_predict_kernel,  # module-level @jax.jit
            (coef, intercept),
            {},
            lambda preds: {pred_col: np.asarray(preds, dtype=np.float64)},
            dtype=np_dtype,
            n_cols=self.n_cols,
            out_cols=[pred_col],
        )

    def _lane_entry(self, mesh: Any = None):
        """Multiplexed serving hook (serving/multiplex): this model's
        (coef, intercept) as ONE lane of a lane-stacked GLM predict — K
        same-shape variants share one lane_linear_predict_kernel dispatch
        per micro-batch, bitwise-equal per tenant to the dedicated entry
        above on integer-exact data."""
        assert self._num_models == 1, "combined multi-models are not servable"
        from ..ops.glm import lane_linear_predict_kernel
        from ..serving.multiplex import LaneEntry

        np_dtype = self._transform_dtype(self.dtype)
        coef = np.ascontiguousarray(np.asarray(self.coef_, dtype=np_dtype))
        intercept = np.asarray(np_dtype.type(self.intercept_))
        pred_col = self.getOrDefault("predictionCol")
        return LaneEntry(
            name="lanes.linreg",
            n_cols=self.n_cols,
            dtype=np_dtype,
            out_cols=[pred_col],
            leaves=(coef, intercept),
            kernel=lane_linear_predict_kernel,
            statics={},
            postprocess=lambda preds: {pred_col: np.asarray(preds, dtype=np.float64)},
        )

    def _get_eval_predict_func(self) -> Callable[[np.ndarray], np.ndarray]:
        np_dtype = self._transform_dtype(self.dtype)
        coefs = np.atleast_2d(np.asarray(self.coef_, dtype=np_dtype))
        intercepts = np.atleast_1d(np.asarray(self.intercept_, dtype=np_dtype))

        def _predict_all(feats: np.ndarray) -> np.ndarray:
            return np.asarray(
                multi_linear_predict_kernel(
                    jax.device_put(np.asarray(feats, dtype=np_dtype)),
                    jax.numpy.asarray(coefs),
                    jax.numpy.asarray(intercepts),
                ),
                dtype=np.float64,
            )

        return _predict_all

    @classmethod
    def _combine(cls, models: List["LinearRegressionModel"]) -> "LinearRegressionModel":
        assert models and all(isinstance(m, cls) for m in models)
        first = models[0]
        combined = cls(
            coef_=np.stack([np.asarray(m.coef_) for m in models]),
            intercept_=[float(m.intercept_) for m in models],
            n_cols=first.n_cols,
            dtype=first.dtype,
        )
        first._copyValues(combined)
        combined._tpu_params.update(first._tpu_params)
        combined._float32_inputs = first._float32_inputs
        return combined

    def _transformEvaluate(self, dataset: Any, evaluator: Any, params=None) -> List[float]:
        return self._transform_evaluate(dataset, evaluator, self._num_models)
