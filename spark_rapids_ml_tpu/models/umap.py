#
# UMAP estimator/model.
#
# Capability parity with the reference's UMAP/UMAPModel
# (/root/reference/python/src/spark_rapids_ml/umap.py:88-1321): the same 17
# solver params (umap.py:95-115) plus sample_fraction (umap.py:332-341), fit
# on (optionally sampled) data with the model carrying embedding_ + raw
# training data for transform (umap.py:831-910), and distributed transform
# that projects each batch against the broadcast model (umap.py:1147-1224).
# Supervised fit (labelCol set -> categorical simplicial-set intersection,
# the reference's y= branch at umap.py:939-947) is supported.
# Differences by design: the kNN graph is built by the mesh-distributed
# exact kNN kernel instead of single-GPU cuML, so fit itself scales across
# the mesh; graph assembly and the SGD layout epochs are mesh-parallel too
# (on-device symmetrize/dedupe/pad + head-block-sharded scan-batched
# epochs, ops/umap.py / docs/umap_engine.md — fixed seed gives the same
# embedding on any mesh shape); "spectral" init is the Laplacian eigenmap
# of the fuzzy graph;
# transform initializes at the weighted neighbor mean then runs the
# n_epochs//3 (or 100/30) SGD refinement epochs against the frozen training
# embedding, as cuml/umap-learn transform does.
#

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

import numpy as np

from ..core import FitInputs, _TpuEstimator, _TpuModel
from ..dataframe import DataFrame, as_dataframe
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasOutputCol,
    Param,
    TypeConverters,
    _dummy,
    _TpuParams,
)
from ..parallel.mesh import get_mesh
from ..ops.knn import knn_search
from ..ops.umap import (
    find_ab_params,
    umap_fit_embedding,
    umap_transform_embedding,
)
from ..utils import get_logger


def _umap_ann_mode() -> str:
    """SRML_UMAP_ANN routes the graph phase's kNN self-join: "" (default)
    keeps the exact engine; "ivfflat" uses the srml-ann IVF-Flat engine."""
    import os

    mode = os.environ.get("SRML_UMAP_ANN", "")
    if mode not in ("", "ivfflat"):
        raise ValueError(
            f"SRML_UMAP_ANN={mode!r} is not supported (only 'ivfflat')"
        )
    return mode


def _ann_self_join(X: np.ndarray, k: int, mesh, seed: int):
    """(dists, ids) kNN self-join via the IVF-Flat engine (ann/ivfflat.py).
    nlist defaults to sqrt(n); nprobe defaults to HALF the lists — the
    graph phase feeds the layout's attraction edges, so it trades less
    speedup for recall headroom vs the serving default (a quarter).  Env
    overrides: SRML_UMAP_ANN_NLIST / SRML_UMAP_ANN_NPROBE."""
    import os

    from ..ann.ivfflat import (
        build_ivfflat_packed,
        default_nlist,
        index_from_packed,
        ivfflat_search_prepared,
    )

    n = X.shape[0]
    nlist = int(os.environ.get("SRML_UMAP_ANN_NLIST", 0)) or default_nlist(n)
    nprobe = int(os.environ.get("SRML_UMAP_ANN_NPROBE", 0)) or max(
        8, nlist // 2
    )
    packed = build_ivfflat_packed(
        X, np.arange(n, dtype=np.int64), nlist, seed=seed
    )
    index = index_from_packed(packed, mesh)
    dists, ids = ivfflat_search_prepared(
        index, X, k, nprobe, mesh, query_block=32768
    )
    if (ids < 0).any():
        # the graph assembly consumes ids as dense row indices; a -1
        # unfillable slot (probed lists held < k candidates for some row)
        # must fail loudly, not gather garbage edges
        raise RuntimeError(
            "IVF-Flat self-join returned unfillable neighbor slots at "
            f"nlist={nlist} nprobe={nprobe}; raise SRML_UMAP_ANN_NPROBE "
            "(or unset SRML_UMAP_ANN to use the exact graph)"
        )
    return dists, ids


class UMAPClass(_TpuParams):
    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        # identity mapping: the reference exposes the solver params directly
        # as Spark Params (umap.py:121-603), so any route that sets the Spark
        # Param (copy(extra), tuning param maps, set()) must reach the solver
        # dict too
        return {
            name: name
            for name in (
                "n_neighbors",
                "n_components",
                "metric",
                "n_epochs",
                "learning_rate",
                "init",
                "min_dist",
                "spread",
                "set_op_mix_ratio",
                "local_connectivity",
                "repulsion_strength",
                "negative_sample_rate",
                "transform_queue_size",
                "a",
                "b",
                "random_state",
            )
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_neighbors": 15,
            "n_components": 2,
            "metric": "euclidean",
            "n_epochs": None,
            "learning_rate": 1.0,
            "init": "spectral",
            "min_dist": 0.1,
            "spread": 1.0,
            "set_op_mix_ratio": 1.0,
            "local_connectivity": 1.0,
            "repulsion_strength": 1.0,
            "negative_sample_rate": 5,
            "transform_queue_size": 4.0,
            "a": None,
            "b": None,
            "precomputed_knn": None,
            "random_state": None,
            "verbose": False,
        }


class _UMAPParams(UMAPClass, HasFeaturesCol, HasFeaturesCols, HasLabelCol, HasOutputCol):
    n_neighbors = Param(_dummy(), "n_neighbors", "size of the local neighborhood", TypeConverters.toFloat)
    n_components = Param(_dummy(), "n_components", "dimension of the embedded space", TypeConverters.toInt)
    metric = Param(_dummy(), "metric", "distance metric (euclidean)", TypeConverters.toString)
    n_epochs = Param(_dummy(), "n_epochs", "number of optimization epochs", TypeConverters.toInt)
    learning_rate = Param(_dummy(), "learning_rate", "initial embedding learning rate", TypeConverters.toFloat)
    init = Param(_dummy(), "init", "low-dim initialization (spectral|random)", TypeConverters.toString)
    min_dist = Param(_dummy(), "min_dist", "minimum embedded point distance", TypeConverters.toFloat)
    spread = Param(_dummy(), "spread", "scale of the embedded points", TypeConverters.toFloat)
    set_op_mix_ratio = Param(_dummy(), "set_op_mix_ratio", "fuzzy union vs intersection mix", TypeConverters.toFloat)
    local_connectivity = Param(_dummy(), "local_connectivity", "local connectivity (nearest assumed-connected neighbors)", TypeConverters.toFloat)
    repulsion_strength = Param(_dummy(), "repulsion_strength", "weight of negative samples", TypeConverters.toFloat)
    negative_sample_rate = Param(_dummy(), "negative_sample_rate", "negative samples per positive", TypeConverters.toInt)
    transform_queue_size = Param(_dummy(), "transform_queue_size", "transform search queue factor", TypeConverters.toFloat)
    a = Param(_dummy(), "a", "embedding curve parameter a", TypeConverters.toFloat)
    b = Param(_dummy(), "b", "embedding curve parameter b", TypeConverters.toFloat)
    random_state = Param(_dummy(), "random_state", "random seed", TypeConverters.toInt)
    sample_fraction = Param(_dummy(), "sample_fraction", "fraction of rows used for fit (umap.py:332-341)", TypeConverters.toFloat)

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(sample_fraction=1.0, outputCol="embedding")

    def getSampleFraction(self) -> float:
        return self.getOrDefault("sample_fraction")

    def setSampleFraction(self, value: float):
        return self._set_params(sample_fraction=value)

    def setOutputCol(self, value: str):
        return self._set_params(outputCol=value)

    def setFeaturesCol(self, value: Union[str, List[str]]):
        if isinstance(value, str):
            self._set_params(featuresCol=value)
        else:
            self._set_params(featuresCols=value)
        return self


class UMAP(_UMAPParams, _TpuEstimator):
    """UMAP on a TPU mesh: exact mesh-distributed kNN graph, vectorized
    fuzzy-set calibration, one-jit SGD layout."""

    # single-node fit by design (reference umap.py:831-850 coalesces to one
    # partition); the fit func host-fetches the whole dataset.  On a >1-worker
    # Spark cluster the adapter degrades to the reference semantics — sample
    # with Spark, fit in a single barrier task, keep inference distributed —
    # instead of erroring (spark/adapter.barrier_fit_estimator).
    _supports_multicontroller_fit = False
    _cluster_fit_single_task = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._initialize_tpu_params()
        self._set_params(**kwargs)

    def _fit_label_col(self):
        # optionally supervised (reference umap.py:722-724, 939-947):
        # labels are consumed only when the user set labelCol explicitly
        return self.getOrDefault("labelCol") if self.isSet("labelCol") else None

    def _get_tpu_fit_func(self, dataset: DataFrame, extra_params=None):
        logger = get_logger(type(self))
        sample_fraction = self.getSampleFraction()

        def _fit(inputs: FitInputs, params: Dict[str, Any]):
            import jax as _jax

            valid = np.asarray(inputs.weight) > 0
            seed = params.get("random_state")
            seed = int(seed) & 0x7FFFFFFF if seed is not None else 42
            # device fast path: a from_device frame with no padding and no
            # sampling never round-trips the feature array through the
            # host link (the np.asarray fetch was 25 MB per fit at the
            # bench shape, 0.3-0.6 s under tunnel congestion) — the kNN
            # self-join consumes the device handle and raw_data_ stays a
            # device array until save/serialize materializes it
            # device-resident frame with no padding/sampling: the kNN
            # self-join consumes the device handle for ANY dtype
            # (prepare_items casts on device).  raw_data_ additionally
            # stays a device array only for f32 frames — a bf16/f16
            # frame would need a full-size f32 device COPY (doubling
            # HBM), so those fetch raw_data_ to the host as before.
            # Trade of the f32 path: raw_data_ IS the frame's array (no
            # extra HBM, no fetch) and stays resident while the model is
            # alive; save/serialize materializes a host copy on demand.
            device_search = (
                isinstance(inputs.X, _jax.Array)
                and sample_fraction >= 1.0
                and int(valid.sum()) == inputs.X.shape[0]
            )
            device_fast = (
                device_search and inputs.X.dtype == _jax.numpy.float32
            )
            if device_fast:
                X: Any = inputs.X
                y = np.asarray(inputs.y)[valid] if inputs.y is not None else None
            else:
                X = np.asarray(inputs.X)[valid]
                y = np.asarray(inputs.y)[valid] if inputs.y is not None else None
                if sample_fraction < 1.0:
                    rng = np.random.default_rng(seed)
                    keep = rng.random(X.shape[0]) < sample_fraction
                    X = X[keep]
                    y = y[keep] if y is not None else None
            n = X.shape[0]
            if n == 0:
                raise RuntimeError(
                    "UMAP fit received 0 rows after sampling "
                    f"(sample_fraction={sample_fraction}); increase "
                    "sample_fraction or the dataset size"
                )
            k = int(min(params["n_neighbors"], n))
            mesh = get_mesh(self.num_workers)
            if params.get("precomputed_knn") is not None:
                # (knn_indices, knn_dists) as in cuML's precomputed_knn
                # (reference umap.py:95-115 param list)
                pre_ids, pre_dists = params["precomputed_knn"]
                ids = np.asarray(pre_ids)[:, :k]
                dists = np.asarray(pre_dists)[:, :k]
                if ids.shape[0] != n:
                    raise ValueError(
                        f"precomputed_knn has {ids.shape[0]} rows but the "
                        f"(sampled) training set has {n}"
                    )
            elif _umap_ann_mode() == "ivfflat":
                # Opt-in (SRML_UMAP_ANN=ivfflat): the graph self-join runs
                # through the IVF-Flat engine instead of the exact scan —
                # sub-linear in n, gated by the k=15 neighbor-preservation
                # test within the established 1% tolerance of the exact-
                # graph reference layout (tests/test_umap_engine.py).
                # SRML_UMAP_ANN_NLIST / SRML_UMAP_ANN_NPROBE override the
                # defaults (sqrt(n) lists, half of them probed — the graph
                # phase needs higher recall than online serving, so the
                # default probes deeper than ann.default_nprobe).
                dists, ids = _ann_self_join(
                    np.asarray(X, np.float32), k, mesh, seed
                )
            else:
                # query_block 32768: the graph build is a self-join of many
                # small-k blocks whose per-block host round-trips (through
                # the tunneled device) dominate — 2 blocks at 50k beats 7.
                # When no row was filtered (no padding, no sampling) the
                # search consumes the DEVICE-resident FitInputs.X directly
                # instead of round-tripping it through the host link.
                search_X: Any = inputs.X if device_search else X
                dists, ids = knn_search(
                    search_X, np.arange(n, dtype=np.int64), search_X, k,
                    mesh, query_block=32768,
                )
            a, b = params.get("a"), params.get("b")
            if a is None or b is None:
                a, b = find_ab_params(
                    float(params["spread"]), float(params["min_dist"])
                )
            logger.info("UMAP graph built: n=%d k=%d (a=%.3f b=%.3f)", n, k, a, b)
            # the same mesh that served the kNN self-join drives the
            # sharded layout epochs: each device owns a head block of the
            # padded edge layout (ops/umap.optimize_layout_sharded)
            embedding = umap_fit_embedding(
                ids,
                dists,
                n_components=int(params["n_components"]),
                a=a,
                b=b,
                n_epochs=params.get("n_epochs"),
                learning_rate=float(params["learning_rate"]),
                init=str(params["init"]),
                set_op_mix_ratio=float(params["set_op_mix_ratio"]),
                local_connectivity=float(params["local_connectivity"]),
                repulsion_strength=float(params["repulsion_strength"]),
                negative_sample_rate=int(params["negative_sample_rate"]),
                seed=seed,
                y=y,
                mesh=mesh,
            )
            return {
                "embedding_": embedding.astype(np.float32),
                "raw_data_": X.astype(np.float32),
                "n_cols": inputs.n_cols,
                "dtype": str(inputs.dtype),
            }

        return _fit

    def _create_model(self, result: Dict[str, Any]) -> "UMAPModel":
        return UMAPModel(**result)


class UMAPModel(_UMAPParams, _TpuModel):
    def __init__(
        self,
        embedding_: np.ndarray,
        raw_data_: np.ndarray,
        n_cols: int,
        dtype: str,
    ) -> None:
        import jax as _jax

        # raw_data_ may arrive as a DEVICE array (the from_device fit fast
        # path): keep the handle — transform's prepare_items consumes it
        # on device, and _get_model_attributes materializes a host copy
        # only when persistence/serialization actually needs one
        raw = (
            raw_data_
            if isinstance(raw_data_, _jax.Array)
            else np.asarray(raw_data_)
        )
        super().__init__(
            embedding_=np.asarray(embedding_),
            raw_data_=raw,
            n_cols=int(n_cols),
            dtype=str(dtype),
        )
        self.embedding_ = np.asarray(embedding_)
        self.raw_data_ = raw
        self.n_cols = int(n_cols)
        self.dtype = str(dtype)

    def _get_model_attributes(self) -> Dict[str, Any]:
        attrs = self._model_attributes
        if not isinstance(attrs.get("raw_data_"), np.ndarray):
            # materialize the device-resident training set on first
            # save/serialize; cached so repeat saves fetch once
            attrs["raw_data_"] = np.asarray(attrs["raw_data_"])
            self.raw_data_ = attrs["raw_data_"]
        return attrs

    @property
    def embedding(self) -> np.ndarray:
        return self.embedding_

    def _out_columns(self) -> List[str]:
        return [self.getOrDefault("outputCol")]

    def _get_tpu_transform_func(self, dataset: DataFrame):
        out_col = self.getOrDefault("outputCol")
        p = self._tpu_params
        k = int(min(p.get("n_neighbors", 15), self.raw_data_.shape[0]))
        local_connectivity = float(p.get("local_connectivity", 1.0))
        a, b = p.get("a"), p.get("b")
        if a is None or b is None:
            a, b = find_ab_params(
                float(p.get("spread", 1.0)), float(p.get("min_dist", 0.1))
            )
        seed = p.get("random_state")
        mesh = get_mesh(self.num_workers)
        from ..ops.knn import knn_search_prepared, prepare_items

        # shard the training set + upload the embedding to device ONCE;
        # reused by every partition
        prepared = prepare_items(
            self.raw_data_,
            np.arange(self.raw_data_.shape[0], dtype=np.int64),
            mesh,
        )
        import jax.numpy as jnp

        emb_f32 = self.embedding_.astype(np.float32)
        emb_dev = jnp.asarray(emb_f32)

        def _transform(features: np.ndarray) -> Dict[str, Any]:
            dists, ids = knn_search_prepared(prepared, features, k, mesh)
            emb = umap_transform_embedding(
                ids,
                dists,
                emb_f32,
                local_connectivity,
                train_embedding_dev=emb_dev,
                a=a,
                b=b,
                n_epochs=p.get("n_epochs"),
                learning_rate=float(p.get("learning_rate", 1.0)),
                repulsion_strength=float(p.get("repulsion_strength", 1.0)),
                negative_sample_rate=int(p.get("negative_sample_rate", 5)),
                seed=int(seed) & 0x7FFFFFFF if seed is not None else 42,
            )
            return {out_col: emb.astype(np.float64)}

        return _transform
