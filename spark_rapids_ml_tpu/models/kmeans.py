#
# KMeans estimator/model.
#
# Capability parity with the reference's KMeans/KMeansModel
# (/root/reference/python/src/spark_rapids_ml/clustering.py:59-466): same
# Spark param mapping (clustering.py:61-82), same solver defaults
# (clustering.py:84-95), same model attributes (cluster_centers_, n_cols,
# dtype) and int prediction output (clustering.py:430-433).  The solver is
# the TPU-native shard_map Lloyd kernel in ops/kmeans.py instead of cuML
# KMeansMG over NCCL.
#

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

import numpy as np

import jax

from ..core import FitInputs, _TpuEstimator, _TpuModelWithPredictionCol
from ..dataframe import DataFrame
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasMaxIter,
    HasPredictionCol,
    HasSeed,
    HasTol,
    HasVerbose,
    HasWeightCol,
    Param,
    TypeConverters,
    _dummy,
    _TpuParams,
)
from ..ops.kmeans import (
    kmeans_predict_kernel,
    lloyd_iterations,
    random_init,
    scalable_kmeans_pp_init,
)
from ..utils import get_logger


class KMeansClass(_TpuParams):
    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        # mirrors clustering.py:61-82: distanceMeasure/weightCol unsupported,
        # initSteps/solver/maxBlockSizeInMB silently ignored
        return {
            "distanceMeasure": None,
            "initMode": "init",
            "k": "n_clusters",
            "initSteps": "",
            "maxIter": "max_iter",
            "seed": "random_state",
            "tol": "tol",
            "weightCol": None,
            "solver": "",
            "maxBlockSizeInMB": "",
        }

    @classmethod
    def _param_value_mapping(cls):
        return {
            "init": lambda v: {
                "k-means||": "scalable-k-means++",
                "random": "random",
                "scalable-k-means++": "scalable-k-means++",
            }.get(v)
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_clusters": 8,
            "max_iter": 300,
            "tol": 0.0001,
            "verbose": False,
            "random_state": 1,
            "init": "scalable-k-means++",
            "n_init": 1,
            "oversampling_factor": 2.0,
            "max_samples_per_batch": 32768,
        }


class _KMeansParams(
    KMeansClass,
    HasFeaturesCol,
    HasFeaturesCols,
    HasPredictionCol,
    HasMaxIter,
    HasTol,
    HasSeed,
    HasWeightCol,
    HasVerbose,
):
    k = Param(_dummy(), "k", "The number of clusters to create. Must be > 1.", TypeConverters.toInt)
    initMode = Param(
        _dummy(),
        "initMode",
        'The initialization algorithm. Supported options: "random" and "k-means||".',
        TypeConverters.toString,
    )
    initSteps = Param(
        _dummy(), "initSteps", "The number of steps for k-means|| initialization mode. Must be > 0.", TypeConverters.toInt
    )
    distanceMeasure = Param(
        _dummy(), "distanceMeasure", "the distance measure", TypeConverters.toString
    )

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(
            k=2, initMode="k-means||", initSteps=2, maxIter=20, tol=0.0001
        )

    def getK(self) -> int:
        return self.getOrDefault("k")

    def setK(self, value: int):
        return self._set_params(k=value)

    def setInitMode(self, value: str):
        return self._set_params(initMode=value)

    def setMaxIter(self, value: int):
        return self._set_params(maxIter=value)

    def setTol(self, value: float):
        return self._set_params(tol=value)

    def setSeed(self, value: int):
        return self._set_params(seed=value)

    def setWeightCol(self, value: str):
        # parity with clustering.py setWeightCol: unsupported
        raise ValueError("'weightCol' is not supported.")


class KMeans(_KMeansParams, _TpuEstimator):
    """Distributed KMeans on a TPU mesh (Lloyd + k-means|| init), API-parity
    with the reference KMeans (clustering.py:146-308)."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._initialize_tpu_params()
        self._set_params(**kwargs)

    def _get_tpu_fit_func(self, dataset: DataFrame, extra_params=None):
        logger = get_logger(type(self))

        def _fit(inputs: FitInputs, params: Dict[str, Any]):
            k = int(params["n_clusters"])
            seed = int(params["random_state"]) & 0x7FFFFFFF
            chunk = min(int(params["max_samples_per_batch"]), inputs.X.shape[0])
            if params["init"] == "random":
                centers0 = random_init(inputs.X, inputs.weight, k, seed)
            else:
                oversample = float(params["oversampling_factor"])
                round_size = max(1, min(int(oversample * k), inputs.n_rows))
                centers0 = scalable_kmeans_pp_init(
                    inputs.X,
                    inputs.weight,
                    k,
                    seed,
                    oversample,
                    rounds=4,
                    round_size=round_size,
                )
            centers, n_iter, inertia = lloyd_iterations(
                inputs.X,
                inputs.weight,
                centers0,
                inputs.mesh,
                int(params["max_iter"]),
                float(params["tol"]),
                chunk,
            )
            # ONE batched device fetch: int()/float()/np.asarray each cost
            # a host round-trip through the tunneled device (~30-100 ms
            # apiece), and centers/n_iter/inertia are ready together
            centers_h, n_iter_h, inertia_h = jax.device_get(
                (centers, n_iter, inertia)
            )
            logger.info(
                "iterations: %d, inertia: %f", int(n_iter_h), float(inertia_h)
            )
            return {
                "cluster_centers_": np.asarray(centers_h, dtype=np.float64),
                "n_cols": inputs.n_cols,
                "dtype": str(inputs.dtype),
                "n_iter_": int(n_iter_h),
                "inertia_": float(inertia_h),
            }

        return _fit

    def _create_model(self, result: Dict[str, Any]) -> "KMeansModel":
        return KMeansModel(**result)

    def streaming(self):
        """Streaming incremental-fit engine over this configured estimator:
        mini-batch Lloyd with count-weighted per-center merge —
        partial_fit/merge/finalize (srml-stream, docs/streaming.md)."""
        from ..stream.engines import StreamingKMeans

        return StreamingKMeans(self)


class KMeansModel(_KMeansParams, _TpuModelWithPredictionCol):
    # cluster ids are integral (Spark KMeansModel emits IntegerType)
    _OUT_COLUMN_DDL = {
        **_TpuModelWithPredictionCol._OUT_COLUMN_DDL, "predictionCol": "int"
    }

    def __init__(
        self,
        cluster_centers_: np.ndarray,
        n_cols: int,
        dtype: str,
        n_iter_: int = 0,
        inertia_: float = 0.0,
    ) -> None:
        super().__init__(
            cluster_centers_=np.asarray(cluster_centers_),
            n_cols=int(n_cols),
            dtype=str(dtype),
            n_iter_=int(n_iter_),
            inertia_=float(inertia_),
        )
        self.cluster_centers_ = np.asarray(cluster_centers_)
        self.n_cols = int(n_cols)
        self.dtype = str(dtype)
        self.n_iter_ = int(n_iter_)
        self.inertia_ = float(inertia_)

    def clusterCenters(self) -> List[np.ndarray]:
        """Parity with Spark KMeansModel.clusterCenters (clustering.py:385-391)."""
        return list(self.cluster_centers_)

    @property
    def hasSummary(self) -> bool:
        return False

    def predict(self, value: np.ndarray) -> int:
        """Single-vector prediction (Spark API parity); same dtype policy as
        transform() so the two paths agree on borderline points."""
        np_dtype = self._transform_dtype(self.dtype)
        arr = np.asarray(value, dtype=np_dtype)[None, :]
        return int(
            np.asarray(
                kmeans_predict_kernel(
                    jax.numpy.asarray(arr),
                    jax.numpy.asarray(self.cluster_centers_.astype(np_dtype)),
                )
            )[0]
        )

    def cpu(self):
        """pyspark.ml KMeansModel (parity hook for clustering.py:393-435)."""
        from ..spark.interop import to_spark_kmeans_model

        return to_spark_kmeans_model(self)

    def _get_tpu_transform_func(self, dataset: DataFrame):
        np_dtype = self._transform_dtype(self.dtype)
        centers = jax.device_put(np.asarray(self.cluster_centers_, dtype=np_dtype))
        pred_col = self.getOrDefault("predictionCol")
        predict = jax.jit(kmeans_predict_kernel)

        def _transform(features: np.ndarray) -> Dict[str, Any]:
            labels = predict(jax.device_put(np.asarray(features, dtype=np_dtype)), centers)
            return {pred_col: np.asarray(labels)}

        return _transform

    def _serving_entry(self, mesh: Any = None):
        """Online inference hook (serving/): nearest-center assignment as a
        single bucket-padded kernel through the AOT executable cache."""
        from ..serving.entry import kernel_entry

        np_dtype = self._transform_dtype(self.dtype)
        centers = jax.device_put(np.asarray(self.cluster_centers_, dtype=np_dtype))
        pred_col = self.getOrDefault("predictionCol")
        return kernel_entry(
            "serve.kmeans",
            jax.jit(kmeans_predict_kernel),
            (centers,),
            {},
            lambda labels: {pred_col: np.asarray(labels)},
            dtype=np_dtype,
            n_cols=self.n_cols,
            out_cols=[pred_col],
            info={"k": len(self.cluster_centers_)},
        )

    def _lane_entry(self, mesh: Any = None):
        """Multiplexed serving hook (serving/multiplex): this model's
        centers as ONE lane of the lane-stacked nearest-center kernel —
        variants must share k (the leaf-shape check in lane_signature
        enforces it)."""
        from ..ops.kmeans import lane_kmeans_predict_kernel
        from ..serving.multiplex import LaneEntry

        np_dtype = self._transform_dtype(self.dtype)
        centers = np.ascontiguousarray(
            np.asarray(self.cluster_centers_, dtype=np_dtype)
        )
        pred_col = self.getOrDefault("predictionCol")
        return LaneEntry(
            name="lanes.kmeans",
            n_cols=self.n_cols,
            dtype=np_dtype,
            out_cols=[pred_col],
            leaves=(centers,),
            kernel=lane_kmeans_predict_kernel,
            statics={},
            postprocess=lambda labels: {pred_col: np.asarray(labels)},
            info={"k": len(self.cluster_centers_)},
        )
