#
# PCA estimator/model.
#
# Capability parity with the reference's PCA/PCAModel
# (/root/reference/python/src/spark_rapids_ml/feature.py:61-440): same Spark
# param surface ({k: n_components} mapping, feature.py:62-65; solver defaults
# feature.py:66-73), same model attributes (mean_, components_,
# explained_variance[_ratio]_, singular_values_, n_cols, dtype), and the same
# Spark-parity transform semantics (no mean removal at transform time,
# feature.py:419-431).  The solver itself is TPU-native: a single jitted
# covariance + eigh kernel over a row-sharded mesh (ops/linalg.py) instead of
# cuML PCAMG over NCCL.
#

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

import numpy as np

import jax

from ..core import (
    FitInputs,
    _TpuEstimator,
    _TpuModel,
)
from ..dataframe import DataFrame
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasInputCol,
    HasInputCols,
    HasOutputCol,
    HasVerbose,
    Param,
    TypeConverters,
    _dummy,
    _TpuParams,
)
from ..ops.linalg import pca_fit, pca_transform_kernel
from ..parallel.mesh import data_sharding


class PCAClass(_TpuParams):
    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {"k": "n_components"}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_components": None,
            "svd_solver": "auto",
            "verbose": False,
            "whiten": False,
        }


class _PCAParams(PCAClass, HasInputCol, HasInputCols, HasOutputCol, HasVerbose):
    k = Param(
        _dummy(),
        "k",
        "the number of principal components (> 0)",
        TypeConverters.toInt,
    )

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(inputCol="features", outputCol="pca_features")

    def getK(self) -> int:
        return self.getOrDefault("k")

    def setK(self, value: int):
        return self._set_params(k=value)

    def setInputCol(self, value: Union[str, List[str]]):
        if isinstance(value, str):
            self._set_params(inputCol=value)
        else:
            self._set_params(inputCols=value)
        return self

    def setInputCols(self, value: List[str]):
        return self._set_params(inputCols=value)

    def setOutputCol(self, value: str):
        return self._set_params(outputCol=value)


class PCA(_PCAParams, _TpuEstimator):
    """Distributed PCA on a TPU mesh.

    The fit is one jitted kernel: weighted scatter/mean over the row-sharded
    dataset (psum over ICI/DCN), replicated (D, D) eigh, deterministic
    component signs.  Mirrors the reference's API (feature.py:106-305).
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._initialize_tpu_params()
        self._set_params(**kwargs)

    def _get_tpu_fit_func(self, dataset: DataFrame, extra_params=None):
        def _fit(inputs: FitInputs, params: Dict[str, Any]):
            k = params.get("n_components") or min(inputs.n_rows, inputs.n_cols)
            k = min(int(k), inputs.n_cols)
            # whiten is honored at transform time (see PCAModel); wide inputs
            # route the eigh through the native host runtime (ops.linalg.pca_fit)
            mean, components, var, ratio, sv = pca_fit(
                inputs.X, inputs.weight, k, mesh=inputs.mesh
            )
            return {
                "mean_": np.asarray(mean, dtype=np.float64),
                "components_": np.asarray(components, dtype=np.float64),
                "explained_variance_": np.asarray(var, dtype=np.float64),
                "explained_variance_ratio_": np.asarray(ratio, dtype=np.float64),
                "singular_values_": np.asarray(sv, dtype=np.float64),
                "n_cols": inputs.n_cols,
                "dtype": str(inputs.dtype),
            }

        return _fit

    def _create_model(self, result: Dict[str, Any]) -> "PCAModel":
        return PCAModel(**result)

    def streaming(self):
        """Streaming incremental-fit engine over this configured estimator:
        mergeable covariance-moment accumulation, finalized through the
        batch kernel's shared eigh derivation — partial_fit/merge/finalize
        (srml-stream, docs/streaming.md)."""
        from ..stream.engines import StreamingPCA

        return StreamingPCA(self)


class PCAModel(_PCAParams, _TpuModel):
    def __init__(
        self,
        mean_: np.ndarray,
        components_: np.ndarray,
        explained_variance_: np.ndarray,
        explained_variance_ratio_: np.ndarray,
        singular_values_: np.ndarray,
        n_cols: int,
        dtype: str,
    ) -> None:
        super().__init__(
            mean_=np.asarray(mean_),
            components_=np.asarray(components_),
            explained_variance_=np.asarray(explained_variance_),
            explained_variance_ratio_=np.asarray(explained_variance_ratio_),
            singular_values_=np.asarray(singular_values_),
            n_cols=int(n_cols),
            dtype=str(dtype),
        )
        self.mean_ = np.asarray(mean_)
        self.components_ = np.asarray(components_)
        self.explained_variance_ = np.asarray(explained_variance_)
        self.explained_variance_ratio_ = np.asarray(explained_variance_ratio_)
        self.singular_values_ = np.asarray(singular_values_)
        self.n_cols = int(n_cols)
        self.dtype = str(dtype)
        self._set_params(k=len(self.components_))

    # -- reference-parity accessors (feature.py:336-360) -------------------
    @property
    def mean(self) -> List[float]:
        return self.mean_.tolist()

    @property
    def pc(self) -> np.ndarray:
        """Principal components, one per *column* (Spark DenseMatrix layout)."""
        return self.components_.T

    @property
    def explainedVariance(self) -> np.ndarray:
        return self.explained_variance_ratio_

    def cpu(self):
        """Return the equivalent pyspark.ml PCAModel (requires pyspark +
        an active SparkSession; parity hook for feature.py:362-376)."""
        from ..spark.interop import to_spark_pca_model

        return to_spark_pca_model(self)

    def _out_columns(self) -> List[str]:
        return [self.getOrDefault("outputCol")]

    def _get_tpu_transform_func(self, dataset: DataFrame):
        np_dtype = self._transform_dtype(self.dtype)
        comps = np.asarray(self.components_, dtype=np_dtype)
        if self._tpu_params.get("whiten"):
            # whitened projection: unit variance per component (note: Spark
            # semantics never center at transform time, so whitening scales
            # the uncentered projection)
            scale = 1.0 / np.sqrt(
                np.maximum(self.explained_variance_, 1e-12)
            ).astype(np_dtype)
            comps = comps * scale[:, None]
        components = jax.device_put(comps)
        out_col = self.getOrDefault("outputCol")

        def _transform(features: np.ndarray) -> Dict[str, Any]:
            projected = pca_transform_kernel(
                jax.device_put(np.asarray(features, dtype=np_dtype)), components
            )
            return {out_col: np.asarray(projected)}

        return _transform

    def _serving_entry(self, mesh: Any = None):
        """Online inference hook (serving/): the (whiten-scaled) projection
        as one bucket-padded kernel through the AOT executable cache —
        exactly the matrix transform() applies, so served and batch outputs
        are bit-identical."""
        from ..serving.entry import kernel_entry

        np_dtype = self._transform_dtype(self.dtype)
        comps = np.asarray(self.components_, dtype=np_dtype)
        if self._tpu_params.get("whiten"):
            scale = 1.0 / np.sqrt(
                np.maximum(self.explained_variance_, 1e-12)
            ).astype(np_dtype)
            comps = comps * scale[:, None]
        components = jax.device_put(comps)
        out_col = self.getOrDefault("outputCol")
        return kernel_entry(
            "serve.pca",
            pca_transform_kernel,  # module-level @jax.jit
            (components,),
            {},
            lambda proj: {out_col: np.asarray(proj)},
            dtype=np_dtype,
            n_cols=self.n_cols,
            out_cols=[out_col],
            info={"k": len(self.components_)},
        )

    def _lane_entry(self, mesh: Any = None):
        """Multiplexed serving hook (serving/multiplex): the
        (whiten-scaled) component matrix as ONE lane of the lane-stacked
        projection kernel — the whiten scale is folded host-side exactly
        as in the dedicated entry, so the lane kernel stays a pure
        gathered matmul."""
        from ..ops.linalg import lane_pca_transform_kernel
        from ..serving.multiplex import LaneEntry

        np_dtype = self._transform_dtype(self.dtype)
        comps = np.asarray(self.components_, dtype=np_dtype)
        if self._tpu_params.get("whiten"):
            scale = 1.0 / np.sqrt(
                np.maximum(self.explained_variance_, 1e-12)
            ).astype(np_dtype)
            comps = comps * scale[:, None]
        out_col = self.getOrDefault("outputCol")
        return LaneEntry(
            name="lanes.pca",
            n_cols=self.n_cols,
            dtype=np_dtype,
            out_cols=[out_col],
            leaves=(np.ascontiguousarray(comps),),
            kernel=lane_pca_transform_kernel,
            statics={},
            postprocess=lambda proj: {out_col: np.asarray(proj)},
            info={"k": len(self.components_)},
        )
