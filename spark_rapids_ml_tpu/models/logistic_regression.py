#
# LogisticRegression estimator/model (binary sigmoid + multinomial softmax,
# L2 / L1 / ElasticNet via L-BFGS / OWL-QN).
#
# Capability parity with the reference's LogisticRegression/
# LogisticRegressionModel (/root/reference/python/src/spark_rapids_ml/
# classification.py:646-1388): same param mapping incl. C = 1/regParam
# (:648-672), same penalty derivation from (regParam, elasticNetParam)
# (:687-710), solver defaults (:674-683) with lbfgs memory 10 and
# non-normalized penalty semantics (:955-961), same model attributes
# (coef_, intercept_, classes_, n_cols, dtype, num_iters), sigmoid/softmax
# probability and argmax/threshold label transforms (:1236-1262), intercept
# sparse-compression rule (:1206-1218), model combine (:1330-1360) and
# single-pass transform-evaluate over MulticlassMetrics.
#

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..core import FitInputs, _TpuEstimatorSupervised, _TpuModelWithPredictionCol
from ..dataframe import DataFrame, as_dataframe
from ..metrics.multiclass import MulticlassMetrics
from ..params import (
    HasElasticNetParam,
    HasFeaturesCol,
    HasFeaturesCols,
    HasFitIntercept,
    HasLabelCol,
    HasMaxIter,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasRegParam,
    HasStandardization,
    HasTol,
    HasVerbose,
    HasWeightCol,
    Param,
    TypeConverters,
    _dummy,
    _TpuParams,
)
from ..ops.logistic import (
    logistic_decision_kernel,
    logistic_fit_kernel,
    scores_to_labels,
    scores_to_probs,
    sweep_logistic_fit_kernel,
)
from ..utils import get_logger


class _ClassificationModelEvaluationMixIn:
    """Single-pass transform+evaluate via MulticlassMetrics, shared by
    LogisticRegressionModel and RandomForestClassificationModel (reference
    classification.py:180-295)."""

    def _partition_metrics(
        self, part: Any, evaluator: Any, num_models: int, predict_all=None
    ) -> List[MulticlassMetrics]:
        """One partition's per-model mergeable metric partials — shared by
        the local evaluate loop and the Spark executor UDF.  Callers looping
        over partitions pass a hoisted predict_all so the model arrays are
        device-staged once per evaluate, not once per partition."""
        from ..core import extract_partition_features

        needs_probs = evaluator.getMetricName() == "logLoss"
        eps = evaluator.getEps()
        input_col, input_cols = self._get_input_columns()
        dtype = self._transform_dtype(self._model_attributes.get("dtype"))
        feats = extract_partition_features(part, input_col, input_cols, dtype)
        labels = part[self.getOrDefault("labelCol")].to_numpy()
        if predict_all is None:
            predict_all = self._get_eval_predict_func()
        preds_all, probs_all = predict_all(feats)  # (M, n), (M, n, C)
        return [
            MulticlassMetrics.from_arrays(
                labels,
                preds_all[i],
                probs=probs_all[i] if needs_probs else None,
                eps=eps,
            )
            for i in range(num_models)
        ]

    def _transform_evaluate(
        self, dataset: Any, evaluator: Any, num_models: int
    ) -> List[float]:
        from ..core import _use_executor_path
        from ..evaluation import MulticlassClassificationEvaluator

        if not isinstance(evaluator, MulticlassClassificationEvaluator):
            raise NotImplementedError(f"{evaluator} is unsupported yet.")
        if _use_executor_path(dataset):
            from ..spark.adapter import executor_transform_evaluate

            return executor_transform_evaluate(
                self, dataset, evaluator, num_models
            )
        df = as_dataframe(dataset)
        label_col = self.getOrDefault("labelCol")
        if label_col not in df.columns:
            raise RuntimeError("Label column is not existing.")
        predict_all = self._get_eval_predict_func()
        metrics: List[Optional[MulticlassMetrics]] = [None] * num_models
        for part in df.partitions:
            if len(part) == 0:
                continue
            for i, m in enumerate(
                self._partition_metrics(part, evaluator, num_models, predict_all)
            ):
                metrics[i] = m if metrics[i] is None else metrics[i].merge(m)
        return [m.evaluate(evaluator) for m in metrics]  # type: ignore[union-attr]


class LogisticRegressionClass(_TpuParams):
    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {
            "maxIter": "max_iter",
            "regParam": "C",
            "elasticNetParam": "l1_ratio",
            "tol": "tol",
            "fitIntercept": "fit_intercept",
            "threshold": None,
            "thresholds": None,
            "standardization": "",
            "weightCol": None,
            "aggregationDepth": None,
            "family": "",
            "maxBlockSizeInMB": None,
        }

    @classmethod
    def _param_value_mapping(cls):
        # spark regParam -> C = 1/regParam (0 stays 0), classification.py:668-672
        return {"C": lambda x: 1 / x if x > 0.0 else (0.0 if x == 0.0 else None)}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "fit_intercept": True,
            "verbose": False,
            "C": 1.0,
            "penalty": "l2",
            "l1_ratio": None,
            "max_iter": 1000,
            "tol": 0.0001,
        }

    @staticmethod
    def _reg_params_value_mapping(reg_param: float, elasticnet_param: float):
        """(regParam, elasticNetParam) -> (penalty, C, l1_ratio), parity with
        classification.py:687-710."""
        if reg_param == 0.0:
            return "none", 0.0, elasticnet_param
        if elasticnet_param == 0.0:
            return "l2", 1.0 / reg_param, elasticnet_param
        if elasticnet_param == 1.0:
            return "l1", 1.0 / reg_param, elasticnet_param
        return "elasticnet", 1.0 / reg_param, elasticnet_param


class _LogisticRegressionParams(
    LogisticRegressionClass,
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasMaxIter,
    HasTol,
    HasRegParam,
    HasElasticNetParam,
    HasFitIntercept,
    HasStandardization,
    HasWeightCol,
    HasVerbose,
):
    family = Param(_dummy(), "family", "the name of family (auto|binomial|multinomial); detected automatically", TypeConverters.toString)
    threshold = Param(_dummy(), "threshold", "binary classification threshold", TypeConverters.toFloat)

    # CSR input fits/transforms without densification via the ELL kernels
    # (ops/sparse.py; reference sparse qn, classification.py:1206-1218)
    _supports_sparse_input = True

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(
            maxIter=100,
            regParam=0.0,
            elasticNetParam=0.0,
            tol=1e-6,
            standardization=True,
            family="auto",
        )

    def setMaxIter(self, value: int):
        return self._set_params(maxIter=value)

    def setRegParam(self, value: float):
        return self._set_params(regParam=value)

    def setElasticNetParam(self, value: float):
        return self._set_params(elasticNetParam=value)

    def setTol(self, value: float):
        return self._set_params(tol=value)

    def setFitIntercept(self, value: bool):
        return self._set_params(fitIntercept=value)

    def setProbabilityCol(self, value: str):
        return self._set_params(probabilityCol=value)

    def setRawPredictionCol(self, value: str):
        return self._set_params(rawPredictionCol=value)


class LogisticRegression(_LogisticRegressionParams, _TpuEstimatorSupervised):
    """Distributed logistic regression on a TPU mesh via fully-jitted
    L-BFGS/OWL-QN with psum'd loss/grad (ops/lbfgs.py, ops/logistic.py)."""

    # class discovery runs per-rank on local shards + control-plane union
    # (core.discover_label_classes) and the encode is a jitted kernel over
    # the row-sharded labels (ops/labels.py), so the whole fit is safe on a
    # multi-process mesh — distributed-capability parity with the
    # reference's LogisticRegressionMG (classification.py:915-1001)
    _supports_multicontroller_fit = True

    def __init__(self, **kwargs: Any) -> None:
        if not kwargs.get("float32_inputs", True):
            get_logger(type(self)).warning(
                "This estimator does not support double precision inputs. "
                "Setting float32_inputs to False will be ignored."
            )
            kwargs.pop("float32_inputs")
        super().__init__()
        self._initialize_tpu_params()
        self._set_tpu_reg_params()
        self._set_params(**kwargs)
        self._set_tpu_reg_params()

    def _set_tpu_reg_params(self) -> None:
        penalty, C, l1_ratio = self._reg_params_value_mapping(
            self.getOrDefault("regParam"), self.getOrDefault("elasticNetParam")
        )
        self._tpu_params["penalty"] = penalty
        self._tpu_params["C"] = C
        self._tpu_params["l1_ratio"] = l1_ratio

    def _set_params(self, **kwargs: Any):
        out = super()._set_params(**kwargs)
        if hasattr(self, "_tpu_params") and (
            "regParam" in kwargs or "elasticNetParam" in kwargs
        ):
            self._set_tpu_reg_params()
        return out

    def _enable_fit_multiple_in_single_pass(self) -> bool:
        return True

    def _supportsTransformEvaluate(self, evaluator: Any) -> bool:
        from ..evaluation import MulticlassClassificationEvaluator

        return isinstance(evaluator, MulticlassClassificationEvaluator)

    def _get_tpu_fit_func(self, dataset: DataFrame, extra_params=None):
        logger = get_logger(type(self))

        def _single_fit(
            inputs: FitInputs, params: Dict[str, Any], classes: np.ndarray, y_enc
        ) -> Dict[str, Any]:
            C = float(params["C"])
            l1_ratio = float(params.get("l1_ratio") or 0.0)
            reg = 1.0 / C if C > 0 else 0.0
            num_classes = len(classes)
            k = 1 if num_classes == 2 else num_classes
            use_owlqn = reg > 0 and l1_ratio > 0
            W, b, n_iter, converged = logistic_fit_kernel(
                inputs.X,
                y_enc,
                inputs.weight,
                k,
                reg,
                l1_ratio,
                bool(params["fit_intercept"]),
                int(params["max_iter"]),
                float(params["tol"]),
                use_owlqn,
            )
            # one batched device fetch (each scalar coercion alone costs a
            # host round-trip through the tunneled device)
            W_h, b_h, n_iter_h, conv_h = jax.device_get(
                (W, b, n_iter, converged)
            )
            logger.info(
                "L-BFGS iters: %d converged: %s", int(n_iter_h), bool(conv_h)
            )
            return {
                "coef_": np.asarray(W_h, dtype=np.float64),
                "intercept_": np.asarray(b_h, dtype=np.float64),
                "classes_": np.asarray(classes, dtype=np.float64),
                "n_cols": inputs.n_cols,
                "dtype": str(inputs.dtype),
                "num_iters": int(n_iter_h),
            }

        def _fit(inputs: FitInputs, params: Dict[str, Any]):
            from ..core import discover_label_classes
            from ..ops.labels import encode_labels_kernel

            assert inputs.y is not None
            classes = discover_label_classes(inputs)
            if len(classes) < 2:
                raise RuntimeError(
                    "LogisticRegression requires at least two distinct labels"
                )
            # encode labels as class indices on device, preserving the row
            # sharding (padded rows clamp into range; masked by w)
            y_enc = encode_labels_kernel(
                inputs.y, jnp.asarray(classes.astype(inputs.y.dtype))
            )
            if extra_params:
                results = []
                for override in extra_params:
                    p = dict(params)
                    p.update(override)
                    if "C" in override or "l1_ratio" in override:
                        # re-derive penalty kind for parity bookkeeping
                        reg = 1 / p["C"] if p["C"] else 0.0
                        p["penalty"], _, _ = self._reg_params_value_mapping(
                            reg, p.get("l1_ratio") or 0.0
                        )
                    results.append(_single_fit(inputs, p, classes, y_enc))
                return results
            return _single_fit(inputs, params, classes, y_enc)

        return _fit

    def _create_model(self, result: Dict[str, Any]) -> "LogisticRegressionModel":
        return LogisticRegressionModel(**result)

    def streaming(self, classes=None):
        """Streaming incremental-fit engine over this configured estimator:
        warm-started per-chunk L-BFGS with count-weighted coefficient
        averaging — partial_fit/merge/finalize (srml-stream,
        docs/streaming.md).  Pass classes= when early chunks may not cover
        the full label set."""
        from ..stream.engines import StreamingLogisticRegression

        return StreamingLogisticRegression(self, classes=classes)

    # -- batched hyperparameter sweep (srml-sweep) -------------------------
    def _supportsBatchedSweep(self, df, paramMaps, evaluator) -> bool:
        if not paramMaps or not self._supportsTransformEvaluate(evaluator):
            return False
        try:
            overrides = [self._paramMap_to_tpu_overrides(pm) for pm in paramMaps]
        except ValueError:
            return False
        if any(set(ov) - {"C", "l1_ratio"} for ov in overrides):
            return False  # only the regularizer axes batch as lanes
        return not self._sweep_sparse_input(df)

    def _fitBatchedSweep(self, df, paramMaps, n_folds, seed):
        """All n_folds x len(paramMaps) logreg fits as ONE lane-batched
        L-BFGS/OWL-QN run per penalty family over the ONE staged dataset —
        folds as fold-id weight masks, candidates as traced reg/l1 lanes
        with per-lane convergence masks (ops/logistic.py,
        ops/lbfgs.minimize_lbfgs_batched)."""
        from .. import profiling
        from ..core import discover_label_classes
        from ..ops import sweep as sweep_ops
        from ..ops.labels import encode_labels_kernel
        from ..sanitize import sanitize_scope

        params = dict(self._tpu_params)
        cand = []
        for pm in paramMaps:
            p = dict(params)
            p.update(self._paramMap_to_tpu_overrides(pm))
            C = float(p["C"])
            l1_ratio = float(p.get("l1_ratio") or 0.0)
            reg = 1.0 / C if C > 0 else 0.0
            cand.append((reg, l1_ratio, reg > 0 and l1_ratio > 0))
        fit_intercept = bool(params["fit_intercept"])
        max_iter = int(params["max_iter"])
        with profiling.phase("srml.ingest"):
            inputs = self._build_fit_inputs(df)
        assert inputs.y is not None
        classes = discover_label_classes(inputs)
        if len(classes) < 2:
            raise RuntimeError(
                "LogisticRegression requires at least two distinct labels"
            )
        num_classes = len(classes)
        kcls = 1 if num_classes == 2 else num_classes
        mesh = inputs.mesh
        fid = sweep_ops.stage_fold_ids(
            inputs.n_rows, inputs.X.shape[0], n_folds, seed, mesh
        )
        results: List[List[Dict[str, Any]]] = [
            [None] * len(cand) for _ in range(n_folds)  # type: ignore[list-item]
        ]
        logger = get_logger(type(self))
        with sanitize_scope():
            y_enc = encode_labels_kernel(
                inputs.y, jnp.asarray(classes.astype(inputs.y.dtype))
            )
            # one lane-batched run per penalty family (OWL-QN is a
            # structurally different optimizer, so it cannot share lanes
            # with the smooth-penalty group) — mirrors _single_fit's
            # per-candidate use_owlqn choice
            tol = jnp.asarray(np.float64(float(params["tol"])))
            families = []
            for owlqn in (False, True):
                idxs = [i for i, c in enumerate(cand) if c[2] == owlqn]
                if not idxs:
                    continue
                _, (regs, l1s) = sweep_ops.pack_lane_subset(
                    cand, idxs, fields=(0, 1)
                )
                families.append((owlqn, idxs, regs, l1s))
            # warm BOTH penalty families' sweep kernels at entry (concrete
            # args — the staged arrays themselves — so the derived keys and
            # captured shardings are exactly the dispatch's): with a mixed
            # grid the OWL-QN executable compiles on the pool WHILE the
            # smooth family's sweep runs instead of serializing behind it
            sweep_ops.warm(
                [
                    (
                        "sweep.logreg.fit",
                        sweep_logistic_fit_kernel,
                        (inputs.X, y_enc, inputs.weight, fid, regs, l1s, tol),
                        dict(
                            k_folds=n_folds,
                            kcls=kcls,
                            fit_intercept=fit_intercept,
                            max_iter=max_iter,
                            use_owlqn=owlqn,
                        ),
                    )
                    for owlqn, _idxs, regs, l1s in families
                ],
                mesh=mesh,
            )
            for owlqn, idxs, regs, l1s in families:
                with profiling.span(
                    "tuning.sweep.solve",
                    candidates=len(idxs),
                    folds=n_folds,
                    owlqn=owlqn,
                ):
                    W, b, n_iter, conv = sweep_ops.dispatch(
                        "sweep.logreg.fit",
                        sweep_logistic_fit_kernel,
                        inputs.X,
                        y_enc,
                        inputs.weight,
                        fid,
                        regs,
                        l1s,
                        tol,
                        mesh=mesh,
                        k_folds=n_folds,
                        kcls=kcls,
                        fit_intercept=fit_intercept,
                        max_iter=max_iter,
                        use_owlqn=owlqn,
                    )
                    # graftlint: disable=R1 (one batched fetch per penalty FAMILY — at most two iterations, each a distinct compiled sweep whose results ship home together)
                    W_h, b_h, n_iter_h, conv_h = jax.device_get(
                        (W, b, n_iter, conv)
                    )
                logger.info(
                    "sweep L-BFGS iters (fold x candidate): %s converged: %s",
                    n_iter_h[:, : len(idxs)].tolist(),
                    conv_h[:, : len(idxs)].tolist(),
                )
                for j, i in enumerate(idxs):
                    for f in range(n_folds):
                        results[f][i] = {
                            "coef_": np.asarray(W_h[f, j], dtype=np.float64),
                            "intercept_": np.asarray(
                                b_h[f, j], dtype=np.float64
                            ),
                            "classes_": np.asarray(classes, dtype=np.float64),
                            "n_cols": inputs.n_cols,
                            "dtype": str(inputs.dtype),
                            "num_iters": int(n_iter_h[f, j]),
                        }
        return results


class LogisticRegressionModel(
    _LogisticRegressionParams,
    _ClassificationModelEvaluationMixIn,
    _TpuModelWithPredictionCol,
):
    def __init__(
        self,
        coef_: np.ndarray,
        intercept_: np.ndarray,
        classes_: np.ndarray,
        n_cols: int,
        dtype: str,
        num_iters: Union[int, List[int]] = 0,
    ) -> None:
        super().__init__(
            coef_=np.asarray(coef_),
            intercept_=np.asarray(intercept_),
            classes_=np.asarray(classes_),
            n_cols=int(n_cols),
            dtype=str(dtype),
            num_iters=num_iters,
        )
        self.coef_ = np.asarray(coef_)
        self.intercept_ = np.asarray(intercept_)
        self.classes_ = np.asarray(classes_)
        self.n_cols = int(n_cols)
        self.dtype = str(dtype)
        self.num_iters = num_iters
        self._num_classes = len(self.classes_)

    @property
    def _num_models(self) -> int:
        return self.coef_.shape[0] if self.coef_.ndim == 3 else 1

    @property
    def numClasses(self) -> int:
        return self._num_classes

    @property
    def coefficients(self) -> np.ndarray:
        assert self._num_models == 1
        if self.coef_.shape[0] == 1:
            return self.coef_[0]
        raise AttributeError(
            "Multinomial models contain a matrix of coefficients, use coefficientMatrix instead."
        )

    @property
    def intercept(self) -> float:
        assert self._num_models == 1
        if len(self.intercept_) == 1:
            return float(self.intercept_[0])
        raise AttributeError(
            "Multinomial models contain a vector of intercepts, use interceptVector instead."
        )

    @property
    def coefficientMatrix(self) -> np.ndarray:
        assert self._num_models == 1
        return self.coef_

    @property
    def interceptVector(self) -> Any:
        """Dense or sparse intercepts, following Spark's compression rule
        (1.5*(nnz+1) < size -> sparse; classification.py:1206-1218).  Returns
        a pyspark Vector when pyspark is available, else a numpy array."""
        assert self._num_models == 1
        intercepts = self.intercept_
        try:
            from pyspark.ml.linalg import Vectors

            nnz = int(np.count_nonzero(intercepts))
            if 1.5 * (nnz + 1.0) < len(intercepts):
                data = {i: float(v) for i, v in enumerate(intercepts) if v != 0}
                return Vectors.sparse(len(intercepts), data)
            return Vectors.dense(list(intercepts))
        except ImportError:
            return intercepts

    def predict(self, value: np.ndarray) -> float:
        np_dtype = self._transform_dtype(self.dtype)
        scores = np.asarray(
            logistic_decision_kernel(
                jnp.asarray(np.asarray(value, np_dtype)[None, :]),
                jnp.asarray(self.coef_.astype(np_dtype)),
                jnp.asarray(self.intercept_.astype(np_dtype)),
            )
        )
        idx = int(
            np.asarray(scores_to_labels(jnp.asarray(scores), self._num_classes))[0]
        )
        return float(self.classes_[idx])

    def predictProbability(self, value: np.ndarray) -> np.ndarray:
        np_dtype = self._transform_dtype(self.dtype)
        scores = logistic_decision_kernel(
            jnp.asarray(np.asarray(value, np_dtype)[None, :]),
            jnp.asarray(self.coef_.astype(np_dtype)),
            jnp.asarray(self.intercept_.astype(np_dtype)),
        )
        return np.asarray(scores_to_probs(scores, self._num_classes))[0]

    def _out_columns(self) -> List[str]:
        return [
            self.getOrDefault("predictionCol"),
            self.getOrDefault("probabilityCol"),
            self.getOrDefault("rawPredictionCol"),
        ]

    def _get_tpu_transform_func(self, dataset: DataFrame):
        assert self._num_models == 1
        np_dtype = self._transform_dtype(self.dtype)
        W = jax.device_put(self.coef_.astype(np_dtype))
        b = jax.device_put(self.intercept_.astype(np_dtype))
        classes = self.classes_
        num_classes = self._num_classes
        pred_col = self.getOrDefault("predictionCol")
        prob_col = self.getOrDefault("probabilityCol")
        raw_col = self.getOrDefault("rawPredictionCol")

        def _transform(features: np.ndarray) -> Dict[str, Any]:
            if hasattr(features, "tocsr"):  # CSR partition -> device ELL
                from ..ops.sparse import ell_device_from_scipy

                Xd = ell_device_from_scipy(features, np_dtype)
            else:
                Xd = jax.device_put(np.asarray(features, np_dtype))
            scores = logistic_decision_kernel(Xd, W, b)
            probs = np.asarray(scores_to_probs(scores, num_classes), np.float64)
            idx = np.asarray(
                scores_to_labels(scores, num_classes), np.int64
            )
            raw = np.asarray(scores, np.float64)
            if num_classes == 2 and raw.shape[1] == 1:
                raw = np.concatenate([-raw, raw], axis=1)
            return {
                pred_col: classes[idx].astype(np.float64),
                prob_col: probs,
                raw_col: raw,
            }

        return _transform

    def _serving_entry(self, mesh: Any = None):
        """Online inference hook (serving/): decision scores, probabilities
        and label indices fused into ONE bucket-padded kernel through the
        AOT executable cache — the same ops the batch transform composes,
        kept on device so a served batch is one dispatch, not three."""
        assert self._num_models == 1, "combined multi-models are not servable"
        from ..serving.entry import kernel_entry

        np_dtype = self._transform_dtype(self.dtype)
        W = jax.device_put(self.coef_.astype(np_dtype))
        b = jax.device_put(self.intercept_.astype(np_dtype))
        classes = self.classes_
        num_classes = self._num_classes
        pred_col = self.getOrDefault("predictionCol")
        prob_col = self.getOrDefault("probabilityCol")
        raw_col = self.getOrDefault("rawPredictionCol")

        def _serve_kernel(X: jax.Array, W: jax.Array, b: jax.Array):
            scores = logistic_decision_kernel(X, W, b)
            return (
                scores,
                scores_to_probs(scores, num_classes),
                scores_to_labels(scores, num_classes),
            )

        def _post(out) -> Dict[str, Any]:
            scores, probs, labels = out
            raw = np.asarray(scores, np.float64)
            if num_classes == 2 and raw.shape[1] == 1:
                raw = np.concatenate([-raw, raw], axis=1)
            idx = np.asarray(labels, np.int64)
            return {
                pred_col: classes[idx].astype(np.float64),
                prob_col: np.asarray(probs, np.float64),
                raw_col: raw,
            }

        return kernel_entry(
            "serve.logreg",
            jax.jit(_serve_kernel),
            (W, b),
            {},
            _post,
            dtype=np_dtype,
            n_cols=self.n_cols,
            out_cols=[pred_col, prob_col, raw_col],
            info={"num_classes": num_classes},
        )

    def _lane_entry(self, mesh: Any = None):
        """Multiplexed serving hook (serving/multiplex): (W, b) as ONE lane
        of the lane-stacked fused decision/probability/label kernel.  The
        class labels ride `meta`: variants sharing a lane buffer must agree
        on them, because the shared postprocess maps label indices through
        variant 0's classes_."""
        assert self._num_models == 1, "combined multi-models are not servable"
        from ..ops.logistic import lane_logistic_predict_kernel
        from ..serving.multiplex import LaneEntry

        np_dtype = self._transform_dtype(self.dtype)
        W = np.ascontiguousarray(self.coef_.astype(np_dtype))
        b = np.ascontiguousarray(self.intercept_.astype(np_dtype))
        classes = self.classes_
        num_classes = self._num_classes
        pred_col = self.getOrDefault("predictionCol")
        prob_col = self.getOrDefault("probabilityCol")
        raw_col = self.getOrDefault("rawPredictionCol")

        def _post(out) -> Dict[str, Any]:
            scores, probs, labels = out
            raw = np.asarray(scores, np.float64)
            if num_classes == 2 and raw.shape[1] == 1:
                raw = np.concatenate([-raw, raw], axis=1)
            idx = np.asarray(labels, np.int64)
            return {
                pred_col: classes[idx].astype(np.float64),
                prob_col: np.asarray(probs, np.float64),
                raw_col: raw,
            }

        return LaneEntry(
            name="lanes.logreg",
            n_cols=self.n_cols,
            dtype=np_dtype,
            out_cols=[pred_col, prob_col, raw_col],
            leaves=(W, b),
            kernel=lane_logistic_predict_kernel,
            statics={"num_classes": num_classes},
            postprocess=_post,
            meta=(str(np.asarray(classes).dtype), np.asarray(classes).tobytes()),
            info={"num_classes": num_classes},
        )

    def _get_eval_predict_func(self) -> Callable[[np.ndarray], tuple]:
        np_dtype = self._transform_dtype(self.dtype)
        coefs = jnp.asarray(
            (self.coef_ if self.coef_.ndim == 3 else self.coef_[None]).astype(np_dtype)
        )  # (M, k, D)
        intercepts = jnp.asarray(
            (
                self.intercept_ if self.intercept_.ndim == 2 else self.intercept_[None]
            ).astype(np_dtype)
        )  # (M, k)
        classes = self.classes_
        num_classes = self._num_classes

        def _predict_all(feats: np.ndarray):
            # one transfer + one batched matmul for all M models; HIGHEST
            # keeps scores bit-comparable with the single-model decision
            # kernel (ops/logistic.py logistic_decision_kernel), which the
            # single-pass CV scoring path is asserted against
            Xd = jax.device_put(np.asarray(feats, np_dtype))
            scores = (
                jnp.einsum(
                    "nd,mkd->mnk",
                    Xd,
                    coefs,
                    precision=jax.lax.Precision.HIGHEST,
                )
                + intercepts[:, None, :]
            )
            probs = np.stack(
                [
                    np.asarray(scores_to_probs(scores[m], num_classes), np.float64)
                    for m in range(scores.shape[0])
                ]
            )
            idx = np.stack(
                [
                    np.asarray(scores_to_labels(scores[m], num_classes), np.int64)
                    for m in range(scores.shape[0])
                ]
            )
            return classes[idx].astype(np.float64), probs

        return _predict_all

    def cpu(self):
        """pyspark.ml LogisticRegressionModel (parity hook for
        classification.py:1124-1146)."""
        from ..spark.interop import to_spark_logistic_model

        return to_spark_logistic_model(self)

    @classmethod
    def _combine(cls, models: List["LogisticRegressionModel"]) -> "LogisticRegressionModel":
        assert models and all(isinstance(m, cls) for m in models)
        first = models[0]
        combined = cls(
            coef_=np.stack([m.coef_ for m in models]),
            intercept_=np.stack([m.intercept_ for m in models]),
            classes_=first.classes_,
            n_cols=first.n_cols,
            dtype=first.dtype,
            num_iters=[int(np.ravel(m.num_iters)[0]) for m in models],
        )
        first._copyValues(combined)
        combined._tpu_params.update(first._tpu_params)
        combined._float32_inputs = first._float32_inputs
        return combined

    def _transformEvaluate(self, dataset: Any, evaluator: Any, params=None) -> List[float]:
        return self._transform_evaluate(dataset, evaluator, self._num_models)
