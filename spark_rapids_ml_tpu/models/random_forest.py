#
# RandomForest classifier/regressor estimators and models.
#
# Capability parity with the reference's shared tree machinery
# (/root/reference/python/src/spark_rapids_ml/tree.py:66-607) and its
# Spark-facing subclasses (classification.py:297-643, regression.py:780-1057):
# same Spark param mapping (tree.py:68-86), same max_features value mapping
# (tree.py:88-110), same solver defaults (tree.py:112-128), int32 label cast
# for classification (classification.py:483-496), probability/rawPrediction
# columns, model combine and single-pass transform-evaluate.
#
# The builder is redesigned TPU-first (ops/forest.py): every tree trains on
# the FULL row-sharded dataset with Poisson bootstrap weights (a statistical
# improvement over the reference's per-worker data shards, tree.py:256-267 —
# there each worker only sees 1/num_workers of the rows).  The forest is
# stored as dense arrays (feature/threshold/leaf-value per node) instead of
# treelite bytes; `trees_to_dicts` exports the portable nested-dict format
# that plays the role of the reference's treelite JSON (utils.py:385-447
# translate_trees interop).
#

from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..core import FitInputs, _TpuEstimatorSupervised, _TpuModelWithPredictionCol
from ..dataframe import DataFrame
from .linear_regression import _RegressionModelEvaluationMixIn
from .logistic_regression import _ClassificationModelEvaluationMixIn
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasSeed,
    HasVerbose,
    HasWeightCol,
    Param,
    TypeConverters,
    _dummy,
    _TpuParams,
)
from .. import profiling
from ..ops.forest import (
    bin_features,
    bin_features_feature_major,
    compute_bin_edges,
    compute_bin_edges_device,
    forest_predict_cached,
    grow_forest,
    warm_forest_kernels,
)
from ..utils import get_logger

_MAX_SUPPORTED_DEPTH = 16  # dense tree layout: 2^(d+1)-1 node slots

# binning subsample cap (compute_bin_edges subsamples to 100k anyway; this
# bound also caps the device->host transfer that feeds it)
_BINNING_SAMPLE_ROWS = 16_384
# cap the sample FETCH, not just its row count: the sample crosses the
# host link, and on a congested tunnel a 100 MB fetch costs minutes while
# edge quality needs only ~100 samples per bin (measured: a 50k-row sample
# at 200k x 500 put ~200 s of pure transfer inside every estimator fit)
_BINNING_SAMPLE_BYTES = 32 << 20


def _binning_quota(X, n_shards_global: int) -> int:
    """Rows each shard may contribute to the binning sample: the byte/row
    budget divided over the GLOBAL shard count, so a 2-process x 4-device
    fit samples exactly like a 1-process x 8-device fit over the same
    global row layout (identical edges either way).  The floor sits on
    the TOTAL, not per shard — a per-shard floor times a big mesh would
    overshoot the byte cap this sample exists to enforce."""
    row_bytes = max(1, X.shape[1] * X.dtype.itemsize)
    budget = max(
        2048, min(_BINNING_SAMPLE_ROWS, _BINNING_SAMPLE_BYTES // row_bytes)
    )
    return max(1, budget // max(1, n_shards_global))


def _binning_rows(shard_weight, quota: int) -> np.ndarray:
    """One shard's sampled row indices: valid (weight > 0) rows, ceil-
    strided down to the quota.  Ceil stride spans the FULL row range — a
    floor stride would truncate to a leading prefix, badly biasing edges
    on label/time-sorted data.  The ONE row-selection policy shared by
    the host-gather and device-edges paths."""
    wv = np.asarray(shard_weight)
    idx = np.flatnonzero(wv > 0)
    if idx.size > quota:
        step = -(-idx.size // quota)
        idx = idx[::step]
    return idx


def _binning_sample(inputs: FitInputs) -> np.ndarray:
    """Bounded strided row sample of the device-resident features for
    quantile binning: per-shard strided gathers of valid rows (at most
    min(_BINNING_SAMPLE_ROWS, _BINNING_SAMPLE_BYTES worth) across the whole
    job), gathered across ranks through the control plane so every rank
    computes IDENTICAL bin edges — the per-rank-sample + gather the
    reference's byte-capped binning would do under its barrier allGather.
    Never round-trips the full dataset to the host and never touches a
    non-addressable shard, so it is safe in multi-process fits."""
    from ..core import _aligned_shard_objs

    X, w = inputs.X, inputs.weight
    shard_pairs = list(_aligned_shard_objs(X, w))
    quota = _binning_quota(
        X, max(1, inputs.nranks) * max(1, len(shard_pairs))
    )
    # On TPU the sample crosses the (congestion-prone) host link: fetch it
    # bf16 — half the bytes.  Quantile edges from a ~2.8k-row sample carry
    # sampling error orders of magnitude above bf16 rounding OF THE
    # RESIDUALS: each feature is centered on device before the cast and
    # restored after the fetch, so offset-dominated features (a year
    # column in [2020, 2026], sensor readings 1000 +/- 0.5) keep their
    # full bin resolution — raw bf16 would collapse them to 1-2 codes.
    # The rounded edges are used consistently for training AND prediction
    # thresholds (no train/serve skew).
    halve = (
        jax.default_backend() == "tpu"
        and np.dtype(inputs.dtype) == np.float32
    )
    parts = []
    for sx, sw in shard_pairs:
        idx = _binning_rows(sw.data, quota)
        if idx.size:
            sub = sx.data[jnp.asarray(idx)]
            if halve:
                mu = jnp.mean(sub, axis=0)
                # graftlint: disable=R1 (loop is over device shards; one fetch per shard IS the batch unit)
                sub_h, mu_h = jax.device_get(
                    ((sub - mu[None, :]).astype(jnp.bfloat16), mu)
                )
                parts.append(
                    sub_h.astype(X.dtype) + np.asarray(mu_h, X.dtype)[None, :]
                )
            else:
                # graftlint: disable=R1 (per-shard fetch: the shard is the batch unit)
                parts.append(np.asarray(sub).astype(X.dtype, copy=False))
    local = (
        np.concatenate(parts)
        if parts
        else np.zeros((0, X.shape[1]), dtype=X.dtype)
    )
    if inputs.nranks > 1 and inputs.control_plane is not None:
        from ..parallel.runner import allgather_ndarray

        # the gathered total stays ~budget rows (the per-shard quota divides
        # by nranks), so each rank posts ~budget/nranks rows worth of
        # message — bounded by _BINNING_SAMPLE_BYTES across the whole job
        local = np.concatenate(
            allgather_ndarray(inputs.control_plane, inputs.rank, local)
        ).astype(X.dtype, copy=False)
    return local


def _binning_sample_device(inputs: FitInputs):
    """Single-rank TPU path: the strided binning sample STAYS ON DEVICE
    (same row selection as _binning_sample) so the quantile edges can be
    computed there (ops/forest.compute_bin_edges_device) and only the
    (D, B-1) edge matrix crosses the host link.  Returns None when the
    fit is multi-rank/multi-shard or non-f32 — those keep the host
    gather path."""
    from ..core import _aligned_shard_objs

    if jax.default_backend() != "tpu" or inputs.nranks > 1:
        return None
    X, w = inputs.X, inputs.weight
    if np.dtype(inputs.dtype) != np.float32:
        return None
    shard_pairs = list(_aligned_shard_objs(X, w))
    if len(shard_pairs) != 1:
        return None
    sx, sw = shard_pairs[0]
    idx = _binning_rows(sw.data, _binning_quota(X, 1))
    if idx.size == 0:
        return None
    return sx.data[jnp.asarray(idx)]


@partial(jax.jit, static_argnames=("n_trees", "bootstrap"))
def _per_tree_stats(stats, weight, key, n_trees, bootstrap):
    """(T, N, S) per-tree bootstrap-weighted stats.  Jitted so the Poisson
    draw is generated SHARDED alongside the row-sharded weight (an eager
    jax.random.poisson would materialize the full (T, N) matrix replicated
    on every device — and is not expressible at all on a multi-process
    mesh)."""
    if bootstrap:
        bw = jax.random.poisson(key, 1.0, (n_trees, weight.shape[0])).astype(
            weight.dtype
        )
        w_t = weight[None, :] * bw
    else:
        w_t = jnp.broadcast_to(weight[None, :], (n_trees, weight.shape[0]))
    return stats[None, :, :] * w_t[:, :, None]


def _str_or_numerical(value: str) -> Union[str, float, int]:
    """'0.3' -> 0.3, '5' -> 5, else the string (reference utils helper
    used by the max_features mapping)."""
    try:
        return int(value)
    except (TypeError, ValueError):
        try:
            return float(value)
        except (TypeError, ValueError):
            return value


def _mxu_eligible(inputs, n_bins, max_features, max_depth, s_split) -> bool:
    """Whether the MXU histogram builder (ops/forest_mxu) serves this fit;
    False -> the mesh-parallel engine (ops/forest.grow_forest).  TPU
    scatter sustains ~10M updates/s, the MXU path ~36 TF-equivalent.  The
    histogram KERNEL has a mesh sharding rule
    (forest_hist.node_histograms_sharded), but the full builder still
    drives a single chip end-to-end (unsharded deep-phase payload sort), so
    multi-device fits run the sharded scan-batched engine — no longer the
    old host-driven per-level loop."""
    from ..ops import forest_mxu

    return (
        jax.default_backend() == "tpu"
        and inputs.mesh.devices.size == 1
        and n_bins <= 128
        and max_features <= 1024
        and forest_mxu.mxu_depth_supported(max_depth, s_split)
    )


def _maybe_grow_mxu(
    inputs,
    bins_fm,        # (D, n_pad) int8 feature-major (bin_features_feature_major)
    edges,
    stats,
    n_trees,
    bootstrap,
    seed,
    is_classification,
    *,
    max_depth,
    n_bins,
    kind,
    max_features,
    min_samples_leaf,
    min_impurity_decrease,
):
    """Grow on the MXU histogram builder.  Caller has already checked
    _mxu_eligible and binned feature-major — the row-major int bin matrix
    this path used to re-lay-out was a redundant 1.2-4.8 GB resident copy
    that tipped the depth-13 benchmark fit over HBM."""
    from ..ops import forest_mxu

    n_pad = bins_fm.shape[1]

    @partial(jax.jit, static_argnames=("n_pad",))
    def _layout(stats, weight, n_pad):
        pad = n_pad - stats.shape[0]
        st = jnp.pad(stats, ((0, pad), (0, 0))).T  # (S_in, n_pad)
        w = jnp.pad(weight, (0, pad))
        return st, w

    st_fm, w_pad = _layout(stats, inputs.weight, n_pad)
    if is_classification:
        base_stats, stats3 = st_fm, None
        # class index per row (deep phase rebuilds one-hot stats post-sort)
        y_vals = jnp.argmax(st_fm, axis=0).astype(jnp.float32)
    else:
        # stats rows are (1, y, y^2)*mask; split search needs only (w, wy)
        base_stats, stats3 = st_fm[:2], st_fm
        y_vals = st_fm[1]
    key = jax.random.PRNGKey((seed + 104729) & 0x7FFFFFFF)
    if bootstrap:
        bw = jax.random.poisson(key, 1.0, (n_trees, n_pad)).astype(w_pad.dtype)
        w_trees = w_pad[None, :] * bw
    else:
        w_trees = jnp.broadcast_to(w_pad[None, :], (n_trees, n_pad))
    return forest_mxu.grow_forest_mxu(
        bins_fm, base_stats, w_trees, stats3, edges,
        max_depth=max_depth, n_bins=n_bins, kind=kind,
        max_features=int(max_features),
        min_samples_leaf=min_samples_leaf,
        min_impurity_decrease=min_impurity_decrease,
        seed=seed, y_vals=y_vals,
    )


class _RandomForestClass(_TpuParams):
    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {
            "maxBins": "n_bins",
            "maxDepth": "max_depth",
            "numTrees": "n_estimators",
            "impurity": "split_criterion",
            "featureSubsetStrategy": "max_features",
            "bootstrap": "bootstrap",
            "seed": "random_state",
            "minInstancesPerNode": "min_samples_leaf",
            "minInfoGain": "",
            "maxMemoryInMB": "",
            "cacheNodeIds": "",
            "checkpointInterval": "",
            "subsamplingRate": "",
            "minWeightFractionPerNode": "",
            "weightCol": None,
            "leafCol": None,
        }

    @classmethod
    def _param_value_mapping(cls):
        def _subset_mapping(v):
            maybe = _str_or_numerical(v) if isinstance(v, str) else v
            if isinstance(maybe, (int, float)) and not isinstance(maybe, bool):
                return maybe
            return {
                "onethird": 1 / 3.0,
                "all": 1.0,
                "auto": "auto",
                "sqrt": "sqrt",
                "log2": "log2",
            }.get(maybe)

        return {"max_features": _subset_mapping}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_estimators": 100,
            "max_depth": 16,
            "max_features": "auto",
            "n_bins": 128,
            "bootstrap": True,
            "verbose": False,
            "min_samples_leaf": 1,
            "min_samples_split": 2,
            "max_samples": 1.0,
            "max_leaves": -1,
            "min_impurity_decrease": 0.0,
            "random_state": None,
            "max_batch_size": 4096,
        }


class _RandomForestParams(
    _RandomForestClass,
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasPredictionCol,
    HasSeed,
    HasWeightCol,
    HasVerbose,
):
    numTrees = Param(_dummy(), "numTrees", "number of trees to train (>= 1)", TypeConverters.toInt)
    maxDepth = Param(_dummy(), "maxDepth", "maximum depth of the tree (>= 0, <= 16)", TypeConverters.toInt)
    maxBins = Param(_dummy(), "maxBins", "max number of bins for discretizing continuous features", TypeConverters.toInt)
    impurity = Param(_dummy(), "impurity", "criterion used for information gain calculation", TypeConverters.toString)
    featureSubsetStrategy = Param(_dummy(), "featureSubsetStrategy", "number of features to consider per split (auto|all|onethird|sqrt|log2|n|fraction)", TypeConverters.toString)
    bootstrap = Param(_dummy(), "bootstrap", "whether bootstrap samples are used", TypeConverters.toBoolean)
    minInstancesPerNode = Param(_dummy(), "minInstancesPerNode", "minimum number of instances each child must have after split", TypeConverters.toInt)
    minInfoGain = Param(_dummy(), "minInfoGain", "minimum information gain for a split (ignored)", TypeConverters.toFloat)
    subsamplingRate = Param(_dummy(), "subsamplingRate", "fraction of data used per tree (ignored)", TypeConverters.toFloat)
    maxMemoryInMB = Param(_dummy(), "maxMemoryInMB", "max memory for histogram aggregation (ignored)", TypeConverters.toInt)
    cacheNodeIds = Param(_dummy(), "cacheNodeIds", "ignored", TypeConverters.toBoolean)
    checkpointInterval = Param(_dummy(), "checkpointInterval", "ignored", TypeConverters.toInt)
    minWeightFractionPerNode = Param(_dummy(), "minWeightFractionPerNode", "ignored", TypeConverters.toFloat)

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(
            numTrees=20,
            maxDepth=5,
            maxBins=32,
            featureSubsetStrategy="auto",
            bootstrap=True,
            minInstancesPerNode=1,
            minInfoGain=0.0,
            subsamplingRate=1.0,
            maxMemoryInMB=256,
            cacheNodeIds=False,
            checkpointInterval=10,
            minWeightFractionPerNode=0.0,
        )

    def setNumTrees(self, value: int):
        return self._set_params(numTrees=value)

    def setMaxDepth(self, value: int):
        return self._set_params(maxDepth=value)

    def setMaxBins(self, value: int):
        return self._set_params(maxBins=value)

    def setImpurity(self, value: str):
        return self._set_params(impurity=value)

    def setFeatureSubsetStrategy(self, value: str):
        return self._set_params(featureSubsetStrategy=value)

    def setSeed(self, value: int):
        return self._set_params(seed=value)

    def getNumTrees(self) -> int:
        return self.getOrDefault("numTrees")

    def getMaxDepth(self) -> int:
        return self.getOrDefault("maxDepth")

    def getMaxBins(self) -> int:
        return self.getOrDefault("maxBins")


def _resolve_max_features(value: Any, n_cols: int, is_classification: bool, n_trees: int) -> int:
    """Spark featureSubsetStrategy semantics: auto = all when numTrees == 1,
    else sqrt (classification) / onethird (regression)."""
    if value == "auto" or value is None:
        if n_trees == 1:
            return n_cols
        return (
            max(1, int(math.sqrt(n_cols)))
            if is_classification
            else max(1, int(n_cols / 3.0))
        )
    if value == "sqrt":
        return max(1, int(math.sqrt(n_cols)))
    if value == "log2":
        return max(1, int(math.log2(n_cols)))
    if isinstance(value, float):
        return max(1, min(n_cols, int(value * n_cols)))
    return max(1, min(n_cols, int(value)))


class _RandomForestEstimator(_RandomForestParams, _TpuEstimatorSupervised):
    _is_classification = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._initialize_tpu_params()
        self._set_params(**kwargs)

    # binning samples per-rank local shards + control-plane gather
    # (_binning_sample) and label stats encode on device (ops/labels.py +
    # jax.nn.one_hot over the sharded labels), so the whole fit runs on a
    # multi-process mesh — and unlike the reference's per-worker tree
    # subsets over per-worker data shards (tree.py:256-267,292-397), every
    # tree here trains on the FULL global dataset with Poisson bootstrap
    # weights under GSPMD
    _supports_multicontroller_fit = True

    def _enable_fit_multiple_in_single_pass(self) -> bool:
        return True

    def _supportsTransformEvaluate(self, evaluator: Any) -> bool:
        from ..evaluation import (
            MulticlassClassificationEvaluator,
            RegressionEvaluator,
        )

        if self._is_classification:
            return isinstance(evaluator, MulticlassClassificationEvaluator)
        return isinstance(evaluator, RegressionEvaluator)

    def _encode_labels(self, y: np.ndarray, valid: np.ndarray):
        raise NotImplementedError

    def _get_tpu_fit_func(self, dataset: DataFrame, extra_params=None):
        logger = get_logger(type(self))
        is_classification = self._is_classification

        def _single_fit(
            inputs: FitInputs, params: Dict[str, Any], get_bins, edges, stats, extra_attrs
        ) -> Dict[str, Any]:
            max_depth = int(params["max_depth"])
            if max_depth > _MAX_SUPPORTED_DEPTH:
                raise ValueError(
                    f"maxDepth > {_MAX_SUPPORTED_DEPTH} is not supported by the dense "
                    f"TPU tree layout (got {max_depth})"
                )
            n_trees = int(params["n_estimators"])
            n_bins = int(params["n_bins"])
            criterion = params.get("split_criterion")
            kind = (
                "regression"
                if not is_classification
                else ("entropy" if criterion == "entropy" else "gini")
            )
            max_features = _resolve_max_features(
                params.get("max_features", "auto"),
                inputs.n_cols,
                is_classification,
                n_trees,
            )
            seed = params.get("random_state")
            seed = int(seed) & 0x7FFFFFFF if seed is not None else 42
            bootstrap = bool(params.get("bootstrap", True))
            grow_kwargs = dict(
                max_depth=max_depth,
                n_bins=n_bins,
                kind=kind,
                max_features=max_features,
                min_samples_leaf=float(params.get("min_samples_leaf", 1)),
                min_impurity_decrease=float(
                    params.get("min_impurity_decrease", 0.0)
                ),
            )
            key = jax.random.PRNGKey(seed)
            s_split = 2 if not is_classification else stats.shape[1]
            if _mxu_eligible(inputs, n_bins, max_features, max_depth, s_split):
                mxu = _maybe_grow_mxu(
                    inputs, get_bins("fm", edges), edges, stats, n_trees,
                    bootstrap, seed, is_classification, **grow_kwargs,
                )
                features, thresholds, leaf_values, node_counts, impurities = mxu
                logger.info(
                    "grew %d trees on the MXU histogram path (depth<=%d, "
                    "bins=%d)", n_trees, max_depth, n_bins,
                )
                attrs = {
                    "features_": features,
                    "thresholds_": thresholds,
                    "leaf_values_": leaf_values,
                    "node_counts_": node_counts,
                    "impurities_": impurities,
                    "max_depth": max_depth,
                    "n_cols": inputs.n_cols,
                    "dtype": str(inputs.dtype),
                }
                attrs.update(extra_attrs)
                return attrs
            # Mesh-parallel engine growth (ops/forest.grow_forest): trees
            # ride the scan-batched level-block kernels in CHUNKS sized so
            # the (combined, D) per-node feature-subset scores at the
            # deepest level and the (Tc, N, S) per-tree stats tensor each
            # stay within budget.  The old per-tree grow_tree fallback —
            # one host level-loop per tree plus five np.asarray device
            # fetches per tree when stacking — is gone: a chunk of ONE
            # tree still runs the batched engine with its single fetch.
            n_pad = inputs.X.shape[0]
            t_sub = (
                max(1, (512 << 20) // max(1, (2**max_depth) * inputs.n_cols * 4))
                if max_features < inputs.n_cols
                else n_trees
            )
            t_stats = max(1, (2 << 30) // max(1, n_pad * stats.shape[1] * 4))
            t_chunk = max(1, min(n_trees, t_sub, t_stats))
            # stage the level-block kernel compiles on the precompile pool
            # BEFORE binning runs, so XLA compiles while rows are binned.
            # The tree count rides every kernel aval shape, so a partial
            # final chunk is its own geometry — warm it too, or its blocks
            # cold-compile serially at the end of the fit
            warm_forest_kernels(
                n_pad, inputs.n_cols, t_chunk, stats.shape[1],
                mesh=inputs.mesh, dtype=stats.dtype, **grow_kwargs,
            )
            t_rem = n_trees % t_chunk
            if t_rem:
                warm_forest_kernels(
                    n_pad, inputs.n_cols, t_rem, stats.shape[1],
                    mesh=inputs.mesh, dtype=stats.dtype, **grow_kwargs,
                )
            Xb = get_bins("rm", edges)
            parts = []
            for t0 in range(0, n_trees, t_chunk):
                tc = min(t_chunk, n_trees - t0)
                key, kt = jax.random.split(key)
                stats_t = _per_tree_stats(stats, inputs.weight, kt, tc, bootstrap)
                parts.append(
                    grow_forest(
                        Xb, stats_t, edges,
                        seed=(seed + 7919 * t0) & 0x7FFFFFFF,
                        mesh=inputs.mesh, **grow_kwargs,
                    )
                )
            if len(parts) == 1:
                features, thresholds, leaf_values, node_counts, impurities = parts[0]
            else:
                features, thresholds, leaf_values, node_counts, impurities = (
                    np.concatenate([p[i] for p in parts]) for i in range(5)
                )
            logger.info("grew %d trees (depth<=%d, bins=%d)", n_trees, max_depth, n_bins)
            attrs = {
                "features_": features,
                "thresholds_": thresholds,
                "leaf_values_": leaf_values,
                "node_counts_": node_counts,
                "impurities_": impurities,
                "max_depth": max_depth,
                "n_cols": inputs.n_cols,
                "dtype": str(inputs.dtype),
            }
            attrs.update(extra_attrs)
            return attrs

        def _fit(inputs: FitInputs, params: Dict[str, Any]):
            assert inputs.y is not None
            n_bins = int(params["n_bins"])
            # quantile edges from a bounded strided row sample (a full
            # np.asarray(inputs.X) round-trips the whole dataset over the
            # host link — 4.8 GB at the benchmark shape — and raises
            # outright multi-process).  Single-rank TPU fits keep the
            # sample on device and sort there (only the 1.5 MB edge
            # matrix crosses the link); multi-rank/CPU fits take the host
            # gather path.
            X_host = None
            with profiling.phase("forest.bin"):
                sample_dev = _binning_sample_device(inputs)
                if sample_dev is not None:
                    edges = compute_bin_edges_device(sample_dev, n_bins)
                else:
                    X_host = _binning_sample(inputs)
                    edges = compute_bin_edges(X_host, n_bins)

            # Lazy per-route binning: the MXU route bins straight into the
            # feature-major int8 layout (bin_features_feature_major), the
            # scatter route row-major.  Binning eagerly row-major and
            # re-laying-out kept TWO full bin matrices resident — the copy
            # that OOM'd the 400k x 3000 depth-13 benchmark fit.  The cache
            # holds the edges OBJECT alongside each entry (id() alone can
            # be recycled after gc) and keeps only the CURRENT edges' bin
            # matrices — distinct-n_bins sweeps drop the previous matrices
            # instead of accumulating one full-size copy per override.
            bins_cache: Dict[str, Any] = {}

            def get_bins(layout: str, e):
                cached = bins_cache.get(layout)
                if cached is not None and cached[0] is e:
                    return cached[1]
                if any(held[0] is not e for held in bins_cache.values()):
                    bins_cache.clear()  # new edges: old matrices are dead
                with profiling.phase("forest.bin"):
                    if layout == "fm":
                        from ..ops.forest_hist import _ROW_TILE

                        n = inputs.X.shape[0]
                        n_pad = -(-n // _ROW_TILE) * _ROW_TILE
                        out = bin_features_feature_major(
                            inputs.X, jnp.asarray(e), n_pad=n_pad
                        )
                    else:
                        out = bin_features(inputs.X, jnp.asarray(e))
                bins_cache[layout] = (e, out)
                return out

            stats, extra_attrs = self._label_stats(inputs)
            if extra_params:
                results = []
                for override in extra_params:
                    p = dict(params)
                    p.update(override)
                    if int(p["n_bins"]) != n_bins:
                        e2 = (
                            compute_bin_edges_device(
                                sample_dev, int(p["n_bins"])
                            )
                            if sample_dev is not None
                            else compute_bin_edges(X_host, int(p["n_bins"]))
                        )
                        results.append(
                            _single_fit(inputs, p, get_bins, e2, stats, extra_attrs)
                        )
                    else:
                        results.append(
                            _single_fit(inputs, p, get_bins, edges, stats, extra_attrs)
                        )
                return results
            return _single_fit(inputs, params, get_bins, edges, stats, extra_attrs)

        return _fit

    def _label_stats(self, inputs: FitInputs):
        raise NotImplementedError


class _RandomForestModelBase(_RandomForestParams, _TpuModelWithPredictionCol):
    """Shared forest model: dense arrays + vectorized traversal predict.

    A _combine'd multi-model stores every sub-model's trees concatenated
    along the tree axis with `_tree_counts` recording the per-model counts
    (the reference concatenates treelite handles the same way, tree.py:592);
    it only supports _transformEvaluate, not transform."""

    @property
    def _num_models(self) -> int:
        counts = getattr(self, "_tree_counts", None)
        return len(counts) if counts else 1

    @classmethod
    def _construct(cls, attrs):
        """A combined multi-model's sub-model split ('tree_counts') is an
        attribute, not a constructor argument — reattach it so the
        combined structure survives executor serialization and npz
        persistence."""
        tc = attrs.pop("tree_counts", None)
        model = cls(**attrs)
        if tc is not None:
            counts = [int(c) for c in np.asarray(tc).tolist()]
            model._tree_counts = counts
            model._model_attributes["tree_counts"] = counts
        return model

    @classmethod
    def _combine(cls, models: List["_RandomForestModelBase"]) -> "_RandomForestModelBase":
        assert models and all(isinstance(m, cls) for m in models)
        first = models[0]
        assert all(m.n_cols == first.n_cols for m in models)
        V = first.leaf_values_.shape[2]
        assert all(m.leaf_values_.shape[2] == V for m in models), (
            "cannot combine forests with different value widths"
        )
        # dense layouts may differ in depth (maxDepth in the param grid):
        # shallower trees embed unchanged in the deeper node indexing, so
        # pad every model's node axis to the largest layout
        M_max = max(m.features_.shape[1] for m in models)

        def pad_nodes(a: np.ndarray, fill=0) -> np.ndarray:
            if a.shape[1] == M_max:
                return a
            width = [(0, 0), (0, M_max - a.shape[1])] + [(0, 0)] * (a.ndim - 2)
            return np.pad(a, width, constant_values=fill)

        kwargs = dict(
            features_=np.concatenate([pad_nodes(m.features_, -1) for m in models]),
            thresholds_=np.concatenate([pad_nodes(m.thresholds_) for m in models]),
            leaf_values_=np.concatenate([pad_nodes(m.leaf_values_) for m in models]),
            node_counts_=np.concatenate([pad_nodes(m.node_counts_) for m in models]),
            impurities_=np.concatenate([pad_nodes(m.impurities_) for m in models]),
            max_depth=max(int(m.max_depth) for m in models),
            n_cols=first.n_cols,
            dtype=first.dtype,
        )
        if hasattr(first, "classes_"):
            assert all(
                np.array_equal(m.classes_, first.classes_) for m in models
            ), "cannot combine classifiers fit on different label sets"
            kwargs.update(classes_=first.classes_, num_classes=first.num_classes)
        combined = cls(**kwargs)
        combined._tree_counts = [m.features_.shape[0] for m in models]
        # record the split in the ATTRIBUTES too: serialize_model ships
        # _get_model_attributes() to the executors, and a combined model
        # arriving there without its sub-model split would score as ONE
        # forest (core._construct_model reattaches it)
        combined._model_attributes["tree_counts"] = combined._tree_counts
        first._copyValues(combined)
        combined._tpu_params.update(first._tpu_params)
        combined._float32_inputs = first._float32_inputs
        return combined

    def _per_model_values(self, features: np.ndarray) -> List[np.ndarray]:
        """Mean leaf values per sub-model, one (N, V) array each — a single
        device pass per sub-model tree slice over one resident feature batch."""
        features = np.atleast_2d(np.asarray(features))
        if features.shape[1] != self.n_cols:
            raise ValueError(
                f"feature width {features.shape[1]} != model n_cols {self.n_cols}"
            )
        np_dtype = self._transform_dtype(self.dtype)
        f, t, v = self._forest_arrays()
        n = features.shape[0]
        # pad the batch to its power-of-two row bucket ONCE, outside the
        # sub-model loop — a combined CV model would otherwise re-pad the
        # identical feature matrix per tree slice
        from ..ops.precompile import shape_bucket

        b = shape_bucket(n)
        feats_np = np.asarray(features, np_dtype)
        if b != n:
            feats_np = np.pad(feats_np, ((0, b - n), (0, 0)))
        feats_dev = jax.device_put(feats_np)
        counts = getattr(self, "_tree_counts", None) or [self.features_.shape[0]]
        out, off = [], 0
        for c in counts:
            sl = slice(off, off + c)
            off += c
            # cached-executable dispatch with power-of-two row bucketing:
            # repeat transforms at any partition size reuse one executable
            # per bucket instead of compiling per distinct batch length
            out.append(
                forest_predict_cached(
                    feats_dev, f[sl], t[sl], v[sl],
                    max_depth=int(self.max_depth),
                )[:n]
            )
        # dispatch every sub-model's kernel first, then ONE batched fetch: a
        # per-slice np.asarray blocked dispatch on each device round-trip
        return list(jax.device_get(out))

    def _forest_arrays(self):
        np_dtype = self._transform_dtype(self.dtype)
        return (
            jnp.asarray(self.features_),
            jnp.asarray(self.thresholds_.astype(np_dtype)),
            jnp.asarray(self.leaf_values_),
        )

    def _predict_values(self, features: np.ndarray) -> np.ndarray:
        assert self._num_models == 1, (
            "transform() on a combined multi-model is unsupported; use "
            "_transformEvaluate"
        )
        return self._per_model_values(features)[0]

    def _serving_values_entry(self, postprocess, out_cols: List[str]):
        """Shared serving plumbing for both forest models: the mean-leaf-
        values traversal dispatched under the SAME 'forest_predict' cache
        name and statics as the batch transform path (ops/forest.
        forest_predict_cached), so serving and transform share compiled
        executables wherever their row buckets coincide."""
        assert self._num_models == 1, "combined multi-models are not servable"
        from ..ops.forest import forest_predict_kernel
        from ..serving.entry import kernel_entry

        f, t, v = self._forest_arrays()
        return kernel_entry(
            "forest_predict",
            forest_predict_kernel,  # module-level jit, static max_depth
            (f, t, v),
            {"max_depth": int(self.max_depth)},
            postprocess,
            dtype=self._transform_dtype(self.dtype),
            n_cols=self.n_cols,
            out_cols=out_cols,
            info={"num_trees": int(self.features_.shape[0])},
        )

    @property
    def getNumTrees(self) -> int:  # property for pyspark API parity
        return self.features_.shape[0]

    @property
    def treeWeights(self) -> List[float]:
        return [1.0] * self.features_.shape[0]

    @property
    def totalNumNodes(self) -> int:
        return int((self.features_ >= 0).sum() * 2 + (self.features_ >= 0).shape[0])

    def trees_to_dicts(self) -> List[Dict[str, Any]]:
        """Portable nested-dict forest export — the role the reference's
        treelite JSON plays for translate_trees (utils.py:385-447).

        The dense node arrays are converted to Python lists ONCE per forest
        (vectorized tolist) before the per-node walk: numpy scalar getitem
        inside the recursion costs ~1 us x 5 arrays x 131k nodes per
        depth-16 tree, which is felt the first time a 100-tree forest goes
        through cpu()."""
        feats = np.asarray(self.features_).tolist()
        thr = np.asarray(self.thresholds_).tolist()
        leaf = np.asarray(self.leaf_values_).tolist()
        cnt = np.asarray(self.node_counts_).tolist()
        imp = np.asarray(self.impurities_).tolist()
        out = []
        for t in range(len(feats)):
            f, th, lv, ct, im = feats[t], thr[t], leaf[t], cnt[t], imp[t]

            def node_dict(i: int) -> Dict[str, Any]:
                if f[i] < 0:
                    return {
                        "leaf_value": lv[i],
                        "instance_count": float(ct[i]),
                    }
                return {
                    "split_feature": int(f[i]),
                    "threshold": float(th[i]),
                    "gain": float(im[i]),
                    "instance_count": float(ct[i]),
                    "yes": node_dict(2 * i + 1),
                    "no": node_dict(2 * i + 2),
                }

            out.append(node_dict(0))
        return out


class RandomForestClassifier(_RandomForestEstimator):
    """Distributed random-forest classifier (API parity with
    classification.py:307-513)."""

    _is_classification = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._setDefault(impurity="gini")
        if "impurity" not in kwargs:
            self._set_tpu_value("split_criterion", "gini")

    @classmethod
    def _param_value_mapping(cls):
        mapping = dict(super()._param_value_mapping())
        mapping["split_criterion"] = lambda x: {"gini": "gini", "entropy": "entropy"}.get(x)
        return mapping

    def _label_stats(self, inputs: FitInputs):
        from ..core import discover_label_classes
        from ..ops.labels import encode_labels_kernel

        # int32 label cast parity (classification.py:483-496); discovery is
        # per-rank local + control-plane union, encode + one-hot stay on
        # device preserving the row sharding (multi-process safe)
        classes = discover_label_classes(inputs, cast=np.int32)
        y_idx = encode_labels_kernel(
            inputs.y.astype(jnp.int32), jnp.asarray(classes)
        )
        onehot = jax.nn.one_hot(y_idx, len(classes), dtype=inputs.X.dtype)
        return onehot, {"classes_": classes.astype(np.float64), "num_classes": len(classes)}

    def _create_model(self, result: Dict[str, Any]) -> "RandomForestClassificationModel":
        return RandomForestClassificationModel(**result)


class RandomForestClassificationModel(
    HasProbabilityCol,
    HasRawPredictionCol,
    _ClassificationModelEvaluationMixIn,
    _RandomForestModelBase,
):
    def __init__(
        self,
        features_: np.ndarray,
        thresholds_: np.ndarray,
        leaf_values_: np.ndarray,
        node_counts_: np.ndarray,
        impurities_: np.ndarray,
        max_depth: int,
        n_cols: int,
        dtype: str,
        classes_: np.ndarray,
        num_classes: int,
    ) -> None:
        super().__init__(
            features_=np.asarray(features_),
            thresholds_=np.asarray(thresholds_),
            leaf_values_=np.asarray(leaf_values_),
            node_counts_=np.asarray(node_counts_),
            impurities_=np.asarray(impurities_),
            max_depth=int(max_depth),
            n_cols=int(n_cols),
            dtype=str(dtype),
            classes_=np.asarray(classes_),
            num_classes=int(num_classes),
        )
        self.features_ = np.asarray(features_)
        self.thresholds_ = np.asarray(thresholds_)
        self.leaf_values_ = np.asarray(leaf_values_)
        self.node_counts_ = np.asarray(node_counts_)
        self.impurities_ = np.asarray(impurities_)
        self.max_depth = int(max_depth)
        self.n_cols = int(n_cols)
        self.dtype = str(dtype)
        self.classes_ = np.asarray(classes_)
        self.num_classes = int(num_classes)

    @property
    def numClasses(self) -> int:
        return self.num_classes

    def _out_columns(self) -> List[str]:
        return [
            self.getOrDefault("predictionCol"),
            self.getOrDefault("probabilityCol"),
            self.getOrDefault("rawPredictionCol"),
        ]

    def _get_tpu_transform_func(self, dataset: DataFrame):
        classes = self.classes_
        n_trees = self.features_.shape[0]
        pred_col = self.getOrDefault("predictionCol")
        prob_col = self.getOrDefault("probabilityCol")
        raw_col = self.getOrDefault("rawPredictionCol")

        def _transform(features: np.ndarray) -> Dict[str, Any]:
            probs = self._predict_values(features)  # (N, C) mean leaf distributions
            probs = probs / np.maximum(probs.sum(axis=1, keepdims=True), 1e-12)
            idx = probs.argmax(axis=1)
            return {
                pred_col: classes[idx].astype(np.float64),
                prob_col: probs.astype(np.float64),
                raw_col: (probs * n_trees).astype(np.float64),
            }

        return _transform

    def _serving_entry(self, mesh: Any = None):
        """Online inference hook (serving/): one forest traversal per
        coalesced batch; class mapping and normalization on host, matching
        transform() exactly."""
        classes = self.classes_
        n_trees = self.features_.shape[0]
        pred_col = self.getOrDefault("predictionCol")
        prob_col = self.getOrDefault("probabilityCol")
        raw_col = self.getOrDefault("rawPredictionCol")

        def _post(values) -> Dict[str, Any]:
            probs = np.asarray(values)
            probs = probs / np.maximum(probs.sum(axis=1, keepdims=True), 1e-12)
            idx = probs.argmax(axis=1)
            return {
                pred_col: classes[idx].astype(np.float64),
                prob_col: probs.astype(np.float64),
                raw_col: (probs * n_trees).astype(np.float64),
            }

        return self._serving_values_entry(_post, [pred_col, prob_col, raw_col])

    def _get_eval_predict_func(self):
        classes = self.classes_

        def _predict_all(feats: np.ndarray):
            preds, probs = [], []
            for p in self._per_model_values(feats):
                p = p / np.maximum(p.sum(axis=1, keepdims=True), 1e-12)
                probs.append(p)
                preds.append(classes[p.argmax(axis=1)].astype(np.float64))
            return np.stack(preds), np.stack(probs)

        return _predict_all

    def predict(self, value: np.ndarray) -> float:
        probs = self._predict_values(np.asarray(value)[None, :])
        return float(self.classes_[int(probs[0].argmax())])

    def predictProbability(self, value: np.ndarray) -> np.ndarray:
        probs = self._predict_values(np.asarray(value)[None, :])[0]
        return probs / max(probs.sum(), 1e-12)

    def _transformEvaluate(self, dataset: Any, evaluator: Any, params=None) -> List[float]:
        return self._transform_evaluate(dataset, evaluator, self._num_models)

    def cpu(self):
        """Convert to pyspark.ml RandomForestClassificationModel via py4j
        tree construction (parity with tree.py:507-553 + classification.py
        cpu()); requires pyspark + an active SparkSession."""
        from ..spark.interop import to_spark_random_forest_model

        return to_spark_random_forest_model(self)


class RandomForestRegressor(_RandomForestEstimator):
    """Distributed random-forest regressor (API parity with
    regression.py:795-968)."""

    _is_classification = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._setDefault(impurity="variance")
        if "impurity" not in kwargs:
            self._set_tpu_value("split_criterion", "variance")

    @classmethod
    def _param_value_mapping(cls):
        mapping = dict(super()._param_value_mapping())
        mapping["split_criterion"] = lambda x: {"variance": "variance", "mse": "variance"}.get(x)
        return mapping

    def _label_stats(self, inputs: FitInputs):
        y = inputs.y
        stats = jnp.stack([jnp.ones_like(y), y, y * y], axis=1)
        return stats, {}

    def _create_model(self, result: Dict[str, Any]) -> "RandomForestRegressionModel":
        return RandomForestRegressionModel(**result)


class RandomForestRegressionModel(
    _RegressionModelEvaluationMixIn, _RandomForestModelBase
):
    def __init__(
        self,
        features_: np.ndarray,
        thresholds_: np.ndarray,
        leaf_values_: np.ndarray,
        node_counts_: np.ndarray,
        impurities_: np.ndarray,
        max_depth: int,
        n_cols: int,
        dtype: str,
    ) -> None:
        super().__init__(
            features_=np.asarray(features_),
            thresholds_=np.asarray(thresholds_),
            leaf_values_=np.asarray(leaf_values_),
            node_counts_=np.asarray(node_counts_),
            impurities_=np.asarray(impurities_),
            max_depth=int(max_depth),
            n_cols=int(n_cols),
            dtype=str(dtype),
        )
        self.features_ = np.asarray(features_)
        self.thresholds_ = np.asarray(thresholds_)
        self.leaf_values_ = np.asarray(leaf_values_)
        self.node_counts_ = np.asarray(node_counts_)
        self.impurities_ = np.asarray(impurities_)
        self.max_depth = int(max_depth)
        self.n_cols = int(n_cols)
        self.dtype = str(dtype)

    def _get_tpu_transform_func(self, dataset: DataFrame):
        pred_col = self.getOrDefault("predictionCol")

        def _transform(features: np.ndarray) -> Dict[str, Any]:
            preds = self._predict_values(features)[:, 0]
            return {pred_col: preds.astype(np.float64)}

        return _transform

    def _serving_entry(self, mesh: Any = None):
        """Online inference hook (serving/): one forest traversal per
        coalesced batch, first value column as the regression prediction."""
        pred_col = self.getOrDefault("predictionCol")
        return self._serving_values_entry(
            lambda values: {
                pred_col: np.asarray(values)[:, 0].astype(np.float64)
            },
            [pred_col],
        )

    def _get_eval_predict_func(self) -> Callable[[np.ndarray], np.ndarray]:
        def _predict_all(feats: np.ndarray) -> np.ndarray:
            return np.stack(
                [p[:, 0].astype(np.float64) for p in self._per_model_values(feats)]
            )

        return _predict_all

    def predict(self, value: np.ndarray) -> float:
        return float(self._predict_values(np.asarray(value)[None, :])[0, 0])

    def _transformEvaluate(self, dataset: Any, evaluator: Any, params=None) -> List[float]:
        return self._transform_evaluate(dataset, evaluator, self._num_models)

    def cpu(self):
        """Convert to pyspark.ml RandomForestRegressionModel via py4j tree
        construction (parity with tree.py:507-553 + regression.py cpu());
        requires pyspark + an active SparkSession."""
        from ..spark.interop import to_spark_random_forest_model

        return to_spark_random_forest_model(self)
