#
# ApproximateNearestNeighbors estimator/model (IVF-Flat).
#
# Param-surface parity with the reference's ApproximateNearestNeighbors
# (cuML algorithm='ivfflat', algoParams={'nlist', 'nprobe'}): fit TRAINS the
# coarse quantizer and packs the inverted lists (unlike the exact
# NearestNeighbors, whose fit only captures the frame — an ANN index is a
# real artifact), kneighbors runs the probed search, and `exactSearch=True`
# routes through the exact brute-force engine over the same packed items (a
# recall-vs-latency escape hatch that shares ids with the probed path).
# Unlike the exact model, this model IS persistable: the packed index
# (items sorted by list, ids, per-list counts, centroids) rides the core
# npz persistence path and restages onto whatever mesh loads it.
#

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np
import pandas as pd

from ..ann.ivfflat import (
    PackedIVF,
    build_ivfflat_packed,
    default_nlist,
    default_nprobe,
    index_from_packed,
    ivfflat_search_prepared,
    warm_probe_kernels,
)
from ..core import _TpuEstimatorSupervised, _TpuModel
from ..dataframe import DataFrame, as_dataframe
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    Param,
    TypeConverters,
    _dummy,
    _TpuParams,
)
from ..parallel.mesh import get_mesh

_ALGO_PARAM_KEYS = {"nlist", "nprobe"}


class ApproximateNearestNeighborsClass(_TpuParams):
    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {"k": "n_neighbors", "algorithm": "algorithm"}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_neighbors": 5,
            "verbose": False,
            "algorithm": "ivfflat",
            "metric": "euclidean",
        }


class _ApproximateNearestNeighborsParams(
    ApproximateNearestNeighborsClass, HasFeaturesCol, HasFeaturesCols
):
    k = Param(_dummy(), "k", "the number of nearest neighbors to retrieve (> 0)", TypeConverters.toInt)
    idCol = Param(_dummy(), "idCol", "id column name; if unset a monotonically increasing id column is generated", TypeConverters.toString)
    algorithm = Param(_dummy(), "algorithm", "the ANN algorithm (only 'ivfflat' is supported)", TypeConverters.toString)
    algoParams = Param(_dummy(), "algoParams", "algorithm parameters: {'nlist': coarse lists, 'nprobe': probed lists per query}", TypeConverters.identity)
    exactSearch = Param(_dummy(), "exactSearch", "route kneighbors through the exact brute-force engine over the indexed items (recall escape hatch)", TypeConverters.toBoolean)

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(k=5, algorithm="ivfflat", exactSearch=False)

    def getK(self) -> int:
        return self.getOrDefault("k")

    def setK(self, value: int):
        return self._set_params(k=value)

    def getIdCol(self) -> str:
        return self.getOrDefault("idCol") if self.isDefined("idCol") else "unique_id"

    def setIdCol(self, value: str):
        self.set(self.getParam("idCol"), value)
        return self

    def getAlgorithm(self) -> str:
        return self.getOrDefault("algorithm")

    def setAlgorithm(self, value: str):
        return self._set_params(algorithm=value)

    def getAlgoParams(self) -> Optional[Dict[str, int]]:
        return self.getOrDefault("algoParams") if self.isDefined("algoParams") else None

    def setAlgoParams(self, value: Dict[str, int]):
        self.set(self.getParam("algoParams"), value)
        return self

    def getExactSearch(self) -> bool:
        return self.getOrDefault("exactSearch")

    def setExactSearch(self, value: bool):
        self.set(self.getParam("exactSearch"), value)
        return self

    def setInputCol(self, value: Union[str, List[str]]):
        if isinstance(value, str):
            self._set_params(featuresCol=value)
        else:
            self._set_params(featuresCols=value)
        return self

    def _resolved_algo_params(self, n_items: int, n_lists: int = None) -> Tuple[int, int]:
        """(nlist, nprobe) with the documented defaults (ann/ivfflat
        default_nlist/default_nprobe) filling unset keys; unknown keys are
        a hard error (a typo'd 'nprobes' must not silently probe 1/4)."""
        ap = dict(self.getAlgoParams() or {})
        unknown = set(ap) - _ALGO_PARAM_KEYS
        if unknown:
            raise ValueError(
                f"unknown algoParams {sorted(unknown)}; supported: "
                f"{sorted(_ALGO_PARAM_KEYS)}"
            )
        nlist = int(ap.get("nlist", n_lists or default_nlist(n_items)))
        nprobe = int(ap.get("nprobe", default_nprobe(nlist)))
        if nlist < 1 or nprobe < 1:
            raise ValueError(
                f"nlist ({nlist}) and nprobe ({nprobe}) must be >= 1"
            )
        return nlist, nprobe

    def _check_algorithm(self) -> None:
        if self.getAlgorithm() != "ivfflat":
            raise ValueError(
                f"algorithm={self.getAlgorithm()!r} is not supported; only "
                "'ivfflat' is implemented (the first ANN tier)"
            )


class ApproximateNearestNeighbors(
    _ApproximateNearestNeighborsParams, _TpuEstimatorSupervised
):
    """IVF-Flat approximate kNN over the TPU mesh (ann/ivfflat.py): the
    kmeans engine trains the coarse quantizer, the fused distance+argmin
    kernel assigns lists, and probed search rides the kNN block pipeline
    with a recall knob (nprobe)."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._initialize_tpu_params()
        self._set_params(**kwargs)

    def _fit(self, dataset: Any) -> "ApproximateNearestNeighborsModel":
        from ..core import _use_executor_path, extract_partition_features

        self._check_algorithm()
        if getattr(dataset, "_device_features", None) is not None:
            raise NotImplementedError(
                "ApproximateNearestNeighbors.fit does not take "
                "DataFrame.from_device frames (their features column is a "
                "placeholder); fit a host frame instead"
            )
        if _use_executor_path(dataset):
            raise NotImplementedError(
                "ApproximateNearestNeighbors builds its index in-process; "
                "collect the pyspark item dataframe (SRML_SPARK_COLLECT=1) "
                "before fitting"
            )
        df = as_dataframe(dataset)
        id_col = self.getIdCol()
        if id_col not in df.columns:
            df = df.with_row_id(id_col)
        self._validate_parameters(df)
        input_col, input_cols = self._get_input_columns()
        feats, ids = [], []
        for part in df.partitions:
            if len(part) == 0:
                continue
            feats.append(
                extract_partition_features(
                    part, input_col, input_cols, np.float32
                )
            )
            ids.append(np.asarray(part[id_col].to_numpy(), np.int64))
        if not feats:
            raise RuntimeError("Dataset is empty; cannot build an IVF index")
        X = np.concatenate(feats) if len(feats) > 1 else feats[0]
        item_ids = np.concatenate(ids) if len(ids) > 1 else ids[0]
        nlist, _nprobe = self._resolved_algo_params(X.shape[0])
        packed = build_ivfflat_packed(X, item_ids, nlist, seed=0)
        model = ApproximateNearestNeighborsModel(
            centroids_=packed.centroids,
            packed_items_=packed.items,
            packed_ids_=packed.ids,
            list_counts_=packed.counts,
            n_lists=packed.n_lists,
            n_items=packed.n_items,
            n_cols=int(X.shape[1]),
            dtype="float32",
        )
        self._copyValues(model)
        model._tpu_params.update(self._tpu_params)
        model._num_workers = self._num_workers
        model._float32_inputs = self._float32_inputs
        model._item_df = df
        return model

    def fit(
        self, dataset: Any, params: Optional[Dict] = None
    ) -> "ApproximateNearestNeighborsModel":
        return self._fit(dataset)

    def _get_tpu_fit_func(self, dataset, extra_params=None):  # pragma: no cover
        raise NotImplementedError("ApproximateNearestNeighbors overrides _fit")

    def _create_model(self, result):  # pragma: no cover
        raise NotImplementedError("ApproximateNearestNeighbors overrides _fit")


class ApproximateNearestNeighborsModel(
    _ApproximateNearestNeighborsParams, _TpuModel
):
    """A fitted IVF-Flat index.  Persistable through the core npz path (the
    packed layout is mesh-independent; staging expands it per mesh); a
    loaded model answers kneighbors without the original item frame."""

    def __init__(
        self,
        centroids_: np.ndarray,
        packed_items_: np.ndarray,
        packed_ids_: np.ndarray,
        list_counts_: np.ndarray,
        n_lists: int,
        n_items: int,
        n_cols: int,
        dtype: str = "float32",
    ) -> None:
        super().__init__(
            centroids_=np.asarray(centroids_),
            packed_items_=np.asarray(packed_items_),
            packed_ids_=np.asarray(packed_ids_),
            list_counts_=np.asarray(list_counts_),
            n_lists=int(n_lists),
            n_items=int(n_items),
            n_cols=int(n_cols),
            dtype=str(dtype),
        )
        self.centroids_ = np.asarray(centroids_, np.float32)
        self.packed_items_ = np.asarray(packed_items_, np.float32)
        self.packed_ids_ = np.asarray(packed_ids_, np.int64)
        self.list_counts_ = np.asarray(list_counts_, np.int64)
        self.n_lists = int(n_lists)
        self.n_items = int(n_items)
        self.n_cols = int(n_cols)
        self.dtype = str(dtype)
        self._item_df: Optional[DataFrame] = None
        # per-mesh staging caches (die with the model, like the exact
        # model's _staged_items): the probed index and the exactSearch
        # prepared item set
        self._staged_index: Optional[Tuple[Any, Any]] = None
        self._staged_exact: Optional[Tuple[Any, Any]] = None

    def _packed(self) -> PackedIVF:
        return PackedIVF(
            self.packed_items_,
            self.packed_ids_,
            self.list_counts_,
            self.centroids_,
            self.n_lists,
            self.n_items,
        )

    def _mesh_key(self, mesh) -> Tuple:
        from ..ops.precompile import mesh_fingerprint

        # value identity, not object identity: get_mesh builds fresh Mesh
        # objects per call, and a re-staged identical mesh must HIT
        return mesh_fingerprint(mesh)

    def _ensure_staged_index(self, mesh):
        key = self._mesh_key(mesh)
        if self._staged_index is None or self._staged_index[0] != key:
            self._staged_index = (key, index_from_packed(self._packed(), mesh))
        return self._staged_index[1]

    def _ensure_staged_exact(self, mesh):
        from ..ops.knn import prepare_items

        key = self._mesh_key(mesh)
        if self._staged_exact is None or self._staged_exact[0] != key:
            self._staged_exact = (
                key,
                prepare_items(self.packed_items_, self.packed_ids_, mesh),
            )
        return self._staged_exact[1]

    def kneighbors(
        self, query_df: Any
    ) -> Tuple[Optional[DataFrame], DataFrame, DataFrame]:
        """Probed approximate k nearest items for every query row (float32
        euclidean, same output frame as the exact model's kneighbors:
        (item_df — None on a loaded model, query_df_withid, knn_df with
        query_<id>, indices, distances)).  exactSearch=True routes through
        the exact engine over the same indexed items, so the two paths
        share the id space and recall_at_k can gate one against the
        other."""
        from ..core import _is_pyspark_dataframe, extract_partition_features

        self._check_algorithm()
        if _is_pyspark_dataframe(query_df):
            raise NotImplementedError(
                "ApproximateNearestNeighborsModel serves in-process query "
                "frames; collect the pyspark frame (SRML_SPARK_COLLECT=1) "
                "first"
            )
        qdf = as_dataframe(query_df)
        id_col = self.getIdCol()
        if id_col not in qdf.columns:
            qdf = qdf.with_row_id(id_col)
        input_col, input_cols = self._get_input_columns()
        mesh = get_mesh(self.num_workers)
        k = self.getK()
        _nlist, nprobe = self._resolved_algo_params(
            self.n_items, n_lists=self.n_lists
        )
        exact = self.getExactSearch()
        if exact:
            from ..ops.knn import knn_search_prepared

            prepared = self._ensure_staged_exact(mesh)
        else:
            index = self._ensure_staged_index(mesh)
        from .. import profiling

        out_parts = []
        with profiling.trace_session("search-ApproximateNearestNeighbors"):
            for part in qdf.partitions:
                if len(part) == 0:
                    out_parts.append(
                        pd.DataFrame(
                            {f"query_{id_col}": [], "indices": [], "distances": []}
                        )
                    )
                    continue
                feats = extract_partition_features(
                    part, input_col, input_cols, np.float32
                )
                if exact:
                    dists, ids = knn_search_prepared(prepared, feats, k, mesh)
                else:
                    dists, ids = ivfflat_search_prepared(
                        index, feats, k, nprobe, mesh
                    )
                out_parts.append(
                    pd.DataFrame(
                        {
                            f"query_{id_col}": part[id_col].to_numpy(),
                            "indices": list(np.asarray(ids)),
                            "distances": list(np.asarray(dists, np.float32)),
                        }
                    )
                )
        return self._item_df, qdf, DataFrame(out_parts)

    def _get_tpu_transform_func(self, dataset):  # pragma: no cover
        raise NotImplementedError(
            "ApproximateNearestNeighborsModel has no transform; use "
            "kneighbors instead."
        )

    def _serving_entry(self, mesh: Any = None):
        """Online ANN hook (serving/): each coalesced batch is ONE probed
        ivfflat_search_prepared call against the staged index; warm submits
        the probe-kernel geometry for every engine bucket (the engine's
        pow2 buckets feed the search's own >=64 query-block rule, same
        contract as the exact kNN entry)."""
        from ..serving.entry import ServingEntry

        self._check_algorithm()
        mesh = mesh or get_mesh(self.num_workers)
        index = self._ensure_staged_index(mesh)
        k = self.getK()
        _nlist, nprobe = self._resolved_algo_params(
            self.n_items, n_lists=self.n_lists
        )
        dtype = np.dtype(np.float32)

        def call(batch: np.ndarray) -> Dict[str, np.ndarray]:
            dists, ids = ivfflat_search_prepared(index, batch, k, nprobe, mesh)
            return {
                "indices": np.asarray(ids),
                "distances": np.asarray(dists, dtype=np.float32),
            }

        def warm(buckets) -> list:
            keys = []
            for b in sorted({max(int(x), 64) for x in buckets}):
                keys.extend(
                    warm_probe_kernels(index, k, nprobe, mesh, n_queries=b)
                )
            return keys

        return ServingEntry(
            name="serve.ann",
            n_cols=int(self.n_cols),
            dtype=dtype,
            out_cols=["indices", "distances"],
            call=call,
            warm=warm,
            info={
                "k": int(min(k, self.n_items)),
                "n_items": int(self.n_items),
                "nlist": int(self.n_lists),
                "nprobe": int(nprobe),
            },
        )
