#
# ApproximateNearestNeighbors estimator/model (IVF-Flat + IVF-PQ).
#
# Param-surface parity with the reference's ApproximateNearestNeighbors
# (cuML algorithm='ivfflat'|'ivfpq'; algoParams={'nlist', 'nprobe'} plus
# the PQ keys {'M', 'n_bits', 'usePrecomputedTables'}): fit TRAINS the
# coarse quantizer (and, for ivfpq, the per-subspace codebooks) and packs
# the inverted lists (unlike the exact NearestNeighbors, whose fit only
# captures the frame — an ANN index is a real artifact), kneighbors runs
# the probed search, and `exactSearch=True` routes through the exact
# brute-force engine over the same packed items (a recall-vs-latency
# escape hatch that shares ids with the probed path).  The ivfpq tier
# additionally re-scores its top k*refine_ratio ADC candidates against the
# host-side f32 payload (the same array exactSearch scores) to recover
# recall — the device index itself stays ~32x compressed.  Unlike the
# exact model, this model IS persistable: the packed index rides the core
# npz persistence path and restages onto whatever mesh loads it.
#

from __future__ import annotations

import warnings
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np
import pandas as pd

from ..ann.ivfflat import (
    PackedIVF,
    build_ivfflat_packed,
    default_nlist,
    default_nprobe,
    index_from_packed,
    ivfflat_search_prepared,
    tiered_index_from_packed,
    warm_probe_kernels,
)
from ..ann.pq import (
    DEFAULT_N_BITS,
    DEFAULT_REFINE_RATIO,
    PackedPQ,
    build_ivfpq_packed,
    default_m_sub,
    index_from_packed_pq,
    ivfpq_search_prepared,
    tiered_index_from_packed_pq,
    warm_pq_probe_kernels,
)
from ..core import _TpuEstimatorSupervised, _TpuModel
from ..dataframe import DataFrame, as_dataframe
from ..params import (
    HasFeaturesCol,
    HasFeaturesCols,
    Param,
    TypeConverters,
    _dummy,
    _TpuParams,
)
from ..parallel.mesh import get_mesh

# per-algorithm algoParams surfaces (a typo'd key is a hard error, never a
# silent default); the PQ keys follow the upstream cuML names.
# 'hot_fraction' (both tiers) opts into the tiered HBM/host-RAM residency
# split (ann/tier.py); 'opq' (pq tier) trains a learned rotation before
# the subspace split (ann/pq.py _train_opq_rotation).
_ALGO_PARAM_KEYS = {
    "ivfflat": {"nlist", "nprobe", "hot_fraction"},
    "ivfpq": {
        "nlist", "nprobe", "M", "n_bits", "usePrecomputedTables",
        "refine_ratio", "opq", "hot_fraction",
    },
}


class ApproximateNearestNeighborsClass(_TpuParams):
    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {"k": "n_neighbors", "algorithm": "algorithm"}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_neighbors": 5,
            "verbose": False,
            "algorithm": "ivfflat",
            "metric": "euclidean",
        }


class _ApproximateNearestNeighborsParams(
    ApproximateNearestNeighborsClass, HasFeaturesCol, HasFeaturesCols
):
    k = Param(_dummy(), "k", "the number of nearest neighbors to retrieve (> 0)", TypeConverters.toInt)
    idCol = Param(_dummy(), "idCol", "id column name; if unset a monotonically increasing id column is generated", TypeConverters.toString)
    algorithm = Param(_dummy(), "algorithm", "the ANN algorithm: 'ivfflat' (raw f32 lists) or 'ivfpq' (product-quantized lists)", TypeConverters.toString)
    algoParams = Param(_dummy(), "algoParams", "algorithm parameters: {'nlist', 'nprobe', 'hot_fraction': HBM-resident list fraction} (both tiers) plus, for ivfpq, {'M': subspaces, 'n_bits': bits per code (4 packs two codes/byte and takes the fast-scan kernel), 'refine_ratio': f32 re-score factor (1 = ADC only), 'opq': train a learned rotation before the subspace split, 'usePrecomputedTables': ignored}", TypeConverters.identity)
    exactSearch = Param(_dummy(), "exactSearch", "route kneighbors through the exact brute-force engine over the indexed items (recall escape hatch)", TypeConverters.toBoolean)

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(k=5, algorithm="ivfflat", exactSearch=False)

    def getK(self) -> int:
        return self.getOrDefault("k")

    def setK(self, value: int):
        return self._set_params(k=value)

    def getIdCol(self) -> str:
        return self.getOrDefault("idCol") if self.isDefined("idCol") else "unique_id"

    def setIdCol(self, value: str):
        self.set(self.getParam("idCol"), value)
        return self

    def getAlgorithm(self) -> str:
        return self.getOrDefault("algorithm")

    def setAlgorithm(self, value: str):
        return self._set_params(algorithm=value)

    def getAlgoParams(self) -> Optional[Dict[str, int]]:
        return self.getOrDefault("algoParams") if self.isDefined("algoParams") else None

    def setAlgoParams(self, value: Dict[str, int]):
        self.set(self.getParam("algoParams"), value)
        return self

    def getExactSearch(self) -> bool:
        return self.getOrDefault("exactSearch")

    def setExactSearch(self, value: bool):
        self.set(self.getParam("exactSearch"), value)
        return self

    def setInputCol(self, value: Union[str, List[str]]):
        if isinstance(value, str):
            self._set_params(featuresCol=value)
        else:
            self._set_params(featuresCols=value)
        return self

    def _validated_algo_params(self) -> Dict[str, Any]:
        algo = self.getAlgorithm()
        ap = dict(self.getAlgoParams() or {})
        known = _ALGO_PARAM_KEYS[algo]
        unknown = set(ap) - known
        if unknown:
            raise ValueError(
                f"unknown algoParams {sorted(unknown)} for algorithm "
                f"{algo!r}; supported: {sorted(known)}"
            )
        return ap

    def _resolved_algo_params(self, n_items: int, n_lists: int = None) -> Tuple[int, int]:
        """(nlist, nprobe) with the documented defaults (ann/ivfflat
        default_nlist/default_nprobe) filling unset keys; unknown keys are
        a hard error (a typo'd 'nprobes' must not silently probe 1/4)."""
        ap = self._validated_algo_params()
        nlist = int(ap.get("nlist", n_lists or default_nlist(n_items)))
        nprobe = int(ap.get("nprobe", default_nprobe(nlist)))
        if nlist < 1 or nprobe < 1:
            raise ValueError(
                f"nlist ({nlist}) and nprobe ({nprobe}) must be >= 1"
            )
        return nlist, nprobe

    def _resolved_pq_params(
        self, dim: int, warn: bool = False
    ) -> Tuple[int, int, int, bool]:
        """(M, n_bits, refine_ratio, opq) for algorithm='ivfpq' with the
        documented defaults (ann/pq default_m_sub, 8 bits, refine x4, no
        rotation).  refine_ratio semantics: 1 means "ADC only, no refine"
        (the probed scan IS the answer); >= 2 re-scores the top
        k*refine_ratio ADC candidates against the host f32 payload.  0 is
        a typed error — it used to slip through the old `>= 0` guard and
        then silently behave like 1 because the refine gate keys off
        `> 1`; an explicit ratio must name a real mode.
        usePrecomputedTables is accepted for upstream compatibility but
        IGNORED with a warning (once, at fit): the ADC formulation folds
        the list-dependent table term into the packed per-item scalar, so
        there is no separate precomputed-table mode to toggle."""
        ap = self._validated_algo_params()
        if warn and "usePrecomputedTables" in ap:
            warnings.warn(
                "algoParams['usePrecomputedTables'] is ignored: the IVF-PQ "
                "engine always folds the list-dependent ADC term into the "
                "packed per-item scalar (docs/ann_engine.md#ivf-pq)",
                stacklevel=3,
            )
        m = int(ap.get("M", default_m_sub(dim)))
        n_bits = int(ap.get("n_bits", DEFAULT_N_BITS))
        ratio = int(ap.get("refine_ratio", DEFAULT_REFINE_RATIO))
        opq = bool(ap.get("opq", False))
        if m < 1:
            raise ValueError(f"M ({m}) must be >= 1")
        if not 1 <= n_bits <= 8:
            raise ValueError(f"n_bits ({n_bits}) must be in [1, 8]")
        if ratio < 1:
            raise ValueError(
                f"refine_ratio ({ratio}) must be >= 1 (1 = ADC only, no "
                "f32 refine pass; >= 2 re-scores top k*ratio candidates)"
            )
        return m, n_bits, ratio, opq

    def _resolved_hot_fraction(self) -> float:
        """The tiered-residency knob for BOTH tiers: the fraction of each
        shard's lists pinned HBM-resident (ann/tier.py pages the rest from
        host RAM on probe demand).  algoParams['hot_fraction'] wins; the
        SRML_ANN_HOT_FRACTION env var is the fleet-wide default; 1.0
        (everything resident — the pre-tier behavior) otherwise."""
        import os

        ap = self._validated_algo_params()
        if "hot_fraction" in ap:
            hf = float(ap["hot_fraction"])
        else:
            hf = float(os.environ.get("SRML_ANN_HOT_FRACTION", "1.0"))
        if not 0.0 <= hf <= 1.0:
            raise ValueError(
                f"hot_fraction ({hf}) must be in [0, 1] (1 = fully "
                "HBM-resident, the default)"
            )
        return hf

    def _check_algorithm(self) -> None:
        if self.getAlgorithm() not in _ALGO_PARAM_KEYS:
            raise ValueError(
                f"algorithm={self.getAlgorithm()!r} is not supported; "
                f"implemented tiers: {sorted(_ALGO_PARAM_KEYS)}"
            )


class ApproximateNearestNeighbors(
    _ApproximateNearestNeighborsParams, _TpuEstimatorSupervised
):
    """IVF-Flat approximate kNN over the TPU mesh (ann/ivfflat.py): the
    kmeans engine trains the coarse quantizer, the fused distance+argmin
    kernel assigns lists, and probed search rides the kNN block pipeline
    with a recall knob (nprobe)."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._initialize_tpu_params()
        self._set_params(**kwargs)

    def _fit(self, dataset: Any) -> "ApproximateNearestNeighborsModel":
        from ..core import _use_executor_path, extract_partition_features

        self._check_algorithm()
        if getattr(dataset, "_device_features", None) is not None:
            raise NotImplementedError(
                "ApproximateNearestNeighbors.fit does not take "
                "DataFrame.from_device frames (their features column is a "
                "placeholder); fit a host frame instead"
            )
        if _use_executor_path(dataset):
            raise NotImplementedError(
                "ApproximateNearestNeighbors builds its index in-process; "
                "collect the pyspark item dataframe (SRML_SPARK_COLLECT=1) "
                "before fitting"
            )
        df = as_dataframe(dataset)
        id_col = self.getIdCol()
        if id_col not in df.columns:
            df = df.with_row_id(id_col)
        self._validate_parameters(df)
        input_col, input_cols = self._get_input_columns()
        feats, ids = [], []
        for part in df.partitions:
            if len(part) == 0:
                continue
            feats.append(
                extract_partition_features(
                    part, input_col, input_cols, np.float32
                )
            )
            ids.append(np.asarray(part[id_col].to_numpy(), np.int64))
        if not feats:
            raise RuntimeError("Dataset is empty; cannot build an IVF index")
        X = np.concatenate(feats) if len(feats) > 1 else feats[0]
        item_ids = np.concatenate(ids) if len(ids) > 1 else ids[0]
        nlist, _nprobe = self._resolved_algo_params(X.shape[0])
        self._resolved_hot_fraction()  # fail fast on an out-of-range knob
        if self.getAlgorithm() == "ivfpq":
            m_sub, n_bits, _ratio, opq = self._resolved_pq_params(
                int(X.shape[1]), warn=True
            )
            pq = build_ivfpq_packed(
                X, item_ids, nlist, m_sub=m_sub, n_bits=n_bits, seed=0,
                opq=opq,
            )
            model = ApproximateNearestNeighborsModel(
                centroids_=pq.centroids,
                packed_items_=pq.items,
                packed_ids_=pq.ids,
                list_counts_=pq.counts,
                n_lists=pq.n_lists,
                n_items=pq.n_items,
                n_cols=int(X.shape[1]),
                dtype="float32",
                pq_codes_=pq.codes,
                pq_scalars_=pq.scalars,
                pq_codebooks_=pq.codebooks,
                pq_n_bits=pq.n_bits,
                pq_rotation_=pq.rotation,
            )
        else:
            packed = build_ivfflat_packed(X, item_ids, nlist, seed=0)
            model = ApproximateNearestNeighborsModel(
                centroids_=packed.centroids,
                packed_items_=packed.items,
                packed_ids_=packed.ids,
                list_counts_=packed.counts,
                n_lists=packed.n_lists,
                n_items=packed.n_items,
                n_cols=int(X.shape[1]),
                dtype="float32",
            )
        self._copyValues(model)
        model._tpu_params.update(self._tpu_params)
        model._num_workers = self._num_workers
        model._float32_inputs = self._float32_inputs
        model._item_df = df
        return model

    def fit(
        self, dataset: Any, params: Optional[Dict] = None
    ) -> "ApproximateNearestNeighborsModel":
        return self._fit(dataset)

    def _get_tpu_fit_func(self, dataset, extra_params=None):  # pragma: no cover
        raise NotImplementedError("ApproximateNearestNeighbors overrides _fit")

    def _create_model(self, result):  # pragma: no cover
        raise NotImplementedError("ApproximateNearestNeighbors overrides _fit")


class ApproximateNearestNeighborsModel(
    _ApproximateNearestNeighborsParams, _TpuModel
):
    """A fitted IVF-Flat index.  Persistable through the core npz path (the
    packed layout is mesh-independent; staging expands it per mesh); a
    loaded model answers kneighbors without the original item frame."""

    def __init__(
        self,
        centroids_: np.ndarray,
        packed_items_: np.ndarray,
        packed_ids_: np.ndarray,
        list_counts_: np.ndarray,
        n_lists: int,
        n_items: int,
        n_cols: int,
        dtype: str = "float32",
        pq_codes_: Optional[np.ndarray] = None,
        pq_scalars_: Optional[np.ndarray] = None,
        pq_codebooks_: Optional[np.ndarray] = None,
        pq_n_bits: Optional[int] = None,
        pq_rotation_: Optional[np.ndarray] = None,
    ) -> None:
        super().__init__(
            centroids_=np.asarray(centroids_),
            packed_items_=np.asarray(packed_items_),
            packed_ids_=np.asarray(packed_ids_),
            list_counts_=np.asarray(list_counts_),
            n_lists=int(n_lists),
            n_items=int(n_items),
            n_cols=int(n_cols),
            dtype=str(dtype),
            pq_codes_=None if pq_codes_ is None else np.asarray(pq_codes_),
            pq_scalars_=None if pq_scalars_ is None else np.asarray(pq_scalars_),
            pq_codebooks_=None
            if pq_codebooks_ is None
            else np.asarray(pq_codebooks_),
            pq_n_bits=None if pq_n_bits is None else int(pq_n_bits),
            pq_rotation_=None
            if pq_rotation_ is None
            else np.asarray(pq_rotation_),
        )
        self.centroids_ = np.asarray(centroids_, np.float32)
        self.packed_items_ = np.asarray(packed_items_, np.float32)
        self.packed_ids_ = np.asarray(packed_ids_, np.int64)
        self.list_counts_ = np.asarray(list_counts_, np.int64)
        self.n_lists = int(n_lists)
        self.n_items = int(n_items)
        self.n_cols = int(n_cols)
        self.dtype = str(dtype)
        # the PQ tier's extra payload (None on an ivfflat model): one-byte
        # codes, ADC item scalars, and the subspace codebooks — together
        # with the shared list layout they form the PackedPQ
        self.pq_codes_ = None if pq_codes_ is None else np.asarray(
            pq_codes_, np.uint8
        )
        self.pq_scalars_ = None if pq_scalars_ is None else np.asarray(
            pq_scalars_, np.float32
        )
        self.pq_codebooks_ = None if pq_codebooks_ is None else np.asarray(
            pq_codebooks_, np.float32
        )
        self.pq_n_bits = None if pq_n_bits is None else int(pq_n_bits)
        # the OPQ rotation (d_pad, d_pad) f32, or None when fit without
        # algoParams['opq']: codes encode ROTATED residuals, so the
        # rotation must persist with the payload — a load that dropped it
        # would decode against the wrong frame
        self.pq_rotation_ = None if pq_rotation_ is None else np.asarray(
            pq_rotation_, np.float32
        )
        self._item_df: Optional[DataFrame] = None
        # per-mesh staging caches (die with the model, like the exact
        # model's _staged_items): the probed index (flat or pq) and the
        # exactSearch prepared item set
        self._staged_index: Optional[Tuple[Any, Any]] = None
        self._staged_pq: Optional[Tuple[Any, Any]] = None
        self._staged_exact: Optional[Tuple[Any, Any]] = None
        # live-mutation holder (ann/mutable.py): once created via
        # mutable_index(), every staged-flat read — kneighbors AND the
        # serve.ann entry — snapshots it, so add/delete/repack are visible
        # to in-flight serving without re-registration
        self._mutable: Optional[Tuple[Any, Any]] = None

    def _packed(self) -> PackedIVF:
        return PackedIVF(
            self.packed_items_,
            self.packed_ids_,
            self.list_counts_,
            self.centroids_,
            self.n_lists,
            self.n_items,
        )

    def _packed_pq(self) -> PackedPQ:
        if self.pq_codes_ is None:
            raise ValueError(
                "this model was fit with algorithm='ivfflat'; it carries no "
                "PQ payload — refit with algorithm='ivfpq'"
            )
        return PackedPQ(
            self.pq_codes_,
            self.pq_scalars_,
            self.packed_ids_,
            self.packed_items_,
            self.list_counts_,
            self.centroids_,
            self.pq_codebooks_,
            self.n_lists,
            self.n_items,
            self.n_cols,
            self.pq_codes_.shape[1],
            self.pq_n_bits,
            rotation=self.pq_rotation_,
        )

    def _mesh_key(self, mesh) -> Tuple:
        from ..ops.precompile import mesh_fingerprint

        # value identity, not object identity: get_mesh builds fresh Mesh
        # objects per call, and a re-staged identical mesh must HIT
        return mesh_fingerprint(mesh)

    def _ensure_staged_index(self, mesh):
        hf = self._resolved_hot_fraction()
        key = (self._mesh_key(mesh), hf)
        if self._mutable is not None:
            if self._mutable[0] != key:
                raise ValueError(
                    "this model's index is live-mutable on a different "
                    "mesh; mutation is per-mesh — freeze_mutations() "
                    "before staging elsewhere"
                )
            return self._mutable[1].index
        if self._staged_index is None or self._staged_index[0] != key:
            if hf < 1.0:
                staged = tiered_index_from_packed(self._packed(), mesh, hf)
            else:
                staged = index_from_packed(self._packed(), mesh)
            self._staged_index = (key, staged)
        return self._staged_index[1]

    def mutable_index(self, mesh: Any = None):
        """The live-mutation holder for this model's IVF-Flat index
        (ann/mutable.MutableIVFIndex): created on first call (staging the
        packed payload on `mesh`), returned thereafter.  Once created,
        kneighbors and the serve.ann entry read the holder's atomic index
        snapshot, so add_items/delete_items/repack are immediately visible
        to serving traffic.  Flat-only: the PQ tier's codes are not
        incrementally mutable (docs/ann_engine.md §incremental-mutation)."""
        self._check_algorithm()
        if self.getAlgorithm() == "ivfpq":
            raise ValueError(
                "live mutation is IVF-Flat-only; the PQ tier requires "
                "codebook-consistent codes (refit to mutate an ivfpq model)"
            )
        from ..ann.mutable import MutableIVFIndex

        mesh = mesh or get_mesh(self.num_workers)
        hf = self._resolved_hot_fraction()
        key = (self._mesh_key(mesh), hf)
        if self._mutable is None:
            self._mutable = (
                key,
                MutableIVFIndex(self._packed(), mesh, hot_fraction=hf),
            )
            self._staged_index = None  # the holder owns staging now
        elif self._mutable[0] != key:
            raise ValueError(
                "mutable index already staged on a different mesh; "
                "freeze_mutations() and re-create to move meshes"
            )
        return self._mutable[1]

    def freeze_mutations(self):
        """Fold the live holder's state back into the model's persistable
        packed payload (compacted live rows) and drop the holder — after
        this, save()/staging behave exactly like a freshly-built index
        over the mutated item set."""
        if self._mutable is None:
            return self
        packed = self._mutable[1].to_packed()
        self.packed_items_ = packed.items
        self.packed_ids_ = packed.ids
        self.list_counts_ = packed.counts
        self.centroids_ = packed.centroids
        self.n_items = packed.n_items
        self._model_attributes["packed_items_"] = packed.items
        self._model_attributes["packed_ids_"] = packed.ids
        self._model_attributes["list_counts_"] = packed.counts
        self._model_attributes["centroids_"] = packed.centroids
        self._model_attributes["n_items"] = packed.n_items
        self._mutable = None
        self._staged_index = None
        self._staged_exact = None
        return self

    def _ensure_staged_pq(self, mesh):
        hf = self._resolved_hot_fraction()
        key = (self._mesh_key(mesh), hf)
        if self._staged_pq is None or self._staged_pq[0] != key:
            if hf < 1.0:
                staged = tiered_index_from_packed_pq(
                    self._packed_pq(), mesh, hf
                )
            else:
                staged = index_from_packed_pq(self._packed_pq(), mesh)
            self._staged_pq = (key, staged)
        return self._staged_pq[1]

    def _ensure_staged_exact(self, mesh):
        from ..ops.knn import prepare_items

        if self._mutable is not None:
            # the exact route stages from packed_items_/packed_ids_, which
            # live mutations do NOT update until freeze — serving a stale
            # payload here would return tombstoned ids and miss every
            # added one, silently
            raise ValueError(
                "exactSearch is unavailable while the index is live-"
                "mutable (the exact route reads the persistable packed "
                "payload, which mutations update only at "
                "freeze_mutations()); freeze first"
            )
        key = self._mesh_key(mesh)
        if self._staged_exact is None or self._staged_exact[0] != key:
            self._staged_exact = (
                key,
                prepare_items(self.packed_items_, self.packed_ids_, mesh),
            )
        return self._staged_exact[1]

    def kneighbors(
        self, query_df: Any
    ) -> Tuple[Optional[DataFrame], DataFrame, DataFrame]:
        """Probed approximate k nearest items for every query row (float32
        euclidean, same output frame as the exact model's kneighbors:
        (item_df — None on a loaded model, query_df_withid, knn_df with
        query_<id>, indices, distances)).  exactSearch=True routes through
        the exact engine over the same indexed items, so the two paths
        share the id space and recall_at_k can gate one against the
        other."""
        from ..core import _is_pyspark_dataframe, extract_partition_features

        self._check_algorithm()
        if _is_pyspark_dataframe(query_df):
            raise NotImplementedError(
                "ApproximateNearestNeighborsModel serves in-process query "
                "frames; collect the pyspark frame (SRML_SPARK_COLLECT=1) "
                "first"
            )
        qdf = as_dataframe(query_df)
        id_col = self.getIdCol()
        if id_col not in qdf.columns:
            qdf = qdf.with_row_id(id_col)
        input_col, input_cols = self._get_input_columns()
        mesh = get_mesh(self.num_workers)
        k = self.getK()
        _nlist, nprobe = self._resolved_algo_params(
            self.n_items, n_lists=self.n_lists
        )
        exact = self.getExactSearch()
        pq = not exact and self.getAlgorithm() == "ivfpq"
        if exact:
            from ..ops.knn import knn_search_prepared

            prepared = self._ensure_staged_exact(mesh)
        elif pq:
            index = self._ensure_staged_pq(mesh)
            _m, _b, refine_ratio, _opq = self._resolved_pq_params(self.n_cols)
        else:
            index = self._ensure_staged_index(mesh)
        from .. import profiling

        out_parts = []
        with profiling.trace_session("search-ApproximateNearestNeighbors"):
            for part in qdf.partitions:
                if len(part) == 0:
                    out_parts.append(
                        pd.DataFrame(
                            {f"query_{id_col}": [], "indices": [], "distances": []}
                        )
                    )
                    continue
                feats = extract_partition_features(
                    part, input_col, input_cols, np.float32
                )
                if exact:
                    dists, ids = knn_search_prepared(prepared, feats, k, mesh)
                elif pq:
                    dists, ids = ivfpq_search_prepared(
                        index, feats, k, nprobe, mesh,
                        refine_items=(
                            self.packed_items_ if refine_ratio > 1 else None
                        ),
                        refine_ratio=refine_ratio,
                    )
                else:
                    dists, ids = ivfflat_search_prepared(
                        index, feats, k, nprobe, mesh
                    )
                out_parts.append(
                    pd.DataFrame(
                        {
                            f"query_{id_col}": part[id_col].to_numpy(),
                            "indices": list(np.asarray(ids)),
                            "distances": list(np.asarray(dists, np.float32)),
                        }
                    )
                )
        return self._item_df, qdf, DataFrame(out_parts)

    def _get_tpu_transform_func(self, dataset):  # pragma: no cover
        raise NotImplementedError(
            "ApproximateNearestNeighborsModel has no transform; use "
            "kneighbors instead."
        )

    def _serving_entry(self, mesh: Any = None):
        """Online ANN hook (serving/): each coalesced batch is ONE probed
        search (flat or PQ per the algorithm param) against the staged
        index; warm submits the probe-kernel geometry for every engine
        bucket (the engine's pow2 buckets feed the search's own >=64
        query-block rule, same contract as the exact kNN entry) — served
        steady state performs zero new compilations on BOTH tiers."""
        from ..serving.entry import ServingEntry

        self._check_algorithm()
        mesh = mesh or get_mesh(self.num_workers)
        pq = self.getAlgorithm() == "ivfpq"
        k = self.getK()
        _nlist, nprobe = self._resolved_algo_params(
            self.n_items, n_lists=self.n_lists
        )
        dtype = np.dtype(np.float32)
        info = {
            "k": int(min(k, self.n_items)),
            "n_items": int(self.n_items),
            "nlist": int(self.n_lists),
            "nprobe": int(nprobe),
            "algorithm": self.getAlgorithm(),
        }
        if pq:
            index = self._ensure_staged_pq(mesh)
            _m, _b, refine_ratio, _opq = self._resolved_pq_params(self.n_cols)
            refine_items = (
                self.packed_items_ if refine_ratio > 1 else None
            )
            info["m_sub"] = int(index.m_sub)
            info["n_bits"] = int(index.n_bits)
            info["refine_ratio"] = int(refine_ratio)

            def call(batch: np.ndarray) -> Dict[str, np.ndarray]:
                dists, ids = ivfpq_search_prepared(
                    index, batch, k, nprobe, mesh,
                    refine_items=refine_items, refine_ratio=refine_ratio,
                )
                return {
                    "indices": np.asarray(ids),
                    "distances": np.asarray(dists, dtype=np.float32),
                }

            def warm(buckets) -> list:
                keys = []
                for b in sorted({max(int(x), 64) for x in buckets}):
                    keys.extend(
                        warm_pq_probe_kernels(
                            index, k, nprobe, mesh, n_queries=b,
                            refine=refine_items is not None,
                            refine_ratio=refine_ratio,
                        )
                    )
                return keys

        else:
            self._ensure_staged_index(mesh)  # stage (or validate holder mesh)

            def call(batch: np.ndarray) -> Dict[str, np.ndarray]:
                # re-read per batch: with a live-mutation holder this is
                # the atomic post-mutation snapshot (add/delete/repack are
                # serving-visible without re-registration); without one it
                # is the cached staged tuple — a dict lookup either way
                dists, ids = ivfflat_search_prepared(
                    self._ensure_staged_index(mesh), batch, k, nprobe, mesh
                )
                return {
                    "indices": np.asarray(ids),
                    "distances": np.asarray(dists, dtype=np.float32),
                }

            def warm(buckets) -> list:
                index = self._ensure_staged_index(mesh)
                holder = self._mutable[1] if self._mutable is not None else None
                keys = []
                for b in sorted({max(int(x), 64) for x in buckets}):
                    keys.extend(
                        warm_probe_kernels(index, k, nprobe, mesh, n_queries=b)
                    )
                    if holder is not None:
                        # a later repack re-warms exactly the geometries
                        # serving dispatches before swapping the index in
                        holder.register_warm(k, nprobe, b)
                return keys

        return ServingEntry(
            name="serve.ann",
            n_cols=int(self.n_cols),
            dtype=dtype,
            out_cols=["indices", "distances"],
            call=call,
            warm=warm,
            info=info,
        )

    def index_bytes_per_item(self, mesh: Any = None) -> float:
        """Device-resident index bytes per indexed item on this mesh — the
        flat-vs-PQ compression headline benchmark/bench_approximate_nn.py
        reports (host-side payloads — ids, the PQ refine f32 vectors — are
        deliberately excluded: device HBM is the capacity constraint the
        PQ tier exists to lift)."""
        self._check_algorithm()
        mesh = mesh or get_mesh(self.num_workers)
        if self.getAlgorithm() == "ivfpq":
            index = self._ensure_staged_pq(mesh)
        else:
            index = self._ensure_staged_index(mesh)
        return index.device_bytes() / max(self.n_items, 1)

    def index_residency(
        self, mesh: Any = None, hbm_budget_bytes: int = 16 << 30
    ) -> Dict[str, float]:
        """The residency breakdown behind index_bytes_per_item: where each
        indexed item's bytes actually live on this mesh, and how many
        items one device's HBM budget admits at this layout.

        - hbm_bytes_per_item: device-resident index bytes / item (the
          whole index for hot_fraction=1; hot lists + the pager pool for
          a tiered split)
        - host_bytes_per_item: host-RAM bytes / item — the tier's warm
          list planes plus the payloads that are ALWAYS host-side (ids
          and, on the pq tier, the f32 refine vectors)
        - items_per_device: floor(hbm_budget_bytes / per-device HBM bytes
          per item) — the headline capacity number at this (n_bits, M,
          hot_fraction) operating point
        """
        self._check_algorithm()
        mesh = mesh or get_mesh(self.num_workers)
        if self.getAlgorithm() == "ivfpq":
            index = self._ensure_staged_pq(mesh)
            host_extra = self.packed_items_.nbytes + self.packed_ids_.nbytes
        else:
            index = self._ensure_staged_index(mesh)
            host_extra = self.packed_ids_.nbytes
        n = max(self.n_items, 1)
        n_dev = max(int(np.prod(list(mesh.shape.values()))), 1)
        hbm_bpi = index.device_bytes() / n
        host_bpi = (
            getattr(index, "host_bytes", lambda: 0)() + host_extra
        ) / n
        per_dev_bpi = hbm_bpi / n_dev
        return {
            "hbm_bytes_per_item": float(hbm_bpi),
            "host_bytes_per_item": float(host_bpi),
            "items_per_device": float(
                np.floor(hbm_budget_bytes / max(per_dev_bpi, 1e-12))
            ),
        }
