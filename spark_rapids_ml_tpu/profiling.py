#
# Tracing / profiling hooks.
#
# TPU-native equivalent of the reference's observability surface (SURVEY.md
# §5): the Scala path wraps phases in NVTX ranges
# (/root/reference/jvm/src/main/scala/org/apache/spark/ml/linalg/distributed/RapidsRowMatrix.scala:62,70)
# and the Python path logs coarse phase lines inside the fit UDF
# (/root/reference/python/src/spark_rapids_ml/core.py:583,617) with wall-clock
# timers in the benchmark harness
# (/root/reference/python/benchmark/benchmark/utils.py:42-50).
#
# Here the same three ideas map to jax:
#   - phase(name): a context manager emitting a jax.profiler.TraceAnnotation
#     (named range in an xprof/tensorboard trace — the NVTX analog on TPU)
#     plus a DEBUG log line with host wall-clock, and recording the duration
#     in a per-thread registry that estimators expose after fit.
#   - maybe_trace(): opt-in whole-program capture — set SRML_PROFILE=/some/dir
#     and every top-level fit() writes an xprof trace there, the moral
#     equivalent of running the reference benchmarks with NCCL_DEBUG=INFO.
#   - with_benchmark(name, fn): wall-clock helper with the same shape as the
#     reference's benchmark/utils.py:42-50.
#   - incr_counter/counters: PROCESS-wide monotonic counters (the precompile
#     subsystem's compile/hit/miss accounting — its worker threads must be
#     able to report into the same registry the main thread reads).
#   - record_event/events: a per-thread ORDERED event log for asserting
#     pipeline interleavings (e.g. "block i+1 dispatched before block i
#     collected" in the kNN query engine) without timing-dependent tests.
#   - record_duration/percentiles: PROCESS-wide duration samples (per-request
#     serving latencies recorded on the dispatch worker thread, read from the
#     main thread) with p50/p95/p99 summaries — the SLO surface the serving
#     engine and the benchmark reports share.
#

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

_log = logging.getLogger("spark_rapids_ml_tpu.profiling")

PROFILE_ENV = "SRML_PROFILE"

_tls = threading.local()


def _registry() -> Dict[str, float]:
    reg = getattr(_tls, "phases", None)
    if reg is None:
        reg = {}
        _tls.phases = reg
    return reg


def reset_phase_times() -> None:
    """Clear the current thread's phase registry (called at fit entry)."""
    _registry().clear()


def phase_times(prefix: str = "") -> Dict[str, float]:
    """Seconds per named phase recorded on this thread since the last reset
    (optionally filtered by name prefix — the benchmark idiom for reporting
    one subsystem's phase set, e.g. "forest." or "knn.")."""
    reg = _registry()
    if not prefix:
        return dict(reg)
    return {k: v for k, v in reg.items() if k.startswith(prefix)}


# -- process-wide counters ---------------------------------------------------
# Unlike the phase registry these are NOT thread-local: the precompile worker
# pool compiles on daemon threads while fits read the counters from the main
# thread, so one locked registry is the only consistent view.

_counters_lock = threading.Lock()
_counters: Dict[str, int] = {}


def incr_counter(name: str, amount: int = 1) -> None:
    """Add `amount` to the process-wide counter `name` (created at 0)."""
    with _counters_lock:
        _counters[name] = _counters.get(name, 0) + amount


def counter(name: str) -> int:
    with _counters_lock:
        return _counters.get(name, 0)


def counters(prefix: str = "") -> Dict[str, int]:
    """Snapshot of all counters (optionally filtered by name prefix)."""
    with _counters_lock:
        return {k: v for k, v in _counters.items() if k.startswith(prefix)}


def counter_deltas(before: Dict[str, int], prefix: str = "") -> Dict[str, int]:
    """Nonzero differences of the current counters vs a `counters(prefix)`
    snapshot — the benchmark/test idiom for "what moved during this fit"
    without resetting the monotonic registry."""
    now = counters(prefix)
    keys = set(now) | set(before)
    return {
        k: now.get(k, 0) - before.get(k, 0)
        for k in sorted(keys)
        if now.get(k, 0) != before.get(k, 0)
    }


def reset_counters(prefix: str = "") -> None:
    """Zero counters matching `prefix` (tests; production code never resets —
    the counters are monotonic so deltas are always well-defined)."""
    with _counters_lock:
        for k in [k for k in _counters if k.startswith(prefix)]:
            del _counters[k]


# -- process-wide duration samples -------------------------------------------
# Like the counters (and unlike the phase registry) these are NOT thread-
# local: the serving engine records request latencies on its dispatch worker
# thread while stats()/tests read the percentiles from the main thread.
# Bounded per name so a long-lived server cannot grow the sample list without
# limit; past the cap new samples overwrite the oldest (ring buffer), keeping
# the percentiles a sliding window over the most recent traffic.

_DURATION_CAP = 65536

_durations_lock = threading.Lock()
_durations: Dict[str, list] = {}
_duration_next: Dict[str, int] = {}  # ring-buffer write cursor past the cap


def record_duration(name: str, seconds: float) -> None:
    """Append one duration sample (seconds) to the process-wide series
    `name`.  Cheap enough for per-request recording; capped per name (ring
    buffer) so recording is observability, never a leak."""
    with _durations_lock:
        series = _durations.get(name)
        if series is None:
            series = []
            _durations[name] = series
        if len(series) < _DURATION_CAP:
            series.append(float(seconds))
        else:
            cur = _duration_next.get(name, 0)
            series[cur] = float(seconds)
            _duration_next[name] = (cur + 1) % _DURATION_CAP


def durations(prefix: str = "") -> Dict[str, list]:
    """Copy of every duration series whose name starts with `prefix`."""
    with _durations_lock:
        return {k: list(v) for k, v in _durations.items() if k.startswith(prefix)}


def reset_durations(prefix: str = "") -> None:
    with _durations_lock:
        for k in [k for k in _durations if k.startswith(prefix)]:
            del _durations[k]
            _duration_next.pop(k, None)


def percentiles(prefix: str = "") -> Dict[str, float]:
    """p50/p95/p99 (plus count/mean/max) over every duration sample recorded
    under names starting with `prefix`, merged into ONE distribution — pass
    an exact series name for a single series, or a subsystem prefix (e.g.
    "serve.kmeans.") for its whole latency surface.  Returns {} when nothing
    was recorded.  Linear interpolation between order statistics, the numpy
    default, so tiny test samples get deterministic values."""
    merged: list = []
    with _durations_lock:
        for k, v in _durations.items():
            if k.startswith(prefix):
                merged.extend(v)
    if not merged:
        return {}
    import numpy as np

    arr = np.asarray(merged, dtype=np.float64)
    p50, p95, p99 = np.percentile(arr, [50.0, 95.0, 99.0])
    return {
        "count": int(arr.size),
        "mean": float(arr.mean()),
        "p50": float(p50),
        "p95": float(p95),
        "p99": float(p99),
        "max": float(arr.max()),
    }


# -- per-thread ordered event log --------------------------------------------
# Bounded so a long-lived process that never drains the log cannot grow it
# without limit; the cap is far above any one search's dispatch/collect count.

_EVENT_CAP = 4096


def _event_log() -> list:
    log = getattr(_tls, "events", None)
    if log is None:
        log = []
        _tls.events = log
    return log


def record_event(name: str, **meta: Any) -> None:
    """Append (name, meta) to this thread's ordered event log (dropped
    silently past the cap — the log is observability, never control flow)."""
    log = _event_log()
    if len(log) < _EVENT_CAP:
        log.append((name, meta))


def events(prefix: str = "") -> list:
    """This thread's events in record order, optionally prefix-filtered."""
    return [(n, m) for n, m in _event_log() if n.startswith(prefix)]


def reset_events() -> None:
    _event_log().clear()


@contextlib.contextmanager
def phase(name: str) -> Iterator[None]:
    """Named range: xprof TraceAnnotation + wall-clock accounting.

    The TraceAnnotation shows up in a tensorboard/xprof capture exactly where
    NVTX ranges show up in nsys for the reference's Scala path."""
    try:
        import jax.profiler

        annotation: contextlib.AbstractContextManager = jax.profiler.TraceAnnotation(
            name
        )
    except Exception:  # pragma: no cover - profiler always importable with jax
        annotation = contextlib.nullcontext()
    t0 = time.perf_counter()
    with annotation:
        yield
    dt = time.perf_counter() - t0
    reg = _registry()
    reg[name] = reg.get(name, 0.0) + dt
    _log.debug("phase %s: %.3fs", name, dt)


@contextlib.contextmanager
def maybe_trace(tag: str = "fit") -> Iterator[None]:
    """If SRML_PROFILE=<dir> is set, capture an xprof trace of the enclosed
    region into <dir>/<tag>.  No-op (zero overhead) otherwise."""
    out_dir = os.environ.get(PROFILE_ENV)
    if not out_dir:
        yield
        return
    import jax.profiler

    target = os.path.join(out_dir, tag)
    os.makedirs(target, exist_ok=True)
    with jax.profiler.trace(target):
        yield
    _log.info("xprof trace for %r written to %s", tag, target)


def with_benchmark(name: str, fn: Callable[[], Any]) -> Tuple[Any, float]:
    """Run fn, returning (result, elapsed_seconds) and logging the timing —
    the reference's benchmark/utils.py:42-50 helper."""
    t0 = time.perf_counter()
    result = fn()
    dt = time.perf_counter() - t0
    _log.info("-" * 100)
    _log.info("%s took: %s sec", name, dt)
    return result, dt


def device_step_annotation(step: int) -> contextlib.AbstractContextManager:
    """StepTraceAnnotation for iteration-granular traces (opt-in use in
    benchmark loops)."""
    try:
        import jax.profiler

        return jax.profiler.StepTraceAnnotation("step", step_num=step)
    except Exception:  # pragma: no cover
        return contextlib.nullcontext()
