#
# srml-scope: the runtime observability layer.
#
# TPU-native equivalent of the reference's observability surface (SURVEY.md
# §5): the Scala path wraps phases in NVTX ranges
# (/root/reference/jvm/src/main/scala/org/apache/spark/ml/linalg/distributed/RapidsRowMatrix.scala:62,70)
# and the Python path logs coarse phase lines inside the fit UDF
# (/root/reference/python/src/spark_rapids_ml/core.py:583,617) with wall-clock
# timers in the benchmark harness
# (/root/reference/python/benchmark/benchmark/utils.py:42-50).  Those ideas
# grew here into three pillars:
#
#   1. HIERARCHICAL SPANS — span(name, **attrs) nests: each span records its
#      parent span (per-thread stack), thread id/name, monotonic start/end
#      timestamps, and any attached counters (bytes=, rows=, block=...).
#      phase(name) is the same function (API-compatible shim) — every
#      existing phase site in the engines is a span site.  Alongside the
#      host-side record, every span still emits a jax.profiler
#      TraceAnnotation so xprof captures carry the same names.  Span records
#      are collected ONLY while a trace session is active: spans off means
#      no allocation, no buffer append, no thread-local stack — the disabled
#      path is the old flat phase timer, nothing more (guarded by
#      tests/test_profiling.py).
#   2. TRACE EXPORT — trace_session(tag) (active when SRML_TRACE_DIR is set)
#      collects every span completed during the session and writes a Chrome
#      trace-event JSON file (load it in Perfetto / chrome://tracing).  Fit,
#      kneighbors, and serving sessions open one automatically.
#   3. MERGEABLE TELEMETRY — TelemetrySnapshot rolls up phase stats,
#      counters, and duration digests into a JSON-safe dict with associative
#      commutative merge rules (mirroring metrics/binary.py partials), so
#      executor-side fit telemetry crosses the Spark wire and merges on the
#      driver: model.fit_telemetry() works on live Spark, not just local
#      mode.  export_metrics() / render_prometheus() are the pull surface
#      (stable JSON + Prometheus text exposition).
#
# The flat primitives underneath are unchanged:
#   - incr_counter/counters: PROCESS-wide monotonic counters (precompile's
#     compile/hit/miss accounting; worker threads report into the registry
#     the main thread reads).
#   - record_event/events: a per-thread ORDERED event log for asserting
#     pipeline interleavings without timing-dependent tests.
#   - record_duration/percentiles: PROCESS-wide duration samples with
#     p50/p95/p99 summaries (the serving SLO surface).
#   - maybe_trace(): opt-in whole-program xprof capture (SRML_PROFILE=<dir>).
#   - now(): the ONE monotonic clock.  Engine/serving modules must take
#     timestamps through it (or through span()) — graftlint R6 rejects raw
#     time.perf_counter()/time.time() outside this module, so every timing
#     source srml-scope reports from is the same clock.
#

from __future__ import annotations

import contextlib
import itertools
import json
import logging
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

_log = logging.getLogger("spark_rapids_ml_tpu.profiling")

PROFILE_ENV = "SRML_PROFILE"
TRACE_ENV = "SRML_TRACE_DIR"
METRIC_TTL_ENV = "SRML_METRIC_TTL_S"

_tls = threading.local()

# srml-watch flight-recorder hook (watch.install sets this to the process
# FlightRecorder).  Unlike trace sessions the recorder is ALWAYS on: span()
# and incr_counter() feed it bounded O(1) ring events so the last moments
# before a hang/crash are reconstructable without any session open.  None
# (SRML_WATCH=0) restores the exact pre-watch code path.
_flight: Optional[Any] = None


def now() -> float:
    """The process's ONE monotonic clock (time.perf_counter).  All timing in
    engine/serving modules goes through here or span() — graftlint R6."""
    return time.perf_counter()


# perf_counter value at import: trace-event timestamps are exported relative
# to it so a Perfetto timeline starts near zero instead of at host uptime
_EPOCH = time.perf_counter()


def _registry() -> Dict[str, float]:
    reg = getattr(_tls, "phases", None)
    if reg is None:
        reg = {}
        _tls.phases = reg
    return reg


def _count_registry() -> Dict[str, int]:
    reg = getattr(_tls, "phase_counts", None)
    if reg is None:
        reg = {}
        _tls.phase_counts = reg
    return reg


def reset_phase_times() -> None:
    """Clear the current thread's phase registry (called at fit entry)."""
    _registry().clear()
    _count_registry().clear()


def phase_times(prefix: str = "") -> Dict[str, float]:
    """Seconds per named phase recorded on this thread since the last reset
    (optionally filtered by name prefix — the benchmark idiom for reporting
    one subsystem's phase set, e.g. "forest." or "knn.")."""
    reg = _registry()
    if not prefix:
        return dict(reg)
    return {k: v for k, v in reg.items() if k.startswith(prefix)}


def phase_stats(prefix: str = "") -> Dict[str, Dict[str, float]]:
    """{name: {"count", "total_s"}} for this thread's phases since the last
    reset — the span rollup a TelemetrySnapshot carries (counts travel with
    totals so merged snapshots can still average per-invocation cost)."""
    reg = _registry()
    cnt = _count_registry()
    return {
        k: {"count": int(cnt.get(k, 0)), "total_s": float(v)}
        for k, v in reg.items()
        if k.startswith(prefix)
    }


# -- process-wide counters ---------------------------------------------------
# Unlike the phase registry these are NOT thread-local: the precompile worker
# pool compiles on daemon threads while fits read the counters from the main
# thread, so one locked registry is the only consistent view.

_counters_lock = threading.Lock()
_counters: Dict[str, int] = {}


def incr_counter(name: str, amount: int = 1) -> None:
    """Add `amount` to the process-wide counter `name` (created at 0)."""
    with _counters_lock:
        total = _counters.get(name, 0) + amount
        _counters[name] = total
    fr = _flight
    if fr is not None:
        fr.on_counter(name, amount, total)


def counter(name: str) -> int:
    with _counters_lock:
        return _counters.get(name, 0)


def counters(prefix: str = "") -> Dict[str, int]:
    """Snapshot of all counters (optionally filtered by name prefix)."""
    with _counters_lock:
        return {k: v for k, v in _counters.items() if k.startswith(prefix)}


def counter_deltas(before: Dict[str, int], prefix: str = "") -> Dict[str, int]:
    """Nonzero differences of the current counters vs a `counters(prefix)`
    snapshot — the benchmark/test idiom for "what moved during this fit"
    without resetting the monotonic registry."""
    now_ = counters(prefix)
    keys = set(now_) | set(before)
    return {
        k: now_.get(k, 0) - before.get(k, 0)
        for k in sorted(keys)
        if now_.get(k, 0) != before.get(k, 0)
    }


def reset_counters(prefix: str = "") -> None:
    """Zero counters matching `prefix` (tests; production code never resets —
    the counters are monotonic so deltas are always well-defined)."""
    with _counters_lock:
        for k in [k for k in _counters if k.startswith(prefix)]:
            del _counters[k]


# -- process-wide duration samples -------------------------------------------
# Like the counters (and unlike the phase registry) these are NOT thread-
# local: the serving engine records request latencies on its dispatch worker
# thread while stats()/tests read the percentiles from the main thread.
# Bounded per name so a long-lived server cannot grow the sample list without
# limit; past the cap new samples overwrite the oldest (ring buffer), keeping
# the percentiles a sliding window over the most recent traffic.

_DURATION_CAP = 65536
# TTL sweeps run at most once per _TTL_SWEEP_EVERY records so the eviction
# scan cost amortizes to ~zero on hot serving paths
_TTL_SWEEP_EVERY = 256

_durations_lock = threading.Lock()
_durations: Dict[str, list] = {}
_duration_next: Dict[str, int] = {}  # ring-buffer write cursor past the cap
# lifetime [count, sum, min, max] per series: unlike the capped ring these
# are MONOTONIC (evicted samples stay counted), so duration_digests deltas
# between two snapshots are exact no matter how busy the series is
_duration_stats: Dict[str, list] = {}
# last-touch clock per series (only maintained while SRML_METRIC_TTL_S > 0)
_duration_touched: Dict[str, float] = {}
_ttl_record_count = 0


def metric_ttl_s() -> float:
    """SRML_METRIC_TTL_S: seconds a duration series may go untouched before
    eviction (0, the default, disables eviction).  The per-series sample
    ring is bounded, but the NUMBER of series is not — a long-lived serving
    process cycling through model names would otherwise leak series."""
    try:
        return float(os.environ.get(METRIC_TTL_ENV, "") or 0.0)
    except ValueError:
        return 0.0


def _evict_stale_series_locked(ttl: float, now_t: float, keep: str) -> None:
    """Drop every series untouched for `ttl` seconds (except `keep`, the
    series being written).  A series recorded before TTL was enabled has no
    touch stamp — it is stamped now and given a full TTL."""
    for k in list(_durations):
        if k == keep:
            continue
        touched = _duration_touched.get(k)
        if touched is None:
            _duration_touched[k] = now_t
        elif now_t - touched > ttl:
            del _durations[k]
            _duration_next.pop(k, None)
            _duration_stats.pop(k, None)
            _duration_touched.pop(k, None)


def record_duration(name: str, seconds: float) -> None:
    """Append one duration sample (seconds) to the process-wide series
    `name`.  Cheap enough for per-request recording; capped per name (ring
    buffer) so recording is observability, never a leak.  With
    SRML_METRIC_TTL_S set, series untouched for the TTL are evicted here
    (amortized: one sweep per _TTL_SWEEP_EVERY records)."""
    global _ttl_record_count
    s = float(seconds)
    ttl = metric_ttl_s()  # env read outside the lock: the hot serving path
    # records several series per batch and must not serialize on it
    with _durations_lock:
        series = _durations.get(name)
        if series is None:
            series = []
            _durations[name] = series
        if len(series) < _DURATION_CAP:
            series.append(s)
        else:
            cur = _duration_next.get(name, 0)
            series[cur] = s
            _duration_next[name] = (cur + 1) % _DURATION_CAP
        stats = _duration_stats.get(name)
        if stats is None:
            _duration_stats[name] = [1, s, s, s]
        else:
            stats[0] += 1
            stats[1] += s
            if s < stats[2]:
                stats[2] = s
            if s > stats[3]:
                stats[3] = s
        if ttl > 0:
            now_t = time.perf_counter()
            _duration_touched[name] = now_t
            _ttl_record_count += 1
            if _ttl_record_count % _TTL_SWEEP_EVERY == 0:
                _evict_stale_series_locked(ttl, now_t, keep=name)


def series_stats() -> Dict[str, Any]:
    """Self-description of the duration registry — series count, total ring
    samples, estimated resident bytes, and per-series lifetime counts +
    last-touch age — so a long-lived serving process can watch its own
    metric footprint (the leak this surface exists to catch)."""
    now_t = time.perf_counter()
    with _durations_lock:
        per = {}
        total_samples = 0
        for k, v in _durations.items():
            total_samples += len(v)
            stats = _duration_stats.get(k) or [len(v), 0.0, 0.0, 0.0]
            touched = _duration_touched.get(k)
            per[k] = {
                "ring_samples": len(v),
                "lifetime_count": int(stats[0]),
                "age_s": (
                    round(now_t - touched, 3) if touched is not None else None
                ),
            }
        return {
            "series_count": len(per),
            "ring_samples": total_samples,
            "est_bytes": total_samples * 8,
            "ttl_s": metric_ttl_s(),
            "series": per,
        }


def durations(prefix: str = "") -> Dict[str, list]:
    """Copy of every duration series whose name starts with `prefix`."""
    with _durations_lock:
        return {k: list(v) for k, v in _durations.items() if k.startswith(prefix)}


def reset_durations(prefix: str = "") -> None:
    with _durations_lock:
        for k in [k for k in _durations if k.startswith(prefix)]:
            del _durations[k]
            _duration_next.pop(k, None)
            _duration_stats.pop(k, None)
            _duration_touched.pop(k, None)


def percentiles(prefix: str = "") -> Dict[str, float]:
    """p50/p95/p99 (plus count/mean/max) over every duration sample recorded
    under names starting with `prefix`, merged into ONE distribution — pass
    an exact series name for a single series, or a subsystem prefix (e.g.
    "serve.kmeans.") for its whole latency surface.  Returns {} when nothing
    was recorded.  Linear interpolation between order statistics, the numpy
    default, so tiny test samples get deterministic values."""
    merged: list = []
    with _durations_lock:
        for k, v in _durations.items():
            if k.startswith(prefix):
                merged.extend(v)
    return _percentile_digest(merged)


def _percentile_digest(samples: list) -> Dict[str, float]:
    if not samples:
        return {}
    import numpy as np

    arr = np.asarray(samples, dtype=np.float64)
    p50, p95, p99 = np.percentile(arr, [50.0, 95.0, 99.0])
    return {
        "count": int(arr.size),
        "mean": float(arr.mean()),
        "p50": float(p50),
        "p95": float(p95),
        "p99": float(p99),
        "max": float(arr.max()),
    }


def duration_digests(prefix: str = "") -> Dict[str, Dict[str, float]]:
    """Mergeable per-series digests {name: {count, sum_s, min_s, max_s}} —
    the duration form a TelemetrySnapshot carries: unlike percentiles these
    merge associatively across executors, so a driver-side rollup is exact
    regardless of merge order.  Built from LIFETIME running totals, not the
    capped sample ring, so count/sum stay monotonic past the ring's
    eviction point and snapshot deltas (registry.telemetry(since=...)) are
    exact on arbitrarily busy series (percentiles over the raw ring remain
    a most-recent-traffic view; see docs/observability.md)."""
    out: Dict[str, Dict[str, float]] = {}
    with _durations_lock:
        for k, s in _duration_stats.items():
            if k.startswith(prefix):
                out[k] = {
                    "count": s[0],
                    "sum_s": s[1],
                    "min_s": s[2],
                    "max_s": s[3],
                }
    return out


# -- per-thread ordered event log --------------------------------------------
# Bounded so a long-lived process that never drains the log cannot grow it
# without limit; the cap is far above any one search's dispatch/collect count.

_EVENT_CAP = 4096


def _event_log() -> list:
    log = getattr(_tls, "events", None)
    if log is None:
        log = []
        _tls.events = log
    return log


def record_event(name: str, **meta: Any) -> None:
    """Append (name, meta) to this thread's ordered event log (dropped
    silently past the cap — the log is observability, never control flow)."""
    log = _event_log()
    if len(log) < _EVENT_CAP:
        log.append((name, meta))


def events(prefix: str = "") -> list:
    """This thread's events in record order, optionally prefix-filtered."""
    return [(n, m) for n, m in _event_log() if n.startswith(prefix)]


def reset_events() -> None:
    _event_log().clear()


# -- hierarchical spans -------------------------------------------------------
# A span is the phase timer grown a parent: while a trace session is active,
# every completed span appends ONE record (name, t0, t1, thread, span id,
# parent id, attrs) to a process-wide bounded buffer under a lock.  The
# per-thread parent stack exists only while collecting, so the disabled path
# is byte-for-byte the old flat timer: TraceAnnotation + two thread-local
# dict updates, no allocation, no lock (asserted by the zero-overhead guard
# in tests/test_profiling.py).

_TRACE_CAP = 131072

_trace_lock = threading.Lock()
_trace_records: List[tuple] = []
_collect_depth = 0  # active trace sessions / collection scopes
_span_ids = itertools.count(1)
_session_seq = itertools.count(1)


class _SpanHandle:
    """Yielded by span(): set(**kv) attaches counters (bytes=, rows=...) to
    the span record mid-flight.  The module-level null handle is what the
    disabled path yields — set() is a no-op there, so call sites never
    branch on whether tracing is on."""

    __slots__ = ("attrs",)

    def __init__(self, attrs: Optional[Dict[str, Any]]):
        self.attrs = attrs

    def set(self, **kv: Any) -> None:
        if self.attrs is not None:
            self.attrs.update(kv)


_NULL_SPAN = _SpanHandle(None)


def _span_stack() -> list:
    stack = getattr(_tls, "span_stack", None)
    if stack is None:
        stack = []
        _tls.span_stack = stack
    return stack


@contextlib.contextmanager
def span(name: str, **attrs: Any) -> Iterator[_SpanHandle]:
    """Named range: xprof TraceAnnotation + wall-clock accounting + (while a
    trace session is active) one hierarchical span record.

    The TraceAnnotation shows up in a tensorboard/xprof capture exactly
    where NVTX ranges show up in nsys for the reference's Scala path; the
    span record is what the Chrome-trace export and TelemetrySnapshot
    rollups are built from.  `attrs` become the trace event's args
    (bytes=, rows=, block=...); they are ignored — never allocated — when
    no session is collecting."""
    try:
        import jax.profiler

        annotation: contextlib.AbstractContextManager = jax.profiler.TraceAnnotation(
            name
        )
    except Exception:  # pragma: no cover - profiler always importable with jax
        annotation = contextlib.nullcontext()
    collecting = _collect_depth > 0
    if collecting:
        sid = next(_span_ids)
        stack = _span_stack()
        parent = stack[-1] if stack else 0
        stack.append(sid)
        handle = _SpanHandle(dict(attrs))
    else:
        handle = _NULL_SPAN
    # flight recorder (srml-watch): ALWAYS on when installed — one bounded
    # ring event per span close plus the open-span stack a hang dump and
    # the stall watchdog read.  Overhead is gated <2% of a warm fit by
    # tests/test_watch.py.
    fr = _flight
    if fr is not None:
        fr.on_span_open(name)
    t0 = time.perf_counter()
    try:
        with annotation:
            yield handle
    finally:
        t1 = time.perf_counter()
        dt = t1 - t0
        reg = _registry()
        reg[name] = reg.get(name, 0.0) + dt
        cnt = _count_registry()
        cnt[name] = cnt.get(name, 0) + 1
        if collecting:
            stack.pop()
            th = threading.current_thread()
            with _trace_lock:
                if len(_trace_records) < _TRACE_CAP:
                    _trace_records.append(
                        (name, t0, t1, th.ident, th.name, sid, parent,
                         handle.attrs)
                    )
        if fr is not None:
            fr.on_span_close(name, t0, t1, sys.exc_info()[0] is not None)
        _log.debug("phase %s: %.3fs", name, dt)


# API-compatible shim: every existing phase site is a span site
phase = span


def span_records() -> List[tuple]:
    """Copy of the collected span records (name, t0, t1, thread_ident,
    thread_name, span_id, parent_id, attrs) — test/introspection surface."""
    with _trace_lock:
        return list(_trace_records)


@contextlib.contextmanager
def collect_spans() -> Iterator[None]:
    """Enable span-record collection for the enclosing scope WITHOUT writing
    a trace file (trace_session composes this with the Chrome-trace writer;
    tests use it directly).  Reentrant; the shared buffer clears when the
    last scope exits."""
    global _collect_depth
    with _trace_lock:
        _collect_depth += 1
    try:
        yield
    finally:
        with _trace_lock:
            _collect_depth -= 1
            if _collect_depth == 0:
                _trace_records.clear()


def _safe_tag(tag: str) -> str:
    return "".join(c if (c.isalnum() or c in "-_.") else "-" for c in tag)


def _write_chrome_trace(path: str, records: List[tuple]) -> None:
    """Write span records as Chrome trace-event JSON (the `traceEvents`
    array format Perfetto and chrome://tracing load): one complete ("X")
    event per span with microsecond ts/dur relative to the process epoch,
    plus thread_name metadata events so worker threads are labeled."""
    pid = os.getpid()
    tid_of: Dict[int, int] = {}
    names: Dict[int, str] = {}
    events_out: List[Dict[str, Any]] = []
    for name, t0, t1, ident, tname, sid, parent, attrs in records:
        tid = tid_of.setdefault(ident, len(tid_of) + 1)
        names.setdefault(tid, tname)
        args: Dict[str, Any] = {"span_id": sid}
        if parent:
            args["parent_id"] = parent
        if attrs:
            args.update(attrs)
        events_out.append(
            {
                "name": name,
                "cat": "srml",
                "ph": "X",
                "ts": (t0 - _EPOCH) * 1e6,
                "dur": (t1 - t0) * 1e6,
                "pid": pid,
                "tid": tid,
                "args": args,
            }
        )
    meta = [
        {
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": tid,
            "args": {"name": tname},
        }
        for tid, tname in sorted(names.items())
    ]
    doc = {"traceEvents": meta + events_out, "displayTimeUnit": "ms"}
    tmp = f"{path}.tmp{pid}"
    try:
        with open(tmp, "w") as f:
            # default=str: span attrs are an open kwargs surface (numpy
            # scalars, dtypes, ...) and a non-JSON attr must degrade to its
            # repr, never fail the export
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise


@contextlib.contextmanager
def trace_session(tag: str = "session") -> Iterator[Optional[str]]:
    """Collect spans for the enclosed region and write them as ONE Chrome
    trace-event JSON file under $SRML_TRACE_DIR (yielding the target path).
    No-op — zero overhead, yields None — when the env var is unset.  Opened
    automatically around every top-level fit (core / parallel runner),
    kneighbors search, and serving engine lifetime; overlapping sessions
    each export their own window of the shared buffer."""
    out_dir = os.environ.get(TRACE_ENV)
    if not out_dir:
        yield None
        return
    try:
        os.makedirs(out_dir, exist_ok=True)
    except OSError as exc:
        # a bad observability env var must never fail the fit/search/server
        # it wraps — degrade to the disabled path with one warning
        _log.warning(
            "%s=%r is not writable (%s); tracing disabled for %r",
            TRACE_ENV, out_dir, exc, tag,
        )
        yield None
        return
    path = os.path.join(
        out_dir,
        f"{_safe_tag(tag)}-{os.getpid()}-{next(_session_seq):04d}.trace.json",
    )
    global _collect_depth
    with _trace_lock:
        _collect_depth += 1
    t_start = time.perf_counter()
    try:
        yield path
    finally:
        with _trace_lock:
            records = [r for r in _trace_records if r[1] >= t_start]
            _collect_depth -= 1
            if _collect_depth == 0:
                _trace_records.clear()
        try:
            _write_chrome_trace(path, records)
            _log.info(
                "srml-scope trace for %r: %d span(s) -> %s",
                tag, len(records), path,
            )
        except Exception as exc:  # disk-full, serialization drift, ...
            # the export is best-effort by design: it runs in a finally
            # around successful fits/searches and must never replace their
            # result with a telemetry crash
            _log.warning("trace export for %r failed: %s", tag, exc)


# -- mergeable telemetry snapshots -------------------------------------------


class TelemetrySnapshot:
    """Serializable rollup of one session's observability: span/phase stats,
    counter deltas, and duration digests.

    Merge rules are associative AND commutative (sums, mins, maxes — the
    same algebra as metrics/binary.py partials), so executor-side snapshots
    captured at fit-task exit can cross the Spark wire as JSON and merge on
    the driver in any order: merge(a, b) == merge(b, a) and
    merge(merge(a, b), c) == merge(a, merge(b, c)) on every rollup field."""

    __slots__ = ("phases", "counters", "durations", "memory", "meta")

    def __init__(
        self,
        phases: Optional[Dict[str, Dict[str, float]]] = None,
        counters: Optional[Dict[str, int]] = None,
        durations: Optional[Dict[str, Dict[str, float]]] = None,
        memory: Optional[Dict[str, Dict[str, float]]] = None,
        meta: Optional[Dict[str, Any]] = None,
    ):
        self.phases = dict(phases or {})
        self.counters = dict(counters or {})
        self.durations = dict(durations or {})
        self.memory = dict(memory or {})
        self.meta = dict(meta or {})
        self.meta.setdefault("ranks", [])

    @classmethod
    def capture(
        cls,
        counters_before: Optional[Dict[str, int]] = None,
        counter_prefix: str = "",
        duration_prefix: Optional[str] = None,
        rank: Optional[int] = None,
    ) -> "TelemetrySnapshot":
        """Snapshot THIS thread's phase stats plus the process counters
        (delta vs `counters_before` when given, so a fit reports what IT
        moved, not process history), optionally duration digests under
        `duration_prefix`, and — when the srml-watch recorder is installed —
        the memory section (per-phase peak-delta attribution + HBM/host
        watermarks; empty on backends without device memory stats)."""
        ctr = (
            counter_deltas(counters_before, counter_prefix)
            if counters_before is not None
            else counters(counter_prefix)
        )
        dur = (
            duration_digests(duration_prefix)
            if duration_prefix is not None
            else {}
        )
        mem: Dict[str, Dict[str, float]] = {}
        fr = _flight
        if fr is not None:
            try:
                mem = fr.telemetry_memory()
            except Exception:  # noqa: BLE001 - observability never fails fits
                mem = {}
        meta: Dict[str, Any] = {"ranks": [int(rank)] if rank is not None else []}
        return cls(
            phases=phase_stats(), counters=ctr, durations=dur, memory=mem,
            meta=meta,
        )

    def merge(self, other: "TelemetrySnapshot") -> "TelemetrySnapshot":
        phases: Dict[str, Dict[str, float]] = {}
        for src in (self.phases, other.phases):
            for k, v in src.items():
                agg = phases.setdefault(k, {"count": 0, "total_s": 0.0})
                agg["count"] += int(v.get("count", 0))
                agg["total_s"] += float(v.get("total_s", 0.0))
        ctr: Dict[str, int] = dict(self.counters)
        for k, v in other.counters.items():
            ctr[k] = ctr.get(k, 0) + v
        dur: Dict[str, Dict[str, float]] = {}
        for src in (self.durations, other.durations):
            for k, v in src.items():
                agg = dur.get(k)
                if agg is None:
                    dur[k] = dict(v)
                else:
                    agg["count"] += v["count"]
                    agg["sum_s"] += v["sum_s"]
                    agg["min_s"] = min(agg["min_s"], v["min_s"])
                    agg["max_s"] = max(agg["max_s"], v["max_s"])
        # memory watermarks: counts sum, peaks MAX (a watermark across ranks
        # is the worst rank's), deltas sum — still associative+commutative
        mem: Dict[str, Dict[str, float]] = {}
        for src in (self.memory, other.memory):
            for k, v in src.items():
                agg = mem.get(k)
                if agg is None:
                    mem[k] = dict(v)
                else:
                    agg["count"] += v.get("count", 0)
                    agg["peak_bytes"] = max(
                        agg.get("peak_bytes", 0.0), v.get("peak_bytes", 0.0)
                    )
                    agg["sum_delta_bytes"] = agg.get(
                        "sum_delta_bytes", 0.0
                    ) + v.get("sum_delta_bytes", 0.0)
        meta = {
            "ranks": sorted(
                set(self.meta.get("ranks", [])) | set(other.meta.get("ranks", []))
            )
        }
        return TelemetrySnapshot(
            phases=phases, counters=ctr, durations=dur, memory=mem, meta=meta
        )

    def delta(self, since: "TelemetrySnapshot") -> "TelemetrySnapshot":
        """What moved between `since` and this snapshot: counter
        differences (zero-change keys dropped) and count/sum duration
        deltas.  min/max cannot be un-merged, so the window keeps the
        current extremes (documented in docs/observability.md).  The ONE
        delta rule behind every scrape-loop surface
        (ModelRegistry.telemetry(since=), Router.telemetry(since=))."""
        ctr = {
            k: v - since.counters.get(k, 0)
            for k, v in self.counters.items()
            if v != since.counters.get(k, 0)
        }
        dur: Dict[str, Dict[str, float]] = {}
        for k, d in self.durations.items():
            prev = since.durations.get(k)
            if prev is None:
                dur[k] = dict(d)
                continue
            dc = d["count"] - prev["count"]
            if dc > 0:
                dur[k] = {
                    "count": dc,
                    "sum_s": d["sum_s"] - prev["sum_s"],
                    "min_s": d["min_s"],
                    "max_s": d["max_s"],
                }
        return TelemetrySnapshot(counters=ctr, durations=dur)

    def phase_seconds(self, prefix: str = "") -> Dict[str, float]:
        """{phase name: total seconds} — the phase_times() view of a merged
        snapshot (what the driver prints for a live-Spark fit)."""
        return {
            k: float(v.get("total_s", 0.0))
            for k, v in self.phases.items()
            if k.startswith(prefix)
        }

    def to_dict(self) -> Dict[str, Any]:
        return {
            "schema": "srml-scope/v1",
            "phases": self.phases,
            "counters": self.counters,
            "durations": self.durations,
            "memory": self.memory,
            "meta": self.meta,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TelemetrySnapshot":
        return cls(
            phases=d.get("phases"),
            counters=d.get("counters"),
            durations=d.get("durations"),
            memory=d.get("memory"),
            meta=d.get("meta"),
        )

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, TelemetrySnapshot)
            and self.to_dict() == other.to_dict()
        )

    def __repr__(self) -> str:
        return (
            f"TelemetrySnapshot(phases={len(self.phases)}, "
            f"counters={len(self.counters)}, durations={len(self.durations)}, "
            f"ranks={self.meta.get('ranks', [])})"
        )


# -- export surface -----------------------------------------------------------

# Gauge providers: named callables returning {gauge name: float} sampled at
# export time (unlike counters, gauges describe CURRENT state — memory
# watermarks, serving health, cache sizes).  srml-watch registers the
# memory/cache provider; each ModelRegistry registers its health provider;
# sanitize registers lockdep.{locks,edges,violations} when armed (gauges,
# not counters, because the counter path's flight-recorder hook takes the
# watch ring lock — itself lockdep-wrapped when armed).
_gauges_lock = threading.Lock()
_gauge_providers: Dict[str, Callable[[], Dict[str, float]]] = {}


def register_gauges(key: str, fn: Callable[[], Dict[str, float]]) -> None:
    """Register (or replace) gauge provider `key`; its dict is merged into
    export_metrics()['gauges'] at every export."""
    with _gauges_lock:
        _gauge_providers[key] = fn


def unregister_gauges(key: str) -> None:
    with _gauges_lock:
        _gauge_providers.pop(key, None)


def collect_gauges(prefix: str = "") -> Dict[str, float]:
    """Sample every registered gauge provider (best-effort: a provider that
    raises is skipped — export must never fail on a sick subsystem, that is
    exactly when it is needed)."""
    with _gauges_lock:
        providers = list(_gauge_providers.values())
    out: Dict[str, float] = {}
    for fn in providers:
        try:
            sampled = fn()
        except Exception:  # noqa: BLE001 - export over failure
            continue
        for k, v in sampled.items():
            if k.startswith(prefix):
                try:
                    out[k] = float(v)
                except (TypeError, ValueError):
                    continue
    return dict(sorted(out.items()))


def spread_attribution(
    phase_runs: List[Dict[str, float]],
    median_s: float,
    floor_pct: float = 1.0,
    top: int = 5,
) -> Dict[str, float]:
    """Attribute a multi-repeat timing spread to phases: for each phase
    name across `phase_runs` (one phase_times() dict per timed repeat),
    report max−min as % of the median run `median_s` — which phase's
    variance IS the spread.  Phases under `floor_pct` are dropped; the
    `top` largest survive, largest first.  The ONE implementation behind
    bench.py's per-arm spread_attribution and benchmark/base.py's
    cross-run aggregation (both write the same artifact keys)."""
    if len(phase_runs) < 2 or median_s <= 0:
        return {}
    names = set().union(*(set(p) for p in phase_runs))
    out = {}
    for n in names:
        vals = [float(p.get(n, 0.0)) for p in phase_runs]
        pct = 100.0 * (max(vals) - min(vals)) / median_s
        if pct >= floor_pct:
            out[n] = round(pct, 1)
    return dict(sorted(out.items(), key=lambda kv: -kv[1])[:top])


def export_metrics(prefix: str = "") -> Dict[str, Any]:
    """One stable JSON document of the process's observability state:
    counters, per-series duration percentile summaries, this thread's
    phase stats, and sampled gauges (memory watermarks, serving health,
    executable-cache size — whatever providers are registered), all
    optionally prefix-filtered.  Embedded into benchmark artifacts and
    round-trippable through json.dumps/loads (asserted by the CI
    observability gate)."""
    dur: Dict[str, Dict[str, float]] = {}
    with _durations_lock:
        series = {
            k: list(v) for k, v in _durations.items() if k.startswith(prefix)
        }
    for k, v in series.items():
        dur[k] = _percentile_digest(v)
    return {
        "schema": "srml-scope/v1",
        "counters": counters(prefix),
        "durations": dur,
        "phases": phase_stats(prefix),
        "gauges": collect_gauges(prefix),
    }


def _prom_escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"')


def render_prometheus(metrics: Optional[Dict[str, Any]] = None) -> str:
    """Prometheus text exposition of export_metrics(): counters as
    `srml_counter{name="..."}`, phases as seconds/count pairs, duration
    series as quantile summaries.  Names ride a label (srml counter names
    carry dots, which Prometheus metric names cannot)."""
    m = metrics if metrics is not None else export_metrics()
    lines = ["# TYPE srml_counter counter"]
    for k, v in sorted(m.get("counters", {}).items()):
        lines.append(f'srml_counter{{name="{_prom_escape(k)}"}} {v}')
    lines.append("# TYPE srml_phase_seconds_total counter")
    lines.append("# TYPE srml_phase_count_total counter")
    for k, v in sorted(m.get("phases", {}).items()):
        n = _prom_escape(k)
        lines.append(f'srml_phase_seconds_total{{name="{n}"}} {v["total_s"]}')
        lines.append(f'srml_phase_count_total{{name="{n}"}} {v["count"]}')
    lines.append("# TYPE srml_duration_seconds summary")
    for k, d in sorted(m.get("durations", {}).items()):
        if not d:
            continue
        n = _prom_escape(k)
        for q_label, q_key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            lines.append(
                f'srml_duration_seconds{{name="{n}",quantile="{q_label}"}} '
                f"{d[q_key]}"
            )
        lines.append(
            f'srml_duration_seconds_sum{{name="{n}"}} '
            f"{d['mean'] * d['count']}"
        )
        lines.append(f'srml_duration_seconds_count{{name="{n}"}} {d["count"]}')
    # gauges (srml-watch health plane) split into the families dashboards
    # alert on: memory watermarks, serving health (per server/replica),
    # router capacity (srml-router), and the rest
    gauges = m.get("gauges", {})
    if gauges:
        fams = {
            "srml_memory_bytes": [],
            "srml_health": [],
            "srml_router": [],
            "srml_elastic": [],
            "srml_gauge": [],
        }
        # exchange link pressure gets its own family with a `link` label
        # (ici|dcn) — the dashboard dimension is the physical link class,
        # not the dotted counter name
        link_entries = []
        for k, v in sorted(gauges.items()):
            if k.startswith("exchange.link."):
                link = k[len("exchange.link."):].removesuffix("_bytes")
                link_entries.append((link, v))
            elif k.startswith("mem."):
                fams["srml_memory_bytes"].append((k, v))
            elif k.startswith("health."):
                fams["srml_health"].append((k, v))
            elif k.startswith("router."):
                fams["srml_router"].append((k, v))
            elif k.startswith(("slicepool.", "autoscale.")):
                # srml-elastic capacity plane: pool ledger + policy loop
                fams["srml_elastic"].append((k, v))
            else:
                fams["srml_gauge"].append((k, v))
        if link_entries:
            lines.append("# TYPE srml_exchange_bytes gauge")
            for link, v in link_entries:
                lines.append(
                    f'srml_exchange_bytes{{link="{_prom_escape(link)}"}} {v}'
                )
        for fam, entries in fams.items():
            if not entries:
                continue
            lines.append(f"# TYPE {fam} gauge")
            for k, v in entries:
                lines.append(f'{fam}{{name="{_prom_escape(k)}"}} {v}')
    return "\n".join(lines) + "\n"


# -- xprof capture / benchmark helpers ----------------------------------------


@contextlib.contextmanager
def maybe_trace(tag: str = "fit") -> Iterator[None]:
    """If SRML_PROFILE=<dir> is set, capture an xprof trace of the enclosed
    region into <dir>/<tag>.  No-op (zero overhead) otherwise."""
    out_dir = os.environ.get(PROFILE_ENV)
    if not out_dir:
        yield
        return
    import jax.profiler

    target = os.path.join(out_dir, tag)
    os.makedirs(target, exist_ok=True)
    with jax.profiler.trace(target):
        yield
    _log.info("xprof trace for %r written to %s", tag, target)


def with_benchmark(name: str, fn: Callable[[], Any]) -> Tuple[Any, float]:
    """Run fn, returning (result, elapsed_seconds) and logging the timing —
    the reference's benchmark/utils.py:42-50 helper."""
    t0 = time.perf_counter()
    result = fn()
    dt = time.perf_counter() - t0
    _log.info("-" * 100)
    _log.info("%s took: %s sec", name, dt)
    return result, dt


def device_step_annotation(step: int) -> contextlib.AbstractContextManager:
    """StepTraceAnnotation for iteration-granular traces (opt-in use in
    benchmark loops)."""
    try:
        import jax.profiler

        return jax.profiler.StepTraceAnnotation("step", step_num=step)
    except Exception:  # pragma: no cover
        return contextlib.nullcontext()


# -- srml-watch bootstrap ------------------------------------------------------
# The flight recorder is ALWAYS on (SRML_WATCH=0 opts out): installed here,
# at the bottom of the module, so watch's own `from . import profiling` sees
# a fully-initialized namespace.  watch.install() sets _flight and registers
# the memory/cache gauge provider.

def _bootstrap_watch() -> None:
    if os.environ.get("SRML_WATCH", "1") == "0":
        return
    try:
        from . import watch

        watch.install()
    except Exception as exc:  # pragma: no cover - never fail the import
        _log.warning("srml-watch flight recorder unavailable: %s", exc)


_bootstrap_watch()
