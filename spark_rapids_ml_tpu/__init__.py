#
# spark_rapids_ml_tpu: a TPU-native distributed classical-ML framework with
# the capabilities of NVIDIA's spark-rapids-ml (reference mounted at
# /root/reference), rebuilt on jax/XLA/pjit: estimators dispatch to jax.jit'd
# solvers sharded over a device Mesh instead of cuML MG kernels over NCCL.
#
from .version import __version__

__all__ = [
    "__version__",
    "KMeans",
    "KMeansModel",
    "PCA",
    "PCAModel",
    "LinearRegression",
    "LinearRegressionModel",
    "LogisticRegression",
    "LogisticRegressionModel",
    "RandomForestClassifier",
    "RandomForestClassificationModel",
    "RandomForestRegressor",
    "RandomForestRegressionModel",
    "NearestNeighbors",
    "NearestNeighborsModel",
    "ApproximateNearestNeighbors",
    "ApproximateNearestNeighborsModel",
    "UMAP",
    "UMAPModel",
    "CrossValidator",
    "Pipeline",
    "PipelineModel",
    "StreamingSession",
    "streaming_fit",
]


def __getattr__(name):  # lazy re-exports keep `import spark_rapids_ml_tpu` light
    from importlib import import_module

    _locations = {
        "KMeans": ".models.kmeans",
        "KMeansModel": ".models.kmeans",
        "PCA": ".models.pca",
        "PCAModel": ".models.pca",
        "LinearRegression": ".models.linear_regression",
        "LinearRegressionModel": ".models.linear_regression",
        "LogisticRegression": ".models.logistic_regression",
        "LogisticRegressionModel": ".models.logistic_regression",
        "RandomForestClassifier": ".models.random_forest",
        "RandomForestClassificationModel": ".models.random_forest",
        "RandomForestRegressor": ".models.random_forest",
        "RandomForestRegressionModel": ".models.random_forest",
        "NearestNeighbors": ".models.knn",
        "NearestNeighborsModel": ".models.knn",
        "ApproximateNearestNeighbors": ".models.approximate_nn",
        "ApproximateNearestNeighborsModel": ".models.approximate_nn",
        "UMAP": ".models.umap",
        "UMAPModel": ".models.umap",
        "CrossValidator": ".tuning",
        "Pipeline": ".pipeline",
        "PipelineModel": ".pipeline",
        "StreamingSession": ".stream",
        "streaming_fit": ".stream",
    }
    if name in _locations:
        try:
            return getattr(import_module(_locations[name], __name__), name)
        except ModuleNotFoundError as e:
            raise AttributeError(
                f"module {__name__!r} has no attribute {name!r} ({e})"
            ) from e
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():  # surface the lazy re-exports to dir()/completion
    return sorted(set(globals()) | set(__all__))
