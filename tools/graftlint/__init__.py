#
# graftlint: AST-level JAX/TPU invariant checks for this codebase.
#
# The reference stack (cuML/NCCL) fails loudly when a worker misuses the
# device; the jax/pjit rebuild fails silently — a stray np.asarray on a
# device array becomes a hidden device->host sync, a Python-scalar jit arg
# becomes a recompile stream, an axis-name typo explodes only at trace time
# on a real mesh.  graftlint moves those failures to review time.  Rules:
#
#   R1 host-sync     np.asarray/.item()/float()/np reductions on values that
#                    dataflow from jnp/jax.lax/jitted calls, inside loops or
#                    jitted bodies; jax.device_get inside loops.
#   R2 recompile     jit-wrapped callables taking shape/config-named params
#                    without static_argnums/static_argnames; Python if/while
#                    on non-static params inside a jitted body.
#   R3 axis-name     lax collectives / PartitionSpec / Mesh axis names given
#                    as string literals instead of names bound through
#                    parallel/mesh (DATA_AXIS/MODEL_AXIS).
#   R4 nondeterminism  legacy np.random global-state calls; unseeded
#                    default_rng(); any RNG call at module scope; iteration
#                    over set values (order feeds collectives/encodings).
#   R5 dtype         float64 dtypes in ops/ solver kernels (TPU demotes f64
#                    to slow emulation; numpy f64 scalars also silently
#                    promote weak-typed jnp math).
#   R6 raw-clock     time.time/time.perf_counter in spark_rapids_ml_tpu
#                    modules outside profiling.py — all timing goes through
#                    srml-scope (profiling.now()/span()) so spans, counters,
#                    and trace exports share one clock.
#   R7 unnamed-thread  threading.Thread/Timer without name= in
#                    spark_rapids_ml_tpu modules — the srml-watch flight
#                    recorder, trace exports, and watchdog reports attribute
#                    events by thread name; "Thread-N" is useless in a hang
#                    dump.
#   R8 remote-dma    pltpu.make_async_remote_copy outside parallel/
#                    exchange.py (the ONE audited home of the inter-chip
#                    DMA surface), and DMA handles .start()ed without a
#                    matching .wait() in the same kernel body — an
#                    unwaited remote copy races the output block's flush
#                    and can wedge the device in FAILED_PRECONDITION.
#   R9 unbounded-wait  .result()/.wait()/.acquire()/.join() with no
#                    timeout, and `except Exception:` bodies with no call
#                    and no raise (silent teardown swallows), in
#                    spark_rapids_ml_tpu/{parallel,serving}/ — the modules
#                    that wait on other processes/threads, where a dead
#                    peer turns an unbounded wait into the srml-shield
#                    motivating failure mode ("hang for 5 minutes, then
#                    die without naming the culprit").
#   R10 raw-socket   socket.socket/create_connection outside parallel/
#                    netplane.py (the ONE audited home of the wire
#                    surface — anywhere else is un-lease-fenced and
#                    un-fault-injectable), and recv/accept inside
#                    netplane without a preceding settimeout in the same
#                    function body (the socket analog of R9).
#   R11 lock-order   whole-program concurrency pass (concurrency.py):
#                    cycles in the package-wide held->acquired lock graph
#                    (lock-order inversions, incl. interprocedural edges
#                    through same-module calls), and blocking operations
#                    performed while a lock is held (socket waits,
#                    Future.result, foreign Condition.wait, compile
#                    waits, device syncs, subprocess/sleep).
#   R12 shared-state instance attributes written both under a lock and
#                    with no lock held, and in-place container mutation
#                    of lock-free attributes, in the thread-spawning
#                    modules (serving/, parallel/, ann/mutable.py,
#                    stream/session.py, watch.py).
#
# Suppression: `# graftlint: disable=R1 (reason)` on the finding line or the
# line directly above.  Granted pragmas are audited in NOTES.md.
#
# The runtime counterpart (SRML_SANITIZE=1 transfer guard + NaN checks) lives
# in spark_rapids_ml_tpu/sanitize.py; docs/graftlint.md documents both.
#

from __future__ import annotations

import hashlib
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple, Union

from .concurrency import ParsedModule, lint_concurrency
from .rules import CONCURRENCY_RULES, RULES, ModuleIndex, lint_tree

__all__ = [
    "Finding",
    "lint_source",
    "lint_paths",
    "load_baseline",
    "write_baseline",
    "apply_baseline",
    "assign_ids",
    "RULE_NAMES",
]

RULE_NAMES = {
    "R1": "host-sync",
    "R2": "recompile",
    "R3": "axis-name",
    "R4": "nondeterminism",
    "R5": "dtype",
    "R6": "raw-clock",
    "R7": "unnamed-thread",
    "R8": "remote-dma",
    "R9": "unbounded-wait",
    "R10": "raw-socket",
    "R11": "lock-order",
    "R12": "shared-state",
}

# Findings sanctioned by construction, not by pragma.  Entries are
# "<path-suffix>" (whole file) or "<path-suffix>::<function>".  Keep this
# list SHORT — the point of the dedup work was shrinking it to single sites.
ALLOWLIST: Dict[str, Tuple[str, ...]] = {
    # the ONE sanctioned np.asarray(block.toarray()) ingest materialization
    # (dense/sparse pandas blocks are host data; the dataflow pass would not
    # taint them, but the entry documents the contract and guards a future
    # device-backed block type)
    "R1": ("spark_rapids_ml_tpu/utils.py::materialize_feature_block",),
    # the axis-name binding site itself: DATA_AXIS/MODEL_AXIS are DEFINED
    # here, so its own Mesh/PartitionSpec construction uses the literals
    "R3": ("spark_rapids_ml_tpu/parallel/mesh.py",),
}

_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+?)(?:\s*\(([^)]*)\))?\s*$"
)


@dataclass(frozen=True)
class Finding:
    rule: str  # "R1".."R5"
    path: str
    line: int
    message: str
    func: str = ""  # enclosing function qualname ("" at module scope)

    @property
    def name(self) -> str:
        return RULE_NAMES[self.rule]

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        return f"{where}: {self.rule}[{self.name}] {self.message}"


def _pragma_rules(line_text: str) -> Optional[set]:
    m = _PRAGMA_RE.search(line_text)
    if not m:
        return None
    return {r.strip() for r in m.group(1).split(",") if r.strip()}


def collect_pragmas(source: str) -> Dict[int, set]:
    """Line number -> set of disabled rules ('all' disables every rule)."""
    out: Dict[int, set] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        rules = _pragma_rules(text)
        if rules:
            out[i] = rules
    return out


def _suppressed(f: Finding, pragmas: Dict[int, set]) -> bool:
    for line in (f.line, f.line - 1):
        rules = pragmas.get(line)
        if rules and (f.rule in rules or "all" in rules):
            return True
    return False


def _allowlisted(f: Finding) -> bool:
    for entry in ALLOWLIST.get(f.rule, ()):
        if "::" in entry:
            suffix, func = entry.split("::", 1)
            if f.path.endswith(suffix) and f.func == func:
                return True
        elif f.path.endswith(entry):
            return True
    return False


def _parse_module(source: str, path: str) -> ParsedModule:
    import ast

    tree = ast.parse(source, filename=path)
    return ParsedModule(path=path, tree=tree, index=ModuleIndex(tree, path))


def _per_module_findings(
    pm: ParsedModule, selected: Set[str]
) -> List[Finding]:
    return [
        Finding(rule=r, path=pm.path, line=line, message=msg, func=func)
        for (r, line, msg, func) in lint_tree(pm.tree, pm.index, selected)
    ]


def _concurrency_findings(
    parsed: List[ParsedModule], selected: Set[str]
) -> List[Finding]:
    if not (selected & set(CONCURRENCY_RULES)):
        return []
    return [
        Finding(rule=r, path=path, line=line, message=msg, func=func)
        for (r, path, line, msg, func) in lint_concurrency(parsed, selected)
    ]


def lint_source(
    source: str, path: str = "<string>", rules: Optional[Iterable[str]] = None
) -> List[Finding]:
    """Lint one module's source; returns unsuppressed findings sorted by
    line.  `rules` restricts to a subset (default: all).  The concurrency
    pass (R11/R12) runs over the single module — interprocedural edges
    stay within it, exactly as in a whole-package run."""
    pm = _parse_module(source, path)
    selected = set(rules) if rules is not None else set(RULES)
    raw = _per_module_findings(pm, selected)
    raw.extend(_concurrency_findings([pm], selected))
    pragmas = collect_pragmas(source)
    return sorted(
        (f for f in raw if not _suppressed(f, pragmas) and not _allowlisted(f)),
        key=lambda f: (f.line, f.rule),
    )


def iter_python_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs if d not in ("__pycache__", ".git")
                )
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        yield os.path.join(root, fn)


def lint_paths(
    paths: Iterable[str], rules: Optional[Iterable[str]] = None
) -> List[Finding]:
    """Lint a set of files/packages as ONE program: per-module rules run
    file by file, then the concurrency pass (R11/R12) runs once over every
    parsed module so the lock graph is package-wide.  Pragmas and the
    allowlist apply to both halves."""
    selected = set(rules) if rules is not None else set(RULES)
    parsed: List[ParsedModule] = []
    pragmas_of: Dict[str, Dict[int, set]] = {}
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        norm = os.path.normpath(path)
        pm = _parse_module(source, norm)
        parsed.append(pm)
        pragmas_of[norm] = collect_pragmas(source)
        findings.extend(_per_module_findings(pm, selected))
    findings.extend(_concurrency_findings(parsed, selected))
    return sorted(
        (
            f
            for f in findings
            if not _suppressed(f, pragmas_of.get(f.path, {}))
            and not _allowlisted(f)
        ),
        key=lambda f: (f.path, f.line, f.rule),
    )


# -- stable finding ids -------------------------------------------------------
# A finding's identity is (rule, path, symbol, fingerprint-of-message) — NO
# line numbers, so a baseline survives unrelated edits that shift code up
# or down.  Identical findings in the same symbol (two copies of the same
# bad call) get an occurrence suffix in first-seen order.

def _fingerprint(f: Finding) -> str:
    h = hashlib.sha1(
        f"{f.rule}|{f.path}|{f.func}|{f.message}".encode("utf-8")
    )
    return h.hexdigest()[:10]


def assign_ids(findings: List[Finding]) -> List[Tuple[str, Finding]]:
    """[(stable id, finding)] in (path, line, rule) order."""
    ordered = sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    seen: Dict[str, int] = {}
    out: List[Tuple[str, Finding]] = []
    for f in ordered:
        base = f"{f.rule}:{f.path}::{f.func or '<module>'}@{_fingerprint(f)}"
        n = seen.get(base, 0)
        seen[base] = n + 1
        out.append((base if n == 0 else f"{base}~{n + 1}", f))
    return out


# -- baseline: ratchet the whole-package gate --------------------------------
# v2 (written by --write-baseline, consumed by --fail-on-new): a list of
# stable finding ids — audited debt.  Findings whose id is recorded demote
# to warnings; any NEW id is an error, so the gate only ever ratchets down.
# v1 (legacy): {"<path>::<rule>": count} — per-(file, rule) count budgets.

Baseline = Union[Dict[str, int], Set[str]]


def load_baseline(path: str) -> Baseline:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if isinstance(data, dict) and data.get("version") == 2:
        ids = data.get("ids")
        if not isinstance(ids, list):
            raise ValueError(f"baseline {path}: v2 needs an 'ids' list")
        return {str(i) for i in ids}
    if not isinstance(data, dict):
        raise ValueError(f"baseline {path} must be a JSON object")
    return {str(k): int(v) for k, v in data.items()}


def write_baseline(path: str, findings: List[Finding]) -> List[str]:
    ids = [i for i, _f in assign_ids(findings)]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 2, "ids": sorted(ids)}, fh, indent=2)
        fh.write("\n")
    return ids


def apply_baseline(
    findings: List[Finding], baseline: Baseline
) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (errors, warnings).  v2 baselines match by
    stable id (line-number independent); v1 baselines match per (path,
    rule) up to the recorded count."""
    errors: List[Finding] = []
    warnings: List[Finding] = []
    if isinstance(baseline, set):
        for fid, f in assign_ids(findings):
            (warnings if fid in baseline else errors).append(f)
        return errors, warnings
    budget = dict(baseline)
    for f in findings:
        k = f"{f.path}::{f.rule}"
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            warnings.append(f)
        else:
            errors.append(f)
    return errors, warnings
