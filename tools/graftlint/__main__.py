#
# CLI: python -m tools.graftlint <paths...>
#
# Exit 0 when clean (or every finding is covered by --baseline), 1 on
# findings, 2 on usage errors.  Always prints the per-rule finding count so
# CI logs show coverage even on green runs (ci/test.sh step 1).
#

from __future__ import annotations

import argparse
import sys
from typing import List

from . import (
    RULE_NAMES,
    apply_baseline,
    lint_paths,
    load_baseline,
    write_baseline,
)


def main(argv: List[str] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="JAX/TPU invariant checks (R1-R10) — see docs/graftlint.md",
    )
    parser.add_argument("paths", nargs="+", help="files or package dirs to lint")
    parser.add_argument(
        "--rules",
        default=None,
        help="comma-separated subset, e.g. R1,R3 (default: all rules)",
    )
    parser.add_argument(
        "--baseline",
        default=None,
        metavar="FILE",
        help="JSON baseline: findings up to the recorded per-(file, rule) "
        "counts are demoted to warnings, so a new rule can land warn-only "
        "before being promoted to an error",
    )
    parser.add_argument(
        "--write-baseline",
        default=None,
        metavar="FILE",
        help="write the current findings as a baseline file and exit 0",
    )
    args = parser.parse_args(argv)

    rules = None
    if args.rules:
        rules = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULE_NAMES]
        if unknown:
            parser.error(f"unknown rule(s): {', '.join(unknown)}")

    try:
        findings = lint_paths(args.paths, rules=rules)
    except (OSError, SyntaxError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        counts = write_baseline(args.write_baseline, findings)
        print(
            f"graftlint: wrote baseline of {len(findings)} finding(s) "
            f"across {len(counts)} (file, rule) key(s) to {args.write_baseline}"
        )
        return 0

    warnings: List = []
    errors = findings
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            print(f"graftlint: bad baseline: {e}", file=sys.stderr)
            return 2
        errors, warnings = apply_baseline(findings, baseline)

    for f in warnings:
        print(f"warning: {f.render()}")
    for f in errors:
        print(f.render())

    per_rule = {r: 0 for r in RULE_NAMES}
    for f in findings:
        per_rule[f.rule] += 1
    summary = "  ".join(
        f"{r}[{RULE_NAMES[r]}]={per_rule[r]}" for r in sorted(per_rule)
    )
    status = "clean" if not errors else f"{len(errors)} error finding(s)"
    baselined = f", {len(warnings)} baselined warning(s)" if warnings else ""
    print(f"graftlint: {summary}")
    print(f"graftlint: {status}{baselined}")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
