#
# CLI: python -m tools.graftlint <paths...>
#
# Exit 0 when clean (or every finding is covered by --baseline), 1 on
# findings, 2 on usage errors.  Always prints the per-rule finding count so
# CI logs show coverage even on green runs (ci/test.sh step 1).
#
# The CI gate is `--baseline ci/graftlint-baseline.json --fail-on-new`:
# findings whose stable id (rule + path + symbol + message fingerprint —
# NO line numbers, so unrelated edits don't churn it) is recorded in the
# baseline demote to warnings; any NEW finding fails the build.
#

from __future__ import annotations

import argparse
import json
import sys
from typing import List

from . import (
    RULE_NAMES,
    apply_baseline,
    assign_ids,
    lint_paths,
    load_baseline,
    write_baseline,
)


def main(argv: List[str] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="JAX/TPU invariant checks (R1-R12) — see docs/graftlint.md",
    )
    parser.add_argument("paths", nargs="+", help="files or package dirs to lint")
    parser.add_argument(
        "--rules",
        default=None,
        help="comma-separated subset, e.g. R1,R3 (default: all rules)",
    )
    parser.add_argument(
        "--baseline",
        default=None,
        metavar="FILE",
        help="JSON baseline: v2 ({version: 2, ids: [...]}) matches findings "
        "by stable id; legacy v1 ({'<path>::<rule>': count}) matches per-"
        "(file, rule) counts.  Matched findings demote to warnings",
    )
    parser.add_argument(
        "--fail-on-new",
        action="store_true",
        help="CI mode: require a v2 (id-keyed) --baseline and fail only on "
        "findings whose id is not recorded — the gate that makes every "
        "NEW finding a build error while the audited debt stays visible "
        "as warnings",
    )
    parser.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="output format; json emits {findings: [{id, rule, name, path, "
        "line, func, message, baselined}], summary: {...}}",
    )
    parser.add_argument(
        "--write-baseline",
        default=None,
        metavar="FILE",
        help="write the current findings as a v2 (id-keyed) baseline file "
        "and exit 0",
    )
    args = parser.parse_args(argv)

    rules = None
    if args.rules:
        rules = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULE_NAMES]
        if unknown:
            parser.error(f"unknown rule(s): {', '.join(unknown)}")
    if args.fail_on_new and not args.baseline:
        parser.error("--fail-on-new requires --baseline")

    try:
        findings = lint_paths(args.paths, rules=rules)
    except (OSError, SyntaxError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        ids = write_baseline(args.write_baseline, findings)
        print(
            f"graftlint: wrote baseline of {len(ids)} finding id(s) "
            f"to {args.write_baseline}"
        )
        return 0

    warnings: List = []
    errors = findings
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            print(f"graftlint: bad baseline: {e}", file=sys.stderr)
            return 2
        if args.fail_on_new and not isinstance(baseline, set):
            print(
                "graftlint: --fail-on-new needs a v2 (id-keyed) baseline; "
                "regenerate it with --write-baseline",
                file=sys.stderr,
            )
            return 2
        errors, warnings = apply_baseline(findings, baseline)

    per_rule = {r: 0 for r in RULE_NAMES}
    for f in findings:
        per_rule[f.rule] += 1

    if args.format == "json":
        warning_set = {id(w) for w in warnings}
        payload = {
            "findings": [
                {
                    "id": fid,
                    "rule": f.rule,
                    "name": f.name,
                    "path": f.path,
                    "line": f.line,
                    "func": f.func,
                    "message": f.message,
                    "baselined": id(f) in warning_set,
                }
                for fid, f in assign_ids(findings)
            ],
            "summary": {
                "per_rule": per_rule,
                "errors": len(errors),
                "warnings": len(warnings),
            },
        }
        print(json.dumps(payload, indent=2))
        return 1 if errors else 0

    for f in warnings:
        print(f"warning: {f.render()}")
    for f in errors:
        print(f.render())
    summary = "  ".join(
        f"{r}[{RULE_NAMES[r]}]={per_rule[r]}"
        for r in sorted(per_rule, key=lambda r: int(r[1:]))
    )
    status = "clean" if not errors else f"{len(errors)} error finding(s)"
    baselined = f", {len(warnings)} baselined warning(s)" if warnings else ""
    print(f"graftlint: {summary}")
    print(f"graftlint: {status}{baselined}")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
